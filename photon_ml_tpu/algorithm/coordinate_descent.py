"""Coordinate descent over GAME coordinates.

Reference spec: algorithm/CoordinateDescent.scala:37-212 — outer loop over
iterations x coordinates: subtract the coordinate's own score from the total
(partial score), update the coordinate's model on those residuals, re-score,
recompute objective = sum of losses + sum of per-coordinate regularization
terms, optionally evaluate on validation data after every update.

TPU-native: scores are dense (N,) device vectors in global row order, so the
reference's KeyValueScore join-arithmetic (KeyValueScore.scala:62-90) is
elementwise add/subtract; the persist/unpersist choreography disappears
(arrays are device-resident); each coordinate's update is one jitted call.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.evaluation.evaluators import Evaluator
from photon_ml_tpu.resilience import faults as _faults
from photon_ml_tpu.resilience import preemption as _preemption
from photon_ml_tpu.types import real_dtype

if TYPE_CHECKING:  # pragma: no cover
    from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer
    from photon_ml_tpu.resilience.guards import DivergenceGuard, GuardEvent

Array = jax.Array


@dataclasses.dataclass
class CoordinateDescentResult:
    """Final per-coordinate parameters + tracking."""

    coefficients: Dict[str, Array]  # coordinate name -> params (D,) or (E, D_loc)
    total_scores: Array  # (N,) final summed training scores
    objective_history: List[float]  # after every coordinate update
    validation_history: List[Dict[str, float]]  # per update, per evaluator
    timings: Dict[str, float]  # coordinate name -> cumulative solve seconds
    # coordinate name -> the LAST update's OptResult (vmapped solves carry a
    # leading entity axis; bucketed coordinates a tuple per bucket) — the
    # raw material of the reference's OptimizationTracker summaries
    # (RandomEffectOptimizationTracker.scala:62-95). Empty in fused-cycle
    # mode (results stay inside the compiled cycle).
    trackers: Dict[str, object] = dataclasses.field(default_factory=dict)
    # divergence-guard incidents during this run (resilience.guards): every
    # rollback / skipped cycle, with the coordinate and step it hit
    guard_events: List["GuardEvent"] = dataclasses.field(default_factory=list)


class CoordinateDescent:
    """Orchestrates coordinates in an update sequence.

    ``coordinates`` is an ordered dict name -> coordinate object exposing:
      initial_coefficients(), update(residual_offsets, init) -> (params, res),
      score(params) -> (N,), regularization_term(params) -> scalar.
    """

    def __init__(
        self,
        coordinates: Dict[str, object],
        training_loss: Callable[[Array], Array],
        validation_scorer: Optional[Callable[[Dict[str, Array]], Array]] = None,
        validation_evaluators: Optional[Dict[str, Tuple[Evaluator, dict]]] = None,
        collect_timings: bool = False,
        fused_cycle: bool = False,
        divergence_guard: Optional["DivergenceGuard"] = None,
    ):
        """``training_loss(total_scores) -> scalar`` is the loss-evaluator
        analogue used for the objective value (the training counterpart of
        cli/game/training/Driver.scala:185-202).

        ``validation_scorer(coefficients) -> (Nv,)`` maps current params to
        validation scores; each validation evaluator is (Evaluator, kwargs
        for evaluate, e.g. labels/weights arrays).

        ``collect_timings=True`` blocks on every coordinate's result so the
        per-coordinate ``timings`` are real solve seconds; the default keeps
        the whole descent async — objective/validation values stay on device
        until the end of the run, so dispatch is never serialized on a host
        round-trip per update (important over a remote device tunnel).

        ``fused_cycle=True`` compiles ONE XLA program per full descent
        iteration — every coordinate's update + rescore + objective (+
        validation metrics) unrolled into a single jitted cycle. The host
        dispatches once per iteration instead of ~4x per coordinate, which
        matters over a remote device tunnel and lets XLA overlap across
        coordinate boundaries. Trade-offs: checkpoints land at iteration
        (not per-update) granularity, and per-coordinate wall timings
        collapse into one '(fused-cycle)' entry.

        ``divergence_guard`` (resilience.guards.DivergenceGuard) gates every
        update: a non-finite parameter/score state is rolled back to the
        coordinate's last good state instead of poisoning the shared score
        vectors. The check blocks on one small scalar per update, so leave
        it None on latency-critical remote-tunnel runs unless needed.
        """
        self.coordinates = coordinates
        self.training_loss = training_loss
        self.validation_scorer = validation_scorer
        self.validation_evaluators = validation_evaluators or {}
        self.collect_timings = collect_timings
        self.fused_cycle = fused_cycle
        self.divergence_guard = divergence_guard
        self._cycle_fn = None
        self._grid_cycle_fn = None  # jitted vmap(_cycle_body), built once
        # jit the per-coordinate update+score once per coordinate, with
        # compile telemetry (photon_ml_tpu.compile.compile_stats) per site.
        # A coordinate may opt OUT (class attr cd_jit=False) when its arrays
        # span non-addressable devices under multihost SPMD — closing over
        # them in an outer jit is illegal; such coordinates jit internally
        # with the global arrays as ARGUMENTS (shard_map calls).
        #
        # Donation: the incoming coefficient state w0 is DONATED into each
        # update — the solver's output state aliases it in place, halving
        # peak HBM for the largest (E, D) stacks — EXCEPT under a
        # divergence guard, whose rollback must keep the pre-update state
        # alive (donating it would hand the guard a deleted buffer).
        from photon_ml_tpu.compile import donation_enabled, instrumented_jit

        self._donate = donation_enabled() and divergence_guard is None

        def _maybe_jit(fn, coord, site, donate=()):
            if not getattr(coord, "cd_jit", True):
                return fn
            return instrumented_jit(fn, site=site, donate_argnums=donate)

        self._update_fns = {
            name: _maybe_jit(
                lambda off, w0, c=coord: c.update(off, w0),
                coord,
                f"cd.update[{name}]",
                donate=(1,) if self._donate else (),
            )
            for name, coord in coordinates.items()
        }
        self._score_fns = {
            name: _maybe_jit(lambda w, c=coord: c.score(w), coord, f"cd.score[{name}]")
            for name, coord in coordinates.items()
        }

    # ------------------------------------------------------------------
    def _cycle_body(self, params, scores, total, lam=None):
        """THE descent cycle: one full iteration over all coordinates
        (unrolled at trace time; coordinate objects are closed over as
        static structure, arrays flow through as traced pytrees). ``lam``
        (coordinate name -> traced total reg weight) is the lambda-grid
        override; None uses each coordinate's static regularization —
        fused mode and the traced-lambda grid share this single body."""
        names = list(self.coordinates)
        objs = []
        vals = []
        for name in names:
            coord = self.coordinates[name]
            partial = total - scores[name]
            if lam is None:
                new_params, _ = coord.update(partial, params[name])
            else:
                new_params, _ = coord.update(
                    partial, params[name], reg_weight=lam[name]
                )
            params = {**params, name: new_params}
            new_score = coord.score(new_params)
            total = partial + new_score
            scores = {**scores, name: new_score}
            obj = self.training_loss(total) + sum(
                self.coordinates[n].regularization_term(params[n])
                if lam is None
                else self.coordinates[n].regularization_term(params[n], lam[n])
                for n in names
            )
            objs.append(obj)
            if self.validation_scorer is not None:
                v_scores = self.validation_scorer(params)
                vals.append(
                    {
                        key: ev.evaluate(v_scores, **kw)
                        for key, (ev, kw) in self.validation_evaluators.items()
                    }
                )
        return params, scores, total, objs, vals

    def _require_jittable_coordinates(self, mode: str) -> None:
        """fused_cycle / run_grid wrap EVERY coordinate in one outer jit; a
        cd_jit=False coordinate (multihost-sharded arrays) would be traced
        with non-addressable constants — fail with a clear message instead
        of JAX's opaque trace error."""
        bad = [n for n, c in self.coordinates.items()
               if not getattr(c, "cd_jit", True)]
        if bad:
            raise ValueError(
                f"{mode} compiles all coordinates into one jitted program, "
                f"but {bad} hold multihost-sharded arrays that cannot be "
                "closed over (cd_jit=False) — use the per-update run() path"
            )

    def _build_cycle(self):
        from photon_ml_tpu.compile import instrumented_jit

        self._require_jittable_coordinates("fused_cycle")
        # donate the carried (params, scores, total) pytrees: each fused
        # iteration's outputs alias the previous iteration's buffers — the
        # whole descent carries ONE copy of the model state instead of two
        return instrumented_jit(
            self._cycle_body,
            site="cd.fused_cycle",
            donate_argnums=(0, 1, 2) if self._donate else (),
        )

    def run_grid(
        self,
        reg_weights: Dict[str, "jnp.ndarray"],
        num_iterations: int,
        num_rows: int,
        init_params: Optional[Dict[str, Array]] = None,
        checkpointers: Optional[List[Optional[object]]] = None,
    ) -> List[CoordinateDescentResult]:
        """Train a lambda grid through ONE compiled descent cycle: the
        traced-``reg_weight`` cycle compiles once and every combo reuses the
        executable (the reference re-runs its whole driver per combo,
        re-tracing everything, cli/game/training/Driver.scala:330-337 —
        compile amortization is this API's win).

        Combos run SEQUENTIALLY, each at its own lambda. A batched variant
        that trained all G combos as one ``vmap`` lane axis shipped in
        rounds 2–4 and lost the measured race every round on every platform
        (0.8–0.86x: each lane pays the slowest lane's while_loop iterations,
        which costs more than the batched-arithmetic win) — it was removed
        per VERDICT r4 #9; the sequential strategy below is exactly what
        its auto-selector always picked.

        ``reg_weights`` maps every coordinate name to a (G,) vector of total
        regularization weights (combo g trains coordinate n at
        ``reg_weights[n][g]``). All coordinates must accept a traced
        ``reg_weight`` in update()/regularization_term() — the plain fixed /
        random-effect coordinates do; factored, bucketed, and distributed
        coordinates do not (their lambda lives in nested static configs).

        ``init_params`` (coordinate name -> unbatched params) warm-starts
        every combo's solver from the same point (e.g. a cheap pre-solve at
        one lambda), cutting each solve's while_loop iteration count.

        ``checkpointers`` (one per combo, or None) enables PER-CYCLE
        checkpoints on the grid: the compiled cycle returns at iteration
        granularity, so each crossed ``save_every`` boundary (and the final
        iteration) lands a checkpoint of the combo's (params, scores,
        total) lane pytree, and a restart resumes the combo from its last
        complete iteration — finished combos replay from their final
        checkpoint without re-solving. Per-UPDATE granularity is the one
        thing the grid cannot offer (updates live inside the compiled
        cycle); the iteration boundaries are also cooperative-preemption
        drain points, exactly like the fused cycle.

        Returns one CoordinateDescentResult per combo, in input order.
        """
        import inspect

        self._require_jittable_coordinates("run_grid")
        names = list(self.coordinates)
        for name in names:
            coord = self.coordinates[name]
            for method in (coord.update, coord.regularization_term):
                if "reg_weight" not in inspect.signature(method).parameters:
                    raise ValueError(
                        f"coordinate {name!r} ({type(coord).__name__})."
                        f"{method.__name__} does not accept a traced "
                        "reg_weight — the traced-lambda grid API needs "
                        "plain fixed/random-effect coordinates"
                    )
        if set(reg_weights) != set(names):
            raise ValueError(
                f"reg_weights keys {sorted(reg_weights)} != coordinates {sorted(names)}"
            )
        lam = {n: jnp.asarray(reg_weights[n], real_dtype()) for n in names}
        sizes = {n: lam[n].shape for n in names}
        g = sizes[names[0]][0] if sizes[names[0]] else 0
        if any(s != (g,) for s in sizes.values()):
            raise ValueError(f"all reg-weight vectors must be shape (G,), got {sizes}")

        if self._grid_cycle_fn is None:
            # one-lane vmap keeps the lane axis in the traced shapes, so
            # every combo (and every run_grid call on this instance) reuses
            # the SAME executable — the compile-amortization win
            from photon_ml_tpu.compile import instrumented_jit

            self._grid_cycle_fn = instrumented_jit(
                jax.vmap(self._cycle_body),
                site="cd.grid_cycle",
                donate_argnums=(0, 1, 2) if self._donate else (),
            )
        cycle_v = self._grid_cycle_fn

        dt = real_dtype()
        # every combo starts from the SAME seeded state — build it once, not
        # once per combo (a G-combo grid would otherwise pay G-1 redundant
        # full-data score passes per coordinate)
        params0 = {
            n: jnp.broadcast_to(
                (w0 := (
                    init_params[n]
                    if init_params is not None and n in init_params
                    else self.coordinates[n].initial_coefficients()
                )), (1,) + w0.shape
            )
            for n in names
        }
        scores0 = {n: jnp.zeros((1, num_rows), dt) for n in names}
        total0 = jnp.zeros((1, num_rows), dt)
        if init_params is not None:
            # mirror run(initial_params=...): a warm-started coordinate
            # contributes its CURRENT scores from step zero, broadcast
            # to the lane axis — otherwise the first grid cycle trains
            # every combo against zero offsets, defeating the warm start.
            # Names MISSING from init_params (e.g. a coordinate new since
            # the prior model) start cold, exactly like run().
            for n in names:
                if n not in init_params:
                    continue
                s0 = self.coordinates[n].score(jnp.asarray(init_params[n], dt))
                scores0[n] = jnp.broadcast_to(s0, (1, num_rows)).astype(dt)
                total0 = total0 + scores0[n]
        if checkpointers is not None and len(checkpointers) != g:
            raise ValueError(
                f"checkpointers must match the grid ({g} combos), "
                f"got {len(checkpointers)}"
            )
        n_coords = len(names)
        out = []
        for i in range(g):
            lam_i = {n: lam[n][i : i + 1] for n in names}
            ck = checkpointers[i] if checkpointers is not None else None
            if self._donate:
                # the donating cycle consumes its (params, scores, total)
                # inputs — hand every combo a fresh copy of the shared
                # seeds, or combo 2 would read combo 1's deleted buffers
                params = jax.tree.map(jnp.copy, dict(params0))
                scores = jax.tree.map(jnp.copy, dict(scores0))
                total = jnp.copy(total0)
            else:
                params = dict(params0)
                scores = dict(scores0)
                total = total0
            objective_history: List[float] = []
            validation_history: List[Dict[str, float]] = []
            start_iter = 0
            if ck is not None:
                restored = ck.restore(params0, scores0, total0)
                if restored is not None:
                    # grid checkpoints land only at iteration boundaries,
                    # so a restored step is always iteration-aligned
                    start_iter = restored.step // n_coords
                    params = restored.params
                    scores = restored.scores
                    total = restored.total_scores
                    objective_history = restored.objective_history
                    validation_history = restored.validation_history

            t0 = time.perf_counter()
            objective_dev: List[Array] = []
            validation_dev: List[Dict[str, Array]] = []

            def _drain():
                # one batched transfer each, like run()'s _drain — never
                # one RTT per scalar over a remote device tunnel
                if objective_dev:
                    objective_history.extend(
                        float(o[0]) for o in jax.device_get(objective_dev)
                    )
                    objective_dev.clear()
                if validation_dev:
                    validation_history.extend(
                        {k: float(v[0]) for k, v in m.items()}
                        for m in jax.device_get(validation_dev)
                    )
                    validation_dev.clear()

            def _save(step):
                from photon_ml_tpu.checkpoint import CheckpointState

                _drain()
                ck.save(
                    CheckpointState(
                        step=step,
                        params=params,
                        scores=scores,
                        total_scores=total,
                        objective_history=objective_history,
                        validation_history=validation_history,
                    )
                )

            for it in range(start_iter, num_iterations):
                step = (it + 1) * n_coords
                params, scores, total, objs, vals = cycle_v(
                    params, scores, total, lam_i
                )
                objective_dev.extend(objs)
                validation_dev.extend(vals)
                is_last = it == num_iterations - 1
                saved_here = ck is not None and (
                    step % ck.save_every < n_coords or is_last
                )
                if saved_here:
                    _save(step)
                if not is_last and _preemption.check(
                    "cycle", step=step, combo=i
                ):
                    if ck is not None:
                        if not saved_here:
                            _save(step)
                        if hasattr(ck, "wait"):
                            ck.wait()
                    raise _preemption.Preempted(
                        f"preempted at grid iteration boundary (combo {i}, "
                        f"step {step}): {_preemption.reason()}",
                        site="cycle",
                    )
            jax.block_until_ready(total)
            elapsed = time.perf_counter() - t0

            _drain()
            out.append(
                CoordinateDescentResult(
                    coefficients={n: params[n][0] for n in names},
                    total_scores=total[0],
                    objective_history=objective_history,
                    validation_history=validation_history,
                    timings={"(grid)": elapsed},
                )
            )
        return out

    def run(
        self,
        num_iterations: int,
        num_rows: int,
        checkpointer: Optional["CoordinateDescentCheckpointer"] = None,
        initial_params: Optional[Dict[str, object]] = None,
        frozen: Optional[set] = None,
    ) -> CoordinateDescentResult:
        """Run the descent; with a ``checkpointer``, state is saved after
        every coordinate update and a restart resumes from the last complete
        step (photon_ml_tpu.checkpoint — a designed upgrade, SURVEY.md §5.4:
        the reference has no mid-run checkpointing).

        ``initial_params`` warm-starts named coordinates from a previous
        run's coefficients (the grid-sweep warm start,
        ModelTraining.scala:158-191 semantics); missing names fall back to
        the coordinate's own initialization. A restored checkpoint takes
        precedence over both.

        ``frozen`` (the delta-retrain skip, photon_ml_tpu.retrain) names
        coordinates whose data AND configuration are unchanged since the
        prior run: they carry their ``initial_params`` coefficients and the
        step-zero scores forward BITWISE without ever solving — the
        objective still counts their loss/regularization contribution and
        histories/checkpoints stay step-aligned, so a frozen coordinate is
        indistinguishable from a converged one to everything downstream.
        Every frozen name must be warm-started (freezing an uninitialized
        coordinate would freeze zeros)."""
        names = list(self.coordinates)
        frozen = frozenset(frozen or ())
        if frozen:
            unknown = frozen - set(names)
            if unknown:
                raise ValueError(f"frozen coordinates {sorted(unknown)} are "
                                 "not in the updating sequence")
            unseeded = [n for n in frozen
                        if initial_params is None or n not in initial_params]
            if unseeded:
                raise ValueError(
                    f"frozen coordinates {sorted(unseeded)} have no "
                    "initial_params — freezing needs the prior coefficients"
                )
            if self.fused_cycle:
                raise ValueError(
                    "frozen coordinates cannot compose with fused_cycle "
                    "(per-coordinate skip lives outside the compiled "
                    "iteration); use the per-update path"
                )
        params = {
            n: (
                initial_params[n]
                if initial_params is not None and n in initial_params
                else self.coordinates[n].initial_coefficients()
            )
            for n in names
        }
        if initial_params is not None and self._donate:
            # donating updates consume their w0 — warm-start params belong
            # to the CALLER (e.g. a previous combo's result); hand the
            # donation a private copy so the caller's arrays survive
            for n in names:
                if n in initial_params and getattr(
                    self.coordinates[n], "cd_jit", True
                ):
                    params[n] = jax.tree.map(jnp.copy, params[n])
        scores = {n: jnp.zeros((num_rows,), real_dtype()) for n in names}
        if initial_params is not None:
            # warm-started coordinates contribute their CURRENT scores from
            # step zero, so the first update already trains on residuals of
            # the warm model (the point of the warm start) rather than on
            # zero offsets
            for n in names:
                if n in initial_params:
                    scores[n] = self.coordinates[n].score(params[n])
        # device scalars until the end of the run — converting per update
        # would serialize every dispatch on a host round-trip (weak over a
        # remote device tunnel); the reference pays the same sync as a Spark
        # reduce per update, we don't have to
        objective_dev: List[Array] = []
        validation_dev: List[Dict[str, Array]] = []
        objective_history: List[float] = []
        validation_history: List[Dict[str, float]] = []
        # per-coordinate entries only where they are actually measured (the
        # fused path measures whole cycles, not coordinates)
        timings = {} if self.fused_cycle else {n: 0.0 for n in names}
        trackers: Dict[str, object] = {}
        total = jnp.zeros((num_rows,), real_dtype())
        for n in names:
            total = total + scores[n]  # zeros unless warm-started above

        start_step = 0
        midstep = None  # mid-coordinate resume payload from an emergency ckpt
        if checkpointer is not None:
            restored = checkpointer.restore(params, scores, total)
            if restored is not None:
                start_step = restored.step
                params = restored.params
                scores = restored.scores
                total = restored.total_scores
                objective_history = restored.objective_history
                validation_history = restored.validation_history
                midstep = restored.partial

        def _drain():
            """Pull accumulated device scalars to host (one batched transfer)."""
            if objective_dev:
                objective_history.extend(float(v) for v in jax.device_get(objective_dev))
                objective_dev.clear()
            if validation_dev:
                host = jax.device_get(validation_dev)
                validation_history.extend(
                    {k: float(v) for k, v in m.items()} for m in host
                )
                validation_dev.clear()

        def _emergency_save(at_step: int, partial=None, already_saved=False):
            """Drain-to-boundary checkpoint for a preemption exit: make the
            completed work durable NOW (and fence an async commit) so the
            relaunched process resumes instead of recomputing. Returns the
            checkpoint path, or None without a checkpointer (the process
            still exits with the distinct preemption code — the supervisor
            just restarts from scratch)."""
            if checkpointer is None:
                return None
            from photon_ml_tpu.checkpoint import STEP_PREFIX, CheckpointState

            _drain()
            # the boundary save a moment ago already covers this step
            path = os.path.join(
                checkpointer.directory, f"{STEP_PREFIX}{at_step}"
            )
            if not already_saved or partial is not None:
                path = checkpointer.save(
                    CheckpointState(
                        step=at_step,
                        params=params,
                        scores=scores,
                        total_scores=total,
                        objective_history=objective_history,
                        validation_history=validation_history,
                        partial=partial,
                    )
                )
            # the fence: an async commit must be durable before the process
            # exits on the preemption path
            if hasattr(checkpointer, "wait"):
                checkpointer.wait()
            return path

        guard = self.divergence_guard
        guard_events_start = len(guard.events) if guard is not None else 0
        if self.fused_cycle:
            n_coords = len(names)
            if start_step % n_coords != 0:
                raise ValueError(
                    f"fused_cycle resume requires an iteration-aligned "
                    f"checkpoint; restored step {start_step} is mid-iteration "
                    f"(coordinates={n_coords}). Re-run unfused to finish the "
                    "partial iteration first."
                )
            if self._cycle_fn is None:
                self._cycle_fn = self._build_cycle()
            for it in range(num_iterations):
                step = (it + 1) * n_coords
                if step <= start_step:
                    continue
                t0 = time.perf_counter()
                new_params, new_scores, new_total, objs, vals = self._cycle_fn(
                    params, scores, total
                )
                if guard is not None:
                    # iteration granularity: the per-update states live
                    # inside the compiled cycle, so a non-finite outcome
                    # rolls the WHOLE iteration back to its entry state
                    new_params, new_total, ok = guard.filter_update(
                        "(fused-cycle)", step, new_params, new_total, params, total
                    )
                    if not ok:
                        new_scores = scores
                        # re-evaluate the rolled-back state once and repeat
                        # it per update so histories (and the step-aligned
                        # checkpoint contract) keep one entry per update
                        obj = self.training_loss(total) + sum(
                            self.coordinates[n].regularization_term(params[n])
                            for n in names
                        )
                        objs = [obj] * n_coords
                        if self.validation_scorer is not None:
                            v_scores = self.validation_scorer(params)
                            vals = [
                                {
                                    key: ev.evaluate(v_scores, **kw)
                                    for key, (ev, kw) in self.validation_evaluators.items()
                                }
                            ] * n_coords
                        else:
                            vals = []
                params, scores, total = new_params, new_scores, new_total
                if self.collect_timings:
                    jax.block_until_ready(total)
                timings["(fused-cycle)"] = (
                    timings.get("(fused-cycle)", 0.0) + time.perf_counter() - t0
                )
                objective_dev.extend(objs)
                validation_dev.extend(vals)
                is_last = it == num_iterations - 1
                # steps advance n_coords at a time here: fire whenever a
                # save_every boundary was CROSSED this iteration, not only
                # when step lands exactly on a multiple
                saved_here = checkpointer is not None and (
                    step % checkpointer.save_every < n_coords or is_last
                )
                if saved_here:
                    from photon_ml_tpu.checkpoint import CheckpointState

                    _drain()
                    checkpointer.save(
                        CheckpointState(
                            step=step,
                            params=params,
                            scores=scores,
                            total_scores=total,
                            objective_history=objective_history,
                            validation_history=validation_history,
                        )
                    )
                # cooperative preemption: iteration boundaries are the fused
                # cycle's only safe points (per-update state lives inside
                # the compiled program) — and they are iteration-ALIGNED, so
                # an emergency checkpoint here always satisfies the fused
                # resume contract above
                if not is_last and _preemption.check("cycle", step=step):
                    path = _emergency_save(step, already_saved=saved_here)
                    raise _preemption.Preempted(
                        f"preempted at iteration boundary (step {step}): "
                        f"{_preemption.reason()}",
                        site="cycle",
                        checkpoint_path=path,
                    )
            _drain()
            return CoordinateDescentResult(
                coefficients=params,
                total_scores=total,
                objective_history=objective_history,
                validation_history=validation_history,
                timings=timings,
                guard_events=(
                    list(guard.events[guard_events_start:])
                    if guard is not None
                    else []
                ),
            )

        step = 0
        for it in range(num_iterations):
            skip_rest_of_cycle = False
            for name in names:
                step += 1
                if step <= start_step:
                    continue  # already completed before the restart
                if not skip_rest_of_cycle and name not in frozen:
                    partial = total - scores[name]  # sum of the OTHER coordinates
                    t0 = time.perf_counter()
                    try:
                        if midstep is not None and step == int(
                            midstep["meta"].get("resume_step", -1)
                        ):
                            # the emergency checkpoint interrupted THIS step:
                            # hand the in-flight coordinate its paused state
                            # (scheduler carries / per-block progress) so it
                            # finishes instead of restarting — bitwise the
                            # same coefficients either way
                            mid_name = midstep["meta"].get("coordinate")
                            if mid_name != name:
                                raise ValueError(
                                    f"checkpoint partial targets coordinate "
                                    f"{mid_name!r} at step {step} but the "
                                    f"sequence reaches {name!r} — updating "
                                    "sequence changed; refusing to resume"
                                )
                            new_params, trackers[name] = self.coordinates[
                                name
                            ].update(partial, params[name], resume=midstep)
                            midstep = None
                        else:
                            new_params, trackers[name] = self._update_fns[name](
                                partial, params[name]
                            )
                    except _preemption.Preempted as e:
                        # an inner loop drained at a block/chunk boundary:
                        # checkpoint the completed steps PLUS the in-flight
                        # coordinate's progress, then unwind to the driver
                        payload = dict(e.partial) if e.partial else None
                        if payload is not None:
                            payload["meta"] = dict(
                                payload.get("meta") or {},
                                coordinate=name,
                                resume_step=step,
                            )
                        e.checkpoint_path = _emergency_save(
                            step - 1, partial=payload
                        )
                        raise
                    # chaos-test hook: a kind="nan" fault at this site
                    # corrupts the update exactly like a diverged solve
                    new_params = _faults.corrupt(
                        "optim.step", new_params, coordinate=name, step=step
                    )
                    new_score = self._score_fns[name](new_params)
                    if guard is not None:
                        new_params, new_score, ok = guard.filter_update(
                            name, step, new_params, new_score,
                            params[name], scores[name],
                        )
                        if not ok and guard.mode == "skip_cycle":
                            skip_rest_of_cycle = True
                    if self.collect_timings:
                        jax.block_until_ready(new_score)
                    timings[name] += time.perf_counter() - t0
                    params[name] = new_params
                    total = partial + new_score
                    scores[name] = new_score
                # else: guard abandoned this cycle OR the coordinate is
                # frozen (delta retrain) — state is unchanged, but
                # histories and checkpoints below stay step-aligned

                # objective = loss(total scores) + sum of reg terms
                # (CoordinateDescent.scala:172-178) — stays on device
                obj = self.training_loss(total) + sum(
                    self.coordinates[n].regularization_term(params[n]) for n in names
                )
                objective_dev.append(obj)

                if self.validation_scorer is not None:
                    v_scores = self.validation_scorer(params)
                    validation_dev.append(
                        {
                            key: ev.evaluate(v_scores, **kw)
                            for key, (ev, kw) in self.validation_evaluators.items()
                        }
                    )

                is_last = it == num_iterations - 1 and name == names[-1]
                saved_here = checkpointer is not None and (
                    step % checkpointer.save_every == 0 or is_last
                )
                if saved_here:
                    from photon_ml_tpu.checkpoint import CheckpointState

                    _drain()
                    checkpointer.save(
                        CheckpointState(
                            step=step,
                            params=params,
                            scores=scores,
                            total_scores=total,
                            objective_history=objective_history,
                            validation_history=validation_history,
                        )
                    )
                # cooperative preemption: every update boundary is a safe
                # drain point — make the finished step durable and unwind
                # with the distinct exit path (the final update just
                # finishes; there is nothing left to preempt)
                if not is_last and _preemption.check("cycle", step=step):
                    path = _emergency_save(step, already_saved=saved_here)
                    raise _preemption.Preempted(
                        f"preempted at update boundary (step {step}): "
                        f"{_preemption.reason()}",
                        site="cycle",
                        checkpoint_path=path,
                    )

        _drain()
        return CoordinateDescentResult(
            coefficients=params,
            total_scores=total,
            objective_history=objective_history,
            validation_history=validation_history,
            timings=timings,
            trackers=trackers,
            guard_events=(
                list(guard.events[guard_events_start:]) if guard is not None else []
            ),
        )
