"""Out-of-core fixed-effect coordinate for GAME coordinate descent.

Extends the GLM driver's --streaming-chunk-rows mechanism (optim/
streaming.py — the StorageLevel MEMORY_AND_DISK/DISK_ONLY answer) to the
GAME fixed-effect coordinate: the FE batch lives in mmap'd row chunks,
each optimizer evaluation streams them through the chunked
value+gradient accumulation, and scoring streams margins chunk by chunk.
Residual offsets fold per chunk (rows are contiguous in chunk order, so a
chunk's residual block is a slice of the global (N,) vector — the
addScoresToOffsets of Coordinate.scala:43-49, chunked).

Drop-in for CoordinateDescent (update/score/initial_coefficients/
regularization_term); cd_jit=False — the orchestrator must call it raw
(each evaluation re-enters the host to stream).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optim.common import OptResult
from photon_ml_tpu.optim.problem import GLMOptimizationProblem, _split_reg_weight
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMSource,
    lbfgs_minimize_streaming,
    make_perhost_hvp,
    make_perhost_value_and_grad,
    make_streaming_hvp,
    make_streaming_value_and_grad,
    tron_minimize_streaming,
)
from photon_ml_tpu.types import OptimizerType, real_dtype

Array = jax.Array


def _elastic_entry_drain(monitor, where: str) -> None:
    """Fixed-effect drain hook: both FE coordinates poll the elastic
    monitor only at whole-evaluation entries (parallel/elastic.py — the
    streamed evaluations may contain collectives, so mid-evaluation drains
    could strand a peer inside one)."""
    if monitor is None:
        return
    from photon_ml_tpu.parallel.elastic import drain_if_replan_pending

    drain_if_replan_pending(monitor, where=where)


def _streamed_update(problem: GLMOptimizationProblem, vg, hvp, l1_weight,
                     init_coefficients: Array) -> Tuple[Array, OptResult]:
    """THE streamed-update dispatch (bounds construction, TRON-vs-LBFGS
    branch), shared by the single-host and per-host coordinates — one
    definition, so the two can never drift apart (the same rule as the
    shared per-chunk kernels in optim/streaming)."""
    bounds = (
        (problem.constraints.lower, problem.constraints.upper)
        if problem.constraints is not None
        else None
    )
    if hvp is not None:
        res = tron_minimize_streaming(
            vg, hvp, jnp.asarray(init_coefficients, real_dtype()),
            problem.optimizer_config, bounds=bounds,
        )
    else:
        res = lbfgs_minimize_streaming(
            vg, jnp.asarray(init_coefficients, real_dtype()),
            problem.optimizer_config, l1_weight=l1_weight, bounds=bounds,
        )
    return res.coefficients, res


@dataclasses.dataclass
class StreamingFixedEffectCoordinate:
    """Fixed-effect coordinate over a :class:`ChunkedGLMSource`."""

    source: ChunkedGLMSource
    problem: GLMOptimizationProblem
    norm: NormalizationContext = dataclasses.field(
        default_factory=NormalizationContext.identity
    )
    # async pipeline depth (io/pipeline.py): chunks read + page-faulted on a
    # background thread while the previous chunk's kernel runs, next chunk's
    # H2D double-buffered. <= 0 = synchronous; None = PHOTON_PREFETCH_DEPTH
    # (default 2). Exact either way — chunk order and the additive
    # accumulation are unchanged.
    prefetch_depth: Optional[int] = None
    # shape canonicalization (photon_ml_tpu.compile): chunk row counts are
    # rounded up the geometric ladder with weight-0 rows, so the tail chunk
    # shares the other chunks' compiled partial. None = PHOTON_SHAPE_LADDER
    # (default off); accepts a ShapeBucketer or a spec string.
    bucketer: Optional[object] = None
    # the resolved execution plan (photon_ml_tpu.compile.plan): fills the
    # ladder / prefetch policies above when unset — a plan already
    # consumed the env vars, so unset fields do not re-resolve them
    plan: Optional[object] = None
    # elastic re-sharding monitor (parallel/elastic.ElasticMonitor): polled
    # at update/score ENTRY only — the streamed optimizer evaluations may
    # contain collectives (the per-host variant's chunk merges), so the
    # safe fixed-effect drain boundaries are between whole evaluations; a
    # re-planned update simply re-runs, which is bitwise (the update is a
    # pure function of (residuals, w0)). None = off.
    elastic: Optional[object] = None

    # streams per evaluation: CoordinateDescent must not wrap update/score
    # in an outer jit (same contract as the multihost coordinates)
    cd_jit = False

    def __post_init__(self):
        from photon_ml_tpu.compile import resolve_bucketer

        if self.plan is not None:
            if self.bucketer is None:
                self.bucketer = self.plan.bucketer or "off"
            if self.prefetch_depth is None:
                self.prefetch_depth = self.plan.prefetch_depth
        self.bucketer = resolve_bucketer(self.bucketer)
        self._margin_fn = jax.jit(
            lambda w, x: x @ self.norm.effective_coefficients(w)
            + self.norm.margin_shift(self.norm.effective_coefficients(w))
        )
        # chunk sizes are static for the source's lifetime: measure once
        # (for mmap'd .npy chunks len() reads only the header)
        self._chunk_sizes = [len(load()["y"]) for load in self.source.loaders]
        # ONE jitted chunk kernel for the whole run: the residual-updated
        # source swaps per update, but make_streaming_value_and_grad closes
        # over objective/norm only through the jitted partial, which caches
        # by function identity — so build it once against a MUTABLE source
        # holder and swap the holder's loaders per update
        self._live_source = ChunkedGLMSource(
            loaders=list(self.source.loaders),
            dim=self.source.dim,
            num_rows=self.source.num_rows,
        )
        l1, l2 = _split_reg_weight(self.problem.regularization, None)
        self._l1, self._l2 = float(l1), float(l2)
        self._vg = make_streaming_value_and_grad(
            self._live_source, self.problem.objective, self.norm,
            l2_weight=self._l2, prefetch_depth=self.prefetch_depth,
            bucketer=self.bucketer,
        )
        # TRON streams one extra pass per CG Hessian-vector product (the
        # reference's one-treeAggregate-per-CG-step cost, TRON.scala:268-281)
        self._hvp = (
            make_streaming_hvp(
                self._live_source, self.problem.objective, self.norm,
                l2_weight=self._l2, prefetch_depth=self.prefetch_depth,
                bucketer=self.bucketer,
            )
            if self.problem.optimizer == OptimizerType.TRON else None
        )

    @property
    def dim(self) -> int:
        return self.source.dim

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.dim,), real_dtype())

    def _residual_source(self, residual_offsets) -> ChunkedGLMSource:
        """Chunk view with the residuals folded into offsets (chunk rows
        are contiguous in source order, so each chunk takes a slice)."""
        resid = np.asarray(residual_offsets)
        loaders = []
        lo = 0
        for load, n_here in zip(self.source.loaders, self._chunk_sizes):
            def wrap(load=load, lo=lo, n_c=n_here):
                chunk = dict(load())
                base = np.asarray(
                    chunk.get("offsets", np.zeros(n_c, np.float32))
                )
                chunk["offsets"] = base + resid[lo : lo + n_c]
                return chunk

            loaders.append(wrap)
            lo += n_here
        return ChunkedGLMSource(
            loaders=loaders, dim=self.source.dim, num_rows=self.source.num_rows
        )

    def update(self, residual_offsets: Array, init_coefficients: Array
               ) -> Tuple[Array, OptResult]:
        _elastic_entry_drain(self.elastic, "streaming-FE update entry")
        # swap the live source's loaders to the residual view; the jitted
        # chunk kernel built once in __post_init__ is reused across updates
        self._live_source.loaders = self._residual_source(
            residual_offsets
        ).loaders
        return _streamed_update(
            self.problem, self._vg, self._hvp, self._l1, init_coefficients
        )

    def score(self, coefficients: Array) -> Array:
        """(N,) raw margins, streamed chunk by chunk through the prefetch +
        double-buffered H2D pipeline (no offsets — GAME scores are additive
        margin contributions, FixedEffectModel.scala:91-100)."""
        from photon_ml_tpu.optim.streaming import pipelined_device_chunks

        _elastic_entry_drain(self.elastic, "streaming-FE score entry")
        outs = []
        # canonicalized chunks carry weight-0 pad rows: slice each chunk's
        # margins back to its real row count so the (N,) layout is unchanged
        for (x, _, _, _), n_here in zip(
            pipelined_device_chunks(
                self.source, real_dtype(), self.prefetch_depth, self.bucketer
            ),
            self._chunk_sizes,
        ):
            outs.append(self._margin_fn(coefficients, x)[:n_here])
        return jnp.concatenate(outs) if outs else jnp.zeros((0,), real_dtype())

    def regularization_term(self, coefficients: Array) -> Array:
        return self.problem.regularization_term_value(coefficients)


@dataclasses.dataclass
class PerHostStreamingFixedEffectCoordinate:
    """Fixed-effect coordinate over a GLOBAL chunk list of which this host
    owns a subset (per-host streaming coordinate descent,
    parallel/perhost_streaming.py): every optimizer evaluation streams the
    OWNED chunks through the same chunked value+gradient kernels as the
    single-host coordinate, per-chunk partials merge exactly over the mesh
    (one reduction — each global chunk is owned by exactly one host), and
    every host replays the single-host sequential fold, so the whole LBFGS
    / TRON trajectory is replicated AND bitwise-equal to the single-host
    streaming run on the same chunk list (optim/streaming.py
    make_perhost_value_and_grad). Scoring scatters owned-chunk margins into
    the global (N,) vector and merges the disjoint writes exactly.

    ``chunk_sizes`` is the global per-chunk row count list (chunks tile
    [0, N) contiguously in order — in the multihost driver a chunk is one
    input part file, so ownership falls out of the per-host file share with
    no routing at all); ``owned_loaders`` maps this host's global chunk ids
    to loaders yielding {"x", "y", optional "offsets"/"weights"} host dicts.
    """

    chunk_sizes: List[int]
    owned_loaders: Dict[int, object]  # chunk id -> () -> host chunk dict
    dim: int
    problem: GLMOptimizationProblem
    ctx: Optional[object] = None  # parallel.mesh.MeshContext
    num_processes: int = 1
    norm: NormalizationContext = dataclasses.field(
        default_factory=NormalizationContext.identity
    )
    prefetch_depth: Optional[int] = None
    bucketer: Optional[object] = None
    # resolved execution plan (photon_ml_tpu.compile.plan): fills ladder /
    # prefetch when unset (authoritative — no env re-resolution under it)
    plan: Optional[object] = None
    # elastic drain hook, polled ONLY at update/score entry (the chunk
    # merges inside an evaluation are collectives — see the single-host
    # coordinate's note). FE chunk ownership is LOGICAL and versioned
    # with the entity-shard plan (EntityShardPlan.fe_chunk_owners): a
    # re-plan re-bases chunks across the surviving hosts the same way it
    # re-bases RE blocks, and the driver rebuilds this coordinate's
    # owned_loaders from plan.owned_fe_chunks() for the new membership
    elastic: Optional[object] = None

    # streams + reduces per evaluation: CoordinateDescent must call it raw
    cd_jit = False

    def __post_init__(self):
        from photon_ml_tpu.compile import instrumented_jit, resolve_bucketer

        if self.num_processes > 1 and self.ctx is None:
            raise ValueError(
                "PerHostStreamingFixedEffectCoordinate needs a MeshContext "
                "to merge chunk partials across processes"
            )
        if self.plan is not None:
            if self.bucketer is None:
                self.bucketer = self.plan.bucketer or "off"
            if self.prefetch_depth is None:
                self.prefetch_depth = self.plan.prefetch_depth
        self.bucketer = resolve_bucketer(self.bucketer)
        self._margin_fn = instrumented_jit(
            lambda w, x: x @ self.norm.effective_coefficients(w)
            + self.norm.margin_shift(self.norm.effective_coefficients(w)),
            site="streaming_fe.perhost_margin",
        )
        self._owned_ids = sorted(self.owned_loaders)
        self._chunk_starts = np.concatenate(
            [[0], np.cumsum(self.chunk_sizes)]
        ).astype(np.int64)
        self.num_rows = int(self._chunk_starts[-1])
        # mutable holder: the jitted per-chunk kernels are built ONCE by the
        # factories below; each update swaps only the loaders (the same
        # residual-view trick as StreamingFixedEffectCoordinate)
        self._live_source = ChunkedGLMSource(
            loaders=[self.owned_loaders[c] for c in self._owned_ids],
            dim=self.dim,
            num_rows=sum(int(self.chunk_sizes[c]) for c in self._owned_ids),
        )
        l1, l2 = _split_reg_weight(self.problem.regularization, None)
        self._l1, self._l2 = float(l1), float(l2)
        self._vg = make_perhost_value_and_grad(
            self._live_source, self._owned_ids, len(self.chunk_sizes),
            self.problem.objective, self.norm, self.ctx, self.num_processes,
            l2_weight=self._l2, prefetch_depth=self.prefetch_depth,
            bucketer=self.bucketer,
        )
        self._hvp = (
            make_perhost_hvp(
                self._live_source, self._owned_ids, len(self.chunk_sizes),
                self.problem.objective, self.norm, self.ctx,
                self.num_processes, l2_weight=self._l2,
                prefetch_depth=self.prefetch_depth, bucketer=self.bucketer,
            )
            if self.problem.optimizer == OptimizerType.TRON else None
        )

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.dim,), real_dtype())

    def _residual_loaders(self, residual_offsets) -> List[object]:
        """Owned-chunk views with the replicated (N,) residuals folded into
        offsets — each chunk takes its contiguous global row slice."""
        resid = np.asarray(residual_offsets)
        loaders = []
        for c in self._owned_ids:
            lo = int(self._chunk_starts[c])
            n_c = int(self.chunk_sizes[c])

            def wrap(load=self.owned_loaders[c], lo=lo, n_c=n_c):
                chunk = dict(load())
                base = np.asarray(
                    chunk.get("offsets", np.zeros(n_c, np.float32))
                )
                chunk["offsets"] = base + resid[lo : lo + n_c]
                return chunk

            loaders.append(wrap)
        return loaders

    def update(self, residual_offsets: Array, init_coefficients: Array
               ) -> Tuple[Array, OptResult]:
        _elastic_entry_drain(self.elastic, "perhost-FE update entry")
        self._live_source.loaders = self._residual_loaders(residual_offsets)
        return _streamed_update(
            self.problem, self._vg, self._hvp, self._l1, init_coefficients
        )

    def score(self, coefficients: Array) -> Array:
        """(N,) raw margins: owned chunks stream through the shared margin
        kernel, scatter into their contiguous global row slices, and the
        disjoint per-host writes merge exactly over the mesh — bitwise the
        single-host concatenation."""
        from photon_ml_tpu.optim.streaming import pipelined_device_chunks
        from photon_ml_tpu.parallel.perhost_streaming import merge_disjoint

        _elastic_entry_drain(self.elastic, "perhost-FE score entry")
        self._live_source.loaders = [
            self.owned_loaders[c] for c in self._owned_ids
        ]
        local = np.zeros(self.num_rows, real_dtype())
        chunks = pipelined_device_chunks(
            self._live_source, real_dtype(), self.prefetch_depth, self.bucketer
        )
        for c, (x, _, _, _) in zip(self._owned_ids, chunks):
            n_c = int(self.chunk_sizes[c])
            lo = int(self._chunk_starts[c])
            # canonicalized chunks carry weight-0 pad rows: slice back
            local[lo : lo + n_c] = np.asarray(
                self._margin_fn(coefficients, x)
            )[:n_c]
        return jnp.asarray(merge_disjoint(local, self.ctx, self.num_processes))

    def regularization_term(self, coefficients: Array) -> Array:
        return self.problem.regularization_term_value(coefficients)
