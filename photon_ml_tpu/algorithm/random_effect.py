"""Random-effect coordinate: vmapped per-entity GLM solves.

Reference spec: algorithm/RandomEffectCoordinate.scala:36-201 — per-entity
solve = activeData join problems join models -> mapValues{ local Breeze
optimizer }, scoring = join models with data by entity. TPU-native:

  * entities are the leading axis of padded ``(E, M, D_loc)`` tensors
    (built at ingest, data/game.py), so "one optimizer per entity"
    (RandomEffectOptimizationProblem.scala:39-125) is the SAME while_loop
    kernel ``vmap``-ed over the entity axis — converged entities keep
    looping as masked no-ops until the slowest lane finishes, which is why
    the kernels are branch-free;
  * sharding the entity axis over the mesh gives the reference's
    co-partitioned-RDD model parallelism with zero joins;
  * scoring is one gather: score_n = sum_k val_nk * W[entity(n), col_nk] —
    the cogroup in RandomEffectModel.scala:129-158 with static indices;
    rows whose entity has no model score 0 (same semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.game import RandomEffectDataset
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
from photon_ml_tpu.optim.common import OptimizerConfig, OptResult
from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_
from photon_ml_tpu.optim.tron import tron_minimize_
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType, real_dtype

Array = jax.Array


def entity_lane_fns(task, optimizer, optimizer_config, regularization,
                    reg_weight=None):
    """Per-lane solver closures over ONE entity's ``(x, y, off, w, ...)``
    problem, shared by the one-shot vmapped solve and the convergence-
    compaction scheduler (optim/scheduler.py) — both paths build the SAME
    objective closures, so their per-iteration arithmetic is bit-identical.

    Returns ``(solve_one, init_one, advance_one, result_of)``:
      * ``solve_one(x, y, off_e, w_e, w0) -> OptResult`` — the one-shot body
        ``RandomEffectCoordinate.update`` vmaps;
      * ``init_one(x, y, off_e, w_e, w0) -> state`` — fresh resumable state;
      * ``advance_one(x, y, off_e, w_e, state, limit) -> state`` — run until
        convergence or the absolute iteration ``limit`` (traced ok);
      * ``result_of(state) -> OptResult`` — view of a final state (works on
        lane-stacked states too).
    """
    from photon_ml_tpu.optim.lbfgs import (
        lbfgs_advance_,
        lbfgs_init_,
        lbfgs_result,
    )
    from photon_ml_tpu.optim.problem import _split_reg_weight
    from photon_ml_tpu.optim.tron import tron_advance_, tron_init_, tron_result

    loss = losses_mod.for_task(task)
    obj = GLMObjective(loss)
    norm = NormalizationContext.identity()
    l1, l2 = _split_reg_weight(regularization, reg_weight)
    cfg = optimizer_config

    def feats_of(x):
        # the lane's features: a dense (M, D) array, or a per-lane
        # SparseSlab view (ops/fused_sparse.py) — the slab already speaks
        # the Features protocol, and its static ``kernel`` field routes
        # the objective to the selected sparse family (fused Pallas GEVM /
        # XLA scatter / segment-sum) without touching the solver kernels
        return x if hasattr(x, "matvec") else DenseFeatures(x)

    def vg_of(x, y, off_e, w_e):
        batch = GLMBatch(feats_of(x), y, off_e, w_e)
        return lambda wt: obj.value_and_grad(wt, batch, norm, l2)

    if optimizer == OptimizerType.TRON:

        def hvp_of(x, y, off_e, w_e):
            batch = GLMBatch(feats_of(x), y, off_e, w_e)
            return lambda wt, v: obj.hessian_vector(wt, v, batch, norm, l2)

        def solve_one(x, y, off_e, w_e, w0):
            return tron_minimize_(
                vg_of(x, y, off_e, w_e), hvp_of(x, y, off_e, w_e), w0, cfg
            )

        def init_one(x, y, off_e, w_e, w0):
            return tron_init_(vg_of(x, y, off_e, w_e), w0, cfg)

        def advance_one(x, y, off_e, w_e, state, limit):
            return tron_advance_(
                vg_of(x, y, off_e, w_e), hvp_of(x, y, off_e, w_e), state, cfg,
                iteration_limit=limit,
            )

        return solve_one, init_one, advance_one, tron_result

    def solve_one(x, y, off_e, w_e, w0):
        return lbfgs_minimize_(vg_of(x, y, off_e, w_e), w0, cfg, l1_weight=l1)

    def init_one(x, y, off_e, w_e, w0):
        return lbfgs_init_(vg_of(x, y, off_e, w_e), w0, cfg, l1_weight=l1)

    def advance_one(x, y, off_e, w_e, state, limit):
        return lbfgs_advance_(
            vg_of(x, y, off_e, w_e), state, cfg, l1_weight=l1,
            iteration_limit=limit,
        )

    return solve_one, init_one, advance_one, lbfgs_result


@dataclasses.dataclass
class RandomEffectCoordinate:
    """Per-entity models over a RandomEffectDataset.

    ``solve_schedule`` (optim/scheduler.SolveSchedule, None = one-shot)
    routes ``update`` through the convergence-compaction scheduler: the
    vmapped solve runs in chunks of K iterations, unconverged lanes are
    compacted into ladder-sized batches between chunks, and finished lanes'
    results scatter back to entity order — bit-identical coefficients, far
    fewer wasted lane-iterations on skewed convergence distributions. A
    scheduled coordinate re-enters the host between chunks, so it opts out
    of the CoordinateDescent outer jit (``cd_jit=False``, like streaming).
    """

    dataset: RandomEffectDataset
    task: TaskType
    optimizer: OptimizerType = OptimizerType.LBFGS
    optimizer_config: Optional[OptimizerConfig] = None
    regularization: RegularizationContext = dataclasses.field(
        default_factory=RegularizationContext.none
    )
    solve_schedule: Optional[object] = None  # optim.scheduler.SolveSchedule
    # telemetry label the compacted solves record under (solve_stats):
    # wrappers set e.g. "bucket3" / "streaming-re[block 7]"
    solve_label: str = "re_solve"
    # sparse per-entity kernels (ops/fused_sparse.py). ``sparse_kernel``:
    # None = PHOTON_SPARSE_KERNEL (default off) | "auto" (race the families
    # and the dense incumbent on this dataset's own tensors) | a family
    # name. ``sparse_slab``: a prebuilt slab from a wrapper (bucketed /
    # streaming coordinates build per-bucket/per-block slabs host-side and
    # pass them through jit; its ``kernel`` field carries the selection).
    sparse_kernel: Optional[str] = None
    sparse_slab: Optional[object] = None  # ops.fused_sparse.SparseSlab
    # GSPMD entity sharding for SCHEDULED solves (parallel.mesh.MeshContext):
    # the dataset's entity axis is padded to a device multiple and sharded
    # over the mesh, and the scheduler's shared chunk kernels run over the
    # sharded arrays — XLA partitions the vmapped lanes across devices
    # while the compaction loop stays host-side OUTSIDE the mesh program.
    # Numerical contract: same as the shard_map engine (allclose at f32 —
    # XLA may fuse a lane's sample/feature reductions differently per
    # per-device batch size); the BITWISE host-count guarantee lives on
    # the owner-computes streaming path, which never re-partitions lanes.
    # One-shot mesh solves keep using the shard_map engine
    # (parallel.distributed.DistributedRandomEffectSolver).
    mesh_ctx: Optional[object] = None

    def __post_init__(self):
        if self.optimizer_config is None:
            self.optimizer_config = (
                OptimizerConfig.tron_default()
                if self.optimizer == OptimizerType.TRON
                else OptimizerConfig.lbfgs_default()
            )
        self._true_entities = self.dataset.num_entities
        if self.mesh_ctx is not None:
            if self.solve_schedule is None:
                raise ValueError(
                    "mesh_ctx on RandomEffectCoordinate is the GSPMD-"
                    "sharded scheduled path and needs a solve_schedule; "
                    "one-shot mesh solves use parallel.distributed."
                    "DistributedRandomEffectSolver"
                )
            from photon_ml_tpu.parallel.distributed import (
                pad_and_shard_re_dataset,
            )

            self.dataset = pad_and_shard_re_dataset(self.dataset, self.mesh_ctx)
            # sparse slabs stay dense under the mesh: the bucketed-COO
            # slab build is a host-side single-device construct (the
            # execution plan records this as a pinned decision)
            self.sparse_kernel = "off"
            self.sparse_slab = None
        if self.solve_schedule is not None:
            # chunk pauses re-enter the host: the outer CoordinateDescent
            # jit must call this coordinate's update raw (instance attr —
            # the class default stays True for one-shot coordinates)
            self.cd_jit = False
        self._slab = self.sparse_slab
        if self._slab is None:
            from photon_ml_tpu.ops.fused_sparse import resolve_sparse_kernel

            spec = resolve_sparse_kernel(self.sparse_kernel)
            if spec is not None:
                self._slab = self._build_slab(spec)

    def _build_slab(self, spec: str):
        """Host-side slab build + (for "auto") the per-dataset family race.
        Needs concrete tensors: coordinates constructed under a trace must
        receive a prebuilt ``sparse_slab`` instead (wrappers that construct
        sub-coordinates inside jit/shard_map pin ``sparse_kernel="off"``)."""
        from photon_ml_tpu.ops import fused_sparse

        ds = self.dataset
        if isinstance(ds.x, jax.core.Tracer):
            raise ValueError(
                "sparse-kernel selection builds the slab host-side and "
                "cannot run under a trace; pass a prebuilt sparse_slab "
                "when constructing this coordinate inside jit"
            )
        # None = the race handed the bucket back to the dense incumbent
        return fused_sparse.build_and_select(
            self.task, ds.x, ds.labels, ds.base_offsets, ds.weights,
            spec, self.solve_label,
        )

    @property
    def num_entities(self) -> int:
        return self.dataset.num_entities

    @property
    def true_entities(self) -> int:
        """Real (pre-mesh-padding) entity count — what exports and exact
        reductions slice to."""
        return self._true_entities

    @property
    def local_dim(self) -> int:
        return self.dataset.local_dim

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.num_entities, self.local_dim), real_dtype())

    # ------------------------------------------------------------------
    def gathered_offsets(self, residual_offsets: Array) -> Array:
        """Global (N,) residual scores gathered into the entity-major
        (E, M) layout and added to the base offsets (the addScoresToOffsets
        of RandomEffectDataSet.scala:57-74, as a gather instead of a
        join). Masked slots (row_index == -1) contribute base offset only."""
        ds = self.dataset
        safe_rows = jnp.maximum(ds.row_index, 0)
        gathered = residual_offsets[safe_rows]
        return ds.base_offsets + jnp.where(ds.row_index >= 0, gathered, 0.0)

    def update(self, residual_offsets: Array, init_coefficients: Array,
               reg_weight: Optional[Array] = None,
               resume: Optional[dict] = None) -> Tuple[Array, OptResult]:
        """Solve every entity's local problem (vmapped).

        ``residual_offsets`` is the global (N,) residual-score vector from
        the other coordinates. ``reg_weight`` overrides the context's
        total regularization weight as a TRACED scalar (the lambda-grid
        vmap axis). ``resume`` is a scheduler preemption snapshot (the
        ``partial`` payload of a
        :class:`~photon_ml_tpu.resilience.preemption.Preempted` raised at a
        chunk boundary) — the interrupted solve continues bitwise-identically
        from its paused carries; only valid with a ``solve_schedule``.

        Returns stacked coefficients (E, D_loc) and the vmapped OptResult
        (every field gains a leading entity axis — this is the
        RandomEffectOptimizationTracker's raw material).
        """
        ds = self.dataset
        off = self.gathered_offsets(residual_offsets)
        # the per-lane feature leaf: the dense (E, M, D) stack, or the
        # bucketed sparse slab when a sparse family was selected — the
        # solver kernels and the scheduler treat it as an opaque pytree
        feats = self._slab if self._slab is not None else ds.x

        if self.solve_schedule is not None:
            if reg_weight is not None:
                raise ValueError(
                    "solve compaction re-enters the host between chunks and "
                    "cannot run inside the traced-lambda grid; drop "
                    "solve_schedule or the reg_weight override"
                )
            from photon_ml_tpu.optim.scheduler import compacted_solve

            results = compacted_solve(
                (feats, ds.labels, off, ds.weights),
                init_coefficients,
                task=self.task,
                optimizer=self.optimizer,
                optimizer_config=self.optimizer_config,
                regularization=self.regularization,
                schedule=self.solve_schedule,
                label=self.solve_label,
                resume=resume,
            )
            if self.mesh_ctx is not None:
                # the coefficient slab keeps the sharded padded shape (the
                # carry contract); trackers trim to real entities at the
                # source, like the shard_map engine
                from photon_ml_tpu.parallel.distributed import (
                    trim_entity_tracker,
                )

                return results.coefficients, trim_entity_tracker(
                    results, self._true_entities, self.num_entities
                )
            return results.coefficients, results

        if resume is not None:
            raise ValueError(
                "a mid-solve resume snapshot needs the convergence "
                "scheduler's chunk boundaries; this coordinate solves "
                "one-shot (no solve_schedule)"
            )
        solve_one, _, _, _ = entity_lane_fns(
            self.task, self.optimizer, self.optimizer_config,
            self.regularization, reg_weight,
        )
        results = jax.vmap(solve_one)(feats, ds.labels, off, ds.weights, init_coefficients)
        return results.coefficients, results

    # ------------------------------------------------------------------
    def coefficient_variances(self, coefficients: Array,
                              residual_offsets: Array) -> Array:
        """Per-entity coefficient variances = 1 / Hessian-diagonal at the
        final coefficients, vmapped over entities -> (E, D_loc).

        Parity: RandomEffectOptimizationProblem builds its per-entity
        problems with the driver's isComputingVariance flag
        (optimization/game/RandomEffectOptimizationProblem.scala:110-124),
        each computing variance = 1/H_jj like the fixed effect
        (LogisticRegressionOptimizationProblem.scala:109-124). Computed
        lazily at save time (one vmapped pass), not per update.
        """
        ds = self.dataset
        loss = losses_mod.for_task(self.task)
        obj = GLMObjective(loss)
        norm = NormalizationContext.identity()
        l2 = self.regularization.l2_weight

        off = self.gathered_offsets(residual_offsets)

        def diag_one(x, y, off_e, w_e, w):
            batch = GLMBatch(DenseFeatures(x), y, off_e, w_e)
            return obj.hessian_diagonal(w, batch, norm, l2)

        from photon_ml_tpu.optim.problem import variances_from_hessian_diag

        diag = jax.vmap(diag_one)(ds.x, ds.labels, off, ds.weights, coefficients)
        return variances_from_hessian_diag(diag)

    # ------------------------------------------------------------------
    def score(self, coefficients: Array) -> Array:
        """Global (N,) scores for ALL rows (active + passive).

        score_n = sum_k val_nk * W[entity_pos_n, feat_idx_nk]; rows whose
        entity has no model (entity_pos == -1) score 0.
        """
        ds = self.dataset
        ep = jnp.maximum(ds.entity_pos, 0)
        li = jnp.maximum(ds.feat_idx, 0)
        coefs = coefficients[ep[:, None], li]  # (N, K)
        valid = (ds.entity_pos[:, None] >= 0) & (ds.feat_idx >= 0)
        return jnp.sum(jnp.where(valid, coefs * ds.feat_val, 0.0), axis=-1)

    # ------------------------------------------------------------------
    def regularization_term(self, coefficients: Array,
                            reg_weight: Optional[Array] = None) -> Array:
        """Sum of per-entity regularization terms
        (RandomEffectOptimizationProblem.getRegularizationTermValue)."""
        from photon_ml_tpu.optim.problem import _split_reg_weight

        l1, l2 = _split_reg_weight(self.regularization, reg_weight)
        if self.mesh_ctx is not None:
            # slice the mesh padding off so the reduction runs over exactly
            # the unsharded coordinate's array shape — the term stays
            # bitwise-equal by construction, not by pad-lanes-are-zero
            coefficients = coefficients[: self._true_entities]
        return l1 * jnp.sum(jnp.abs(coefficients)) + 0.5 * l2 * jnp.sum(
            jnp.square(coefficients)
        )

    # ------------------------------------------------------------------
    def global_coefficients(self, coefficients: Array) -> Array:
        return global_coefficients(self.dataset, coefficients)


def global_coefficients(dataset: RandomEffectDataset, coefficients: Array) -> Array:
    """Per-entity local coefficients back in the global feature space
    -> (E, D_global) (RandomEffectModelInProjectedSpace.toRandomEffectModel
    parity). INDEX_MAP/IDENTITY datasets scatter via local_to_global;
    RANDOM datasets back-project through the stored projection matrix
    (W_global = W_proj @ M). Host-sized output; for export/inspection."""
    ds = dataset
    if ds.projection_matrix is not None:
        return coefficients @ ds.projection_matrix
    e, d_loc = coefficients.shape
    out = jnp.zeros((e, ds.global_dim), coefficients.dtype)
    cols = jnp.maximum(ds.local_to_global, 0)
    valid = ds.local_to_global >= 0
    rows = jnp.broadcast_to(jnp.arange(e)[:, None], cols.shape)
    return out.at[rows.reshape(-1), cols.reshape(-1)].add(
        jnp.where(valid, coefficients, 0.0).reshape(-1)
    )
