"""Out-of-core random-effect coordinate: entity-block streaming.

The reference trains random-effect datasets that exceed memory by spilling
the grouped per-entity datasets to disk (StorageLevel.scala:22-24
DISK_ONLY, applied to every coordinate's dataset and intermediate scores at
CoordinateDescent.scala:134-147) and streaming them back per pass. This is
the TPU-native equivalent (VERDICT r4 next-round #3): the entity-major
tensor stacks are written ONCE to disk as entity blocks (each block built
and released one at a time), and every coordinate update / scoring pass
streams one block's slab through the vmapped solver — only one block is
ever resident on host or device. Coefficients are spilled to per-block
``.npy`` files between coordinate updates (the checkpoint layout of
photon_ml_tpu.checkpoint: plain arrays in a step directory), so the
coordinate's state handle is a directory, not a device array.

Entities are sorted by active-sample count before blocking, so each block
pads only to ITS max count — the same tight-padding insight as
algorithm/bucketed_random_effect.py, applied to the disk layout.

Same coordinate protocol as RandomEffectCoordinate (drop-in for
CoordinateDescent) with ``cd_jit=False``: every evaluation re-enters the
host to stream, exactly like StreamingFixedEffectCoordinate. Coefficient
matrices (E, D) are assumed to fit in memory when exported for validation
scoring / model save — it is the (E, M, D) DATA slabs, a factor M larger,
that stream.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.algorithm.bucketed_random_effect import _filter_game_data
from photon_ml_tpu.algorithm.random_effect import (
    RandomEffectCoordinate,
    global_coefficients,
)
from photon_ml_tpu.data.game import (
    GameData,
    RandomEffectDataConfig,
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.resilience import preemption as _preemption
from photon_ml_tpu.types import OptimizerType, TaskType, real_dtype

Array = jax.Array

_instance_seq = 0

_DATASET_FIELDS = (
    "row_index", "x", "labels", "base_offsets", "weights",
    "entity_pos", "feat_idx", "feat_val", "local_to_global",
)


def plan_entity_blocks(
    counts: np.ndarray,
    *,
    global_dim: int,
    active_upper_bound: Optional[int] = None,
    block_entities: Optional[int] = None,
    memory_budget_bytes: Optional[int] = None,
    itemsize: Optional[int] = None,
) -> List[np.ndarray]:
    """The entity blocking as a pure function of the (V,) per-entity row
    counts (dense-vocab space): sort present entities by count (stable, so
    similar-sized entities share a block and per-block padding stays tight),
    then cut by ``block_entities`` or the memory budget. Extracted from
    :func:`write_re_entity_blocks` so the MULTIHOST planner
    (parallel/perhost_streaming.py) derives the IDENTICAL blocking from
    collectively-merged counts — block composition is what makes the
    per-host solves bitwise-equal to the single-host streaming run."""
    counts = np.asarray(counts)
    n = int(counts.sum())
    present = np.nonzero(counts > 0)[0]
    order = present[np.argsort(counts[present], kind="stable")]
    cap = active_upper_bound or (int(counts.max()) if n else 1)
    active = np.minimum(counts[order], cap)
    if (block_entities is None) == (memory_budget_bytes is None):
        raise ValueError(
            "exactly one of block_entities / memory_budget_bytes is required"
        )
    itemsize = itemsize or np.dtype(real_dtype()).itemsize
    blocks: List[np.ndarray] = []
    if block_entities is not None:
        for lo in range(0, len(order), block_entities):
            blocks.append(np.sort(order[lo : lo + block_entities]))
    else:
        if memory_budget_bytes <= 0:
            raise ValueError(
                f"memory_budget_bytes must be positive, got {memory_budget_bytes}"
            )
        start = 0
        while start < len(order):
            end = start + 1
            while end < len(order):
                # padded x-stack estimate if [start, end] became one block:
                # (end-start+1) entities x max-count x ~max nnz width
                width = int(active[end])
                est = (end - start + 1) * width * itemsize
                # conservative local dim: entities see <= width * K features;
                # use the shard's global dim as the hard upper bound
                d_bound = min(global_dim, width * 64)
                if est * d_bound > memory_budget_bytes:
                    break
                end += 1
            blocks.append(np.sort(order[start:end]))
            start = end
    return blocks


def build_block_payload(
    data: GameData,
    config: RandomEffectDataConfig,
    entity_ids: np.ndarray,
    bucketer=None,
    memory_budget_bytes: Optional[int] = None,
    label: str = "block",
    row_to_global: Optional[np.ndarray] = None,
) -> dict:
    """One entity block's on-disk payload, built through the SAME
    build_random_effect_dataset path as the in-memory coordinate.
    ``data`` may be the FULL dataset (single-host) or a host-local subset
    holding every row of ``entity_ids`` (the multihost owner-computes path);
    in the latter case ``row_to_global`` maps local row positions to the
    GLOBAL row ids recorded as the block's ``row_sel`` (what residual
    gather and score scatter index)."""
    from photon_ml_tpu.compile import canonicalize_re_arrays

    re_id = config.random_effect_id
    ids = data.ids[re_id]
    row_sel = np.nonzero(np.isin(ids, entity_ids))[0]
    filtered = _filter_game_data(
        data, re_id, config.feature_shard_id, row_sel, entity_ids
    )
    ds = build_random_effect_dataset(filtered, config)
    payload = {f: np.asarray(getattr(ds, f)) for f in _DATASET_FIELDS}
    if bucketer is not None:
        # canonical ladder shapes: the budget below is checked on the
        # PADDED slab — the padded slab is what becomes resident
        payload = canonicalize_re_arrays(payload, bucketer)
    if memory_budget_bytes is not None and payload["x"].nbytes > memory_budget_bytes:
        raise ValueError(
            f"{label}: x-stack {payload['x'].nbytes}B exceeds the "
            f"{memory_budget_bytes}B budget — lower active_upper_bound "
            "or raise the budget (one entity's slab must fit)"
        )
    row_global = row_sel if row_to_global is None else row_to_global[row_sel]
    payload["row_sel"] = np.asarray(row_global).astype(np.int64)
    payload["entity_ids"] = np.asarray(entity_ids).astype(np.int64)
    payload["dense_ids"] = filtered.ids[re_id].astype(np.int32)
    del ds, filtered
    return payload


def write_block_file(out_dir: str, name: str, payload: dict) -> dict:
    """Atomically write one block payload; returns its manifest meta entry."""
    path = os.path.join(out_dir, name)
    with open(path + ".tmp", "wb") as f:
        np.savez(f, **payload)
    os.replace(path + ".tmp", path)
    return dict(
        file=name,
        # padded lane/local-dim counts: the shapes the solver and the
        # spilled coefficient stacks actually carry (padded lanes
        # scatter nothing — no row's entity_pos points at them)
        num_entities=int(payload["x"].shape[0]),
        local_dim=int(payload["x"].shape[2]),
        num_rows=int(len(payload["row_sel"])),
        x_bytes=int(payload["x"].nbytes),
    )


def write_streaming_manifest_json(
    out_dir: str,
    metas: List[dict],
    *,
    num_rows: int,
    global_dim: int,
    vocab: List[str],
    random_effect_id: str,
    feature_shard_id: str,
    ladder: Optional[str],
) -> None:
    """Atomically commit a block directory's ``manifest.json`` — shared by
    the cold builder below and the delta builder
    (:func:`photon_ml_tpu.retrain.delta.build_delta_streaming_manifest`),
    so the two layouts cannot drift apart."""
    manifest = dict(
        blocks=metas,
        num_rows=int(num_rows),
        global_dim=int(global_dim),
        vocab=list(vocab),
        random_effect_id=random_effect_id,
        feature_shard_id=feature_shard_id,
        ladder=ladder,
    )
    with open(os.path.join(out_dir, "manifest.json.tmp"), "w") as f:
        json.dump(manifest, f)
    os.replace(
        os.path.join(out_dir, "manifest.json.tmp"),
        os.path.join(out_dir, "manifest.json"),
    )


def write_re_entity_blocks(
    data: GameData,
    config: RandomEffectDataConfig,
    out_dir: str,
    block_entities: Optional[int] = None,
    memory_budget_bytes: Optional[int] = None,
    tensor_cache=None,
    cache_key: Optional[str] = None,
    bucketer=None,
) -> "StreamingREManifest":
    """Split the random-effect dataset into entity blocks on disk.

    Exactly one of ``block_entities`` / ``memory_budget_bytes`` sizes the
    blocks; with a budget, blocks are cut so no block's x-stack exceeds
    it. Each block is built through the SAME build_random_effect_dataset
    path as the in-memory coordinate (grouping, reservoir caps, INDEX_MAP
    projection — RandomEffectDataSet.scala:171-357 semantics) over only its
    entities' rows, then written and released — the full stack never
    exists anywhere.

    With a ``tensor_cache`` (:class:`photon_ml_tpu.io.tensor_cache.
    TensorCache`) and ``cache_key`` (content address of the SOURCE inputs +
    ingest config, computed by the caller who knows the source files), the
    block directory is built once under the cache and later calls with the
    same key return the committed manifest without re-grouping or
    re-padding anything — ``out_dir`` is ignored on a hit. A cache-write
    failure that survives retries degrades to the plain uncached build.
    :class:`StreamingRandomEffectCoordinate` detects a cache-resident
    manifest and spills its default run state to a private temp dir
    instead of the shared entry (pass ``state_root`` to control it).

    With a ``bucketer`` (:class:`photon_ml_tpu.compile.ShapeBucketer` or a
    spec string), every block's dims — entity lanes, active samples, local
    dim, scoring rows, nnz width — are rounded up the canonical ladder
    with masked padding BEFORE writing, so N blocks stream through ~log(N)
    compiled solver executables instead of N. The ladder spec is recorded
    in the manifest (callers including it in ``cache_key`` keep ladder
    changes from serving stale block shapes).
    """
    from photon_ml_tpu.compile import resolve_bucketer

    bucketer = resolve_bucketer(bucketer)
    if tensor_cache is not None and cache_key is not None:
        hit = tensor_cache.get_dir(cache_key)
        if hit is not None:
            return StreamingREManifest.load(hit)
        from photon_ml_tpu.resilience import RetryError

        try:
            entry = tensor_cache.build_dir(
                cache_key,
                lambda tmp: write_re_entity_blocks(
                    data, config, tmp,
                    block_entities=block_entities,
                    memory_budget_bytes=memory_budget_bytes,
                    bucketer=bucketer,
                ),
            )
            return StreamingREManifest.load(entry)
        except RetryError:
            pass  # cache unusable: fall through to the plain build
    if config.projector == "RANDOM":
        raise ValueError(
            "streaming random effects support INDEX_MAP/IDENTITY projectors "
            "(a shared RANDOM projection matrix would have to be replicated "
            "into every block; use the in-memory coordinate)"
        )
    re_id = config.random_effect_id
    ids = data.ids[re_id]
    n = data.num_rows
    counts = np.bincount(ids, minlength=int(ids.max()) + 1 if n else 0)
    blocks = plan_entity_blocks(
        counts,
        global_dim=data.shards[config.feature_shard_id].dim,
        active_upper_bound=config.active_upper_bound,
        block_entities=block_entities,
        memory_budget_bytes=memory_budget_bytes,
    )

    os.makedirs(out_dir, exist_ok=True)
    metas = []
    for i, entity_ids in enumerate(blocks):
        payload = build_block_payload(
            data, config, entity_ids, bucketer=bucketer,
            memory_budget_bytes=memory_budget_bytes, label=f"block {i}",
        )
        metas.append(write_block_file(out_dir, f"block-{i:05d}.npz", payload))
        del payload

    write_streaming_manifest_json(
        out_dir, metas,
        num_rows=int(n),
        global_dim=int(data.shards[config.feature_shard_id].dim),
        vocab=list(data.id_vocabs[re_id]),
        random_effect_id=re_id,
        feature_shard_id=config.feature_shard_id,
        ladder=(f"{bucketer.base}:{bucketer.growth:g}" if bucketer else None),
    )
    return StreamingREManifest.load(out_dir)


@dataclasses.dataclass
class StreamingREManifest:
    """On-disk entity-block layout descriptor."""

    dir: str
    blocks: List[dict]
    num_rows: int
    global_dim: int
    vocab: List[str]
    random_effect_id: str
    feature_shard_id: str
    # "BASE:GROWTH" canonical-ladder spec the blocks were padded with at
    # write time (photon_ml_tpu.compile), or None for natural shapes;
    # absent in pre-ladder manifests (load() defaults it)
    ladder: Optional[str] = None

    @classmethod
    def load(cls, path: str) -> "StreamingREManifest":
        with open(os.path.join(path, "manifest.json")) as f:
            m = json.load(f)
        return cls(dir=path, **m)

    @property
    def num_entities(self) -> int:
        return sum(b["num_entities"] for b in self.blocks)

    @property
    def max_block_bytes(self) -> int:
        return max(b["x_bytes"] for b in self.blocks)

    def load_block_host(self, i: int) -> dict:
        """Block i's arrays faulted onto the HOST (numpy, no device
        placement) — the disk stage of the prefetch pipeline. ``np.asarray``
        here (not a lazy mmap handle) so the page-cache faulting happens on
        the prefetch thread, not in the consumer's timed solve window."""
        z = np.load(os.path.join(self.dir, self.blocks[i]["file"]), mmap_mode="r")
        out = {f: np.asarray(z[f]) for f in _DATASET_FIELDS}
        out["row_sel"] = np.asarray(z["row_sel"])
        out["dense_ids"] = np.asarray(z["dense_ids"])
        out["_index"] = i
        return out

    def _block_from_host(
        self, host: dict
    ) -> Tuple[RandomEffectDataset, np.ndarray, np.ndarray]:
        i = host["_index"]
        ds = RandomEffectDataset(
            **{f: jnp.asarray(host[f]) for f in _DATASET_FIELDS},
            num_entities=self.blocks[i]["num_entities"],
            global_dim=self.global_dim,
        )
        return ds, host["row_sel"], host["dense_ids"]

    def load_block(self, i: int) -> Tuple[RandomEffectDataset, np.ndarray, np.ndarray]:
        """(dataset, row_sel, dense_ids) for block i (synchronous)."""
        return self._block_from_host(self.load_block_host(i))

    def iter_blocks(
        self, prefetch_depth: Optional[int] = None, start: int = 0,
        indices: Optional[List[int]] = None,
    ) -> "Iterator[Tuple[int, RandomEffectDataset, np.ndarray, np.ndarray]]":
        """Yield ``(i, dataset, row_sel, dense_ids)`` for every block from
        ``start`` on, with the async pipeline (io/pipeline.py): up to
        ``prefetch_depth`` blocks are read + page-faulted on a background
        thread while earlier blocks solve, and the NEXT block's
        host->device transfer (``jnp.asarray``, an async dispatch) is
        issued while the CURRENT block is consumed — double-buffered H2D.
        Depth <= 0 is the synchronous loop; block order and arithmetic are
        identical either way, so results are bit-identical with the
        pipeline on or off. ``start`` (a preemption resume) skips finished
        blocks BEFORE the prefetcher reads them, so resume cost is
        proportional to the remaining work, not the whole epoch.
        ``indices`` (the delta-retrain skip path) streams exactly the named
        blocks in the given order instead of ``range(start, n)`` — frozen
        blocks are never read from disk at all."""
        from photon_ml_tpu.io.pipeline import (
            Prefetcher,
            device_pipelined,
            resolve_depth,
        )

        depth = resolve_depth(prefetch_depth)
        n = len(self.blocks)
        seq = list(indices) if indices is not None else list(range(start, n))
        if depth <= 0:
            for i in seq:
                ds, row_sel, dense_ids = self.load_block(i)
                yield i, ds, row_sel, dense_ids
            return
        host_blocks = Prefetcher(
            lambda: (self.load_block_host(i) for i in seq),
            depth=depth,
            name="re-block-prefetch",
        )

        def place(host):
            return (host["_index"],) + self._block_from_host(host)

        yield from device_pipelined(host_blocks, place, depth=1)

    def load_block_meta(self, i: int) -> "BlockMeta":
        """Metadata-only view of block i: the per-entity bookkeeping arrays
        WITHOUT the (E, M, D) data slab — export/validation setup must not
        stream the whole dataset onto the device just to read positions."""
        z = np.load(os.path.join(self.dir, self.blocks[i]["file"]), mmap_mode="r")
        return BlockMeta(
            entity_pos=np.asarray(z["entity_pos"]),
            dense_ids=np.asarray(z["dense_ids"]),
            entity_ids=np.asarray(z["entity_ids"]),
            row_sel=np.asarray(z["row_sel"]),
            local_to_global=np.asarray(z["local_to_global"]),
            global_dim=self.global_dim,
        )


@dataclasses.dataclass
class BlockMeta:
    """Per-entity bookkeeping of one block (no data slab). Duck-types the
    fields :func:`global_coefficients` consults (streaming blocks never
    carry a RANDOM projection, so ``projection_matrix`` is always None)."""

    entity_pos: np.ndarray
    dense_ids: np.ndarray
    entity_ids: np.ndarray
    row_sel: np.ndarray
    local_to_global: np.ndarray
    global_dim: int
    projection_matrix = None


def _positions_of_dense(m: "BlockMeta") -> np.ndarray:
    """dense (block-local) entity id -> tensor position, -1 where absent.
    ``entity_pos`` is per ROW; only rows with a real tensor position carry
    their entity's mapping (dropped-passive rows hold -1). In a
    ladder-canonicalized block ``entity_pos`` carries -1 pad rows beyond
    the real rows ``dense_ids`` covers — slice to the real extent first."""
    entity_pos = m.entity_pos[: len(m.dense_ids)]
    known = entity_pos >= 0
    pos_of_dense = np.full(len(m.entity_ids), -1, np.int32)
    pos_of_dense[m.dense_ids[known]] = entity_pos[known]
    return pos_of_dense


@dataclasses.dataclass
class SpilledREState:
    """Coordinate state spilled to disk: per-block ``coefs-<i>.npy`` under
    ``dir`` (the checkpoint layout — plain arrays in a step directory).
    A missing file means zeros (the initial state costs no IO)."""

    dir: str
    shapes: List[Tuple[int, int]]

    def _path(self, i: int) -> str:
        """Block i's spill file. The per-host subclass
        (parallel/perhost_streaming.PerHostSpilledREState) names files by
        GLOBAL block id instead, so an elastic re-plan moves a block's
        coefficients as one file copy."""
        return os.path.join(self.dir, f"coefs-{i:05d}.npy")

    def block(self, i: int) -> np.ndarray:
        path = self._path(i)
        if not os.path.exists(path):
            return np.zeros(self.shapes[i], real_dtype())
        return np.load(path)

    def write(self, i: int, arr: np.ndarray) -> None:
        os.makedirs(self.dir, exist_ok=True)
        path = self._path(i)
        with open(path + ".tmp", "wb") as f:
            np.save(f, np.asarray(arr))
        os.replace(path + ".tmp", path)

    # -- checkpoint-by-reference protocol (photon_ml_tpu.checkpoint) --------
    # the coefficients are ALREADY durable (atomic per-block .npy spills),
    # so a descent checkpoint stores the directory handle, not the arrays:
    # streaming runs checkpoint without ever materializing the full stack
    def __checkpoint_ref__(self) -> dict:
        return {
            "kind": "spilled_re_state",
            "dir": self.dir,
            "shapes": [list(map(int, s)) for s in self.shapes],
            # distinguishes "never written: zeros by design" (the initial
            # state) from "written but since vanished" — the latter must
            # REJECT on restore, or block() would silently serve zeros for
            # trained coefficients
            "written": os.path.isdir(self.dir),
        }

    def __checkpoint_from_ref__(self, ref: dict) -> "SpilledREState":
        from photon_ml_tpu.checkpoint import CheckpointRefError

        if ref.get("kind") != "spilled_re_state":
            raise CheckpointRefError(
                f"checkpoint ref kind {ref.get('kind')!r} is not a spilled "
                "streaming state — coordinate types changed since the save"
            )
        shapes = [tuple(s) for s in ref["shapes"]]
        if shapes != [tuple(s) for s in self.shapes]:
            raise CheckpointRefError(
                "spilled-state ref shapes do not match this manifest's "
                f"blocks ({shapes[:3]}... vs {self.shapes[:3]}...) — the "
                "streaming blocks were rebuilt differently; refusing to resume"
            )
        if ref.get("written") and not os.path.isdir(ref["dir"]):
            raise CheckpointRefError(
                f"spilled coefficient dir {ref['dir']} referenced by this "
                "checkpoint no longer exists (epoch GC'd or output dir "
                "wiped) — restoring would silently zero trained "
                "coefficients; falling back to an older step"
            )
        return SpilledREState(dir=ref["dir"], shapes=shapes)


# ONE jitted update/score kernel shared by every block of every streaming
# coordinate in the process: the block dataset rides through as a pytree
# ARGUMENT and the solver configuration as hashable statics, so the jit
# cache keys on (shapes, config) — ladder-canonicalized blocks
# (write_re_entity_blocks bucketer) collapse onto ~log(N) compiled
# executables ACROSS coordinates and grid combos, counted per site by
# photon_ml_tpu.compile.compile_stats. w0 is donated: each block's
# coefficient stack is loaded fresh from the spill and dead after the
# solve, so the solver output aliases it in place. Built lazily so
# PHOTON_DONATE set before first training still applies.
_BLOCK_KERNEL_STATICS = ("task", "optimizer", "optimizer_config", "regularization")
_BLOCK_UPDATE_JIT = None
_BLOCK_SCORE_JIT = None


def _block_coord(ds, task, optimizer, optimizer_config, regularization,
                 sparse_slab=None):
    # sparse_kernel="off": the STREAMING coordinate owns slab selection
    # (_slab_for, host-side, cached). The sub-coordinate must never fall
    # back to PHOTON_SPARSE_KERNEL itself — under the block jit ds.x is a
    # tracer (the traced-construction guard would raise), and host-side a
    # dense decision (slab=None) would be re-derived every update
    return RandomEffectCoordinate(
        dataset=ds, task=task, optimizer=optimizer,
        optimizer_config=optimizer_config, regularization=regularization,
        sparse_kernel="off", sparse_slab=sparse_slab,
    )


def _block_update(ds, local_resid, w0, slab=None, **cfg):
    """``slab``: a prebuilt ops.fused_sparse.SparseSlab for this block
    (None = dense path). It rides as a pytree ARGUMENT like the dataset,
    so ladder-shaped slabs from different blocks share the executable; its
    static ``kernel`` field keys the jit cache on the selected family."""
    global _BLOCK_UPDATE_JIT
    if _BLOCK_UPDATE_JIT is None:
        from photon_ml_tpu.compile import donation_enabled, instrumented_jit

        def impl(ds, local_resid, w0, slab, task, optimizer, optimizer_config,
                 regularization):
            return _block_coord(
                ds, task, optimizer, optimizer_config, regularization,
                sparse_slab=slab,
            ).update(local_resid, w0)

        _BLOCK_UPDATE_JIT = instrumented_jit(
            impl,
            site="streaming_re.block_update",
            static_argnames=_BLOCK_KERNEL_STATICS,
            donate_argnums=(2,) if donation_enabled() else (),
        )
    return _BLOCK_UPDATE_JIT(ds, local_resid, w0, slab, **cfg)


def _block_score(ds, w, **cfg):
    global _BLOCK_SCORE_JIT
    if _BLOCK_SCORE_JIT is None:
        from photon_ml_tpu.compile import instrumented_jit

        def impl(ds, w, task, optimizer, optimizer_config, regularization):
            return _block_coord(
                ds, task, optimizer, optimizer_config, regularization
            ).score(w)

        _BLOCK_SCORE_JIT = instrumented_jit(
            impl,
            site="streaming_re.block_score",
            static_argnames=_BLOCK_KERNEL_STATICS,
        )
    return _BLOCK_SCORE_JIT(ds, w, **cfg)


@dataclasses.dataclass
class StreamingRandomEffectCoordinate:
    """Random-effect coordinate over disk-resident entity blocks."""

    manifest: StreamingREManifest
    task: TaskType
    optimizer: OptimizerType = OptimizerType.LBFGS
    optimizer_config: Optional[OptimizerConfig] = None
    regularization: RegularizationContext = dataclasses.field(
        default_factory=RegularizationContext.none
    )
    state_root: Optional[str] = None  # default: <manifest.dir>/state
    # async pipeline depth (io/pipeline.py): how many blocks the background
    # thread reads ahead of the solve, with the next block's H2D transfer
    # double-buffered against the current solve. <= 0 = synchronous; None =
    # PHOTON_PREFETCH_DEPTH (default 2). Results are bit-identical either
    # way (tests/test_pipeline.py) — this only moves I/O off the solve path.
    prefetch_depth: Optional[int] = None
    # convergence-compaction schedule (optim.scheduler.SolveSchedule, None =
    # one-shot): each block's vmapped solve runs chunked with active-lane
    # repacking through the scheduler's PROCESS-SHARED chunk kernels — since
    # ladder-canonicalized blocks share shapes, compacted batches from every
    # block reuse the same executables, and compaction composes with the
    # prefetch pipeline (block k+1 prefetches while block k's chunks run)
    solve_schedule: Optional[object] = None
    # gap-guided adaptive visitation (optim.convergence.AdaptiveSchedule,
    # None = always-visit): blocks are visited in DESCENDING convergence-
    # score order and a block whose score sat under tolerance for
    # `patience` consecutive epochs is skipped — coefficients carried
    # forward bitwise like a frozen block, the skip a recorded
    # PlanDecision (self.skip_decisions), the `optim.block_skip` fault
    # site guarding the decision (an injected fault degrades the epoch to
    # visit-everything). Score recording into the convergence ledger is
    # ALWAYS on: it is host-side arithmetic over telemetry the solves
    # already return, so the default path stays bitwise-identical.
    adaptive: Optional[object] = None
    # prior-run ledger entries (retrain.json's convergence_ledger) seeding
    # a run whose manifest dir has no fresh sidecar — scores survive delta
    # retrains even when the manifest itself is cache-resident
    ledger_seed: Optional[dict] = None
    # sparse per-entity kernels (ops/fused_sparse.py), selected per block
    # SHAPE: None = PHOTON_SPARSE_KERNEL (default off) | "auto" | family.
    # Block slabs are built host-side once (first epoch) and cached on the
    # coordinate; ladder-shaped slabs reuse the shared block executable.
    sparse_kernel: Optional[str] = None
    # the resolved execution plan (photon_ml_tpu.compile.plan): fills the
    # solve-schedule / sparse-kernel / prefetch policies above when they
    # are unset, so drivers thread ONE resolved object instead of three
    # flags. A plan is authoritative — it already consumed the env vars
    # (and may have pinned a policy), so unset fields do NOT re-resolve
    # the environment underneath it.
    plan: Optional[object] = None
    # delta-retrain skip set (photon_ml_tpu.retrain): block indices whose
    # data AND entity membership are unchanged since the prior run. Their
    # solve is SKIPPED — coefficients carry forward bitwise from the
    # (warm-seeded) incoming state without even reading the data slab —
    # and their score contribution is computed once and cached (frozen
    # coefficients over frozen rows are epoch-invariant). The caller
    # guarantees the incoming state holds the prior model's coefficients
    # for these blocks (retrain.warm.seed_spilled_state).
    frozen_blocks: Optional[frozenset] = None
    # elastic re-sharding monitor (parallel/elastic.ElasticMonitor, or any
    # object with poll() -> Optional[proposal]): polled at the SAME safe
    # boundaries as the preemption flag — update entry, every block
    # boundary, score entry. A pending membership proposal unwinds with
    # ReplanRequired (a Preempted subclass, so CD's emergency-checkpoint
    # machinery runs) carrying the per-block progress. None = off.
    elastic: Optional[object] = None
    # epoch numbering floor for a coordinate REBUILT mid-run (an elastic
    # re-plan rebinds the coordinate onto the re-based manifest): fresh
    # epochs continue ABOVE the interrupted run's numbering so new spill
    # dirs never collide with ones the checkpointed state still references
    # (update()'s GC additionally never removes its own input dir). 0 = a
    # fresh run, the pre-elastic numbering.
    initial_epoch: int = 0

    # streams per evaluation — CoordinateDescent must call update/score raw
    cd_jit = False

    def __post_init__(self):
        if self.plan is not None:
            if self.solve_schedule is None:
                self.solve_schedule = self.plan.schedule
            if self.adaptive is None:
                self.adaptive = self.plan.adaptive
            if self.sparse_kernel is None:
                self.sparse_kernel = self.plan.sparse_kernel or "off"
            if self.prefetch_depth is None:
                self.prefetch_depth = self.plan.prefetch_depth
        if self.state_root is None:
            # unique per coordinate INSTANCE: grid combos each build their
            # own coordinate over the shared manifest, and a shared epoch
            # numbering would let combo k+1 overwrite the spilled state a
            # finished combo's result handle still points at (model
            # selection saves after all combos ran)
            global _instance_seq
            _instance_seq += 1
            base = self.manifest.dir
            if os.path.exists(os.path.join(base, "meta.json")):
                # the manifest lives in a shared tensor-cache entry (only
                # cache commits carry meta.json next to manifest.json):
                # spilling run state there would grow the immutable entry
                # without bound and race concurrent runs — redirect the
                # default to a private temp dir instead
                import tempfile

                base = tempfile.mkdtemp(prefix="photon-re-state-")
            self.state_root = os.path.join(
                base, f"state-{os.getpid()}-{_instance_seq}"
            )
        self._epoch = int(self.initial_epoch)
        self._last_input_state_dir: Optional[str] = None
        self._last_output_state_dir: Optional[str] = None
        self._shapes = [
            (b["num_entities"], b["local_dim"]) for b in self.manifest.blocks
        ]
        from photon_ml_tpu.ops.fused_sparse import resolve_sparse_kernel

        self._sparse_spec = resolve_sparse_kernel(self.sparse_kernel)
        self._sparse_slabs: dict = {}
        self.frozen_blocks = frozenset(self.frozen_blocks or ())
        bad = [i for i in self.frozen_blocks
               if not 0 <= i < len(self.manifest.blocks)]
        if bad:
            raise ValueError(
                f"frozen_blocks {sorted(bad)} out of range for a "
                f"{len(self.manifest.blocks)}-block manifest"
            )
        # frozen block -> (row_sel, host scores): epoch-invariant by the
        # frozen contract, so one streaming pass covers the whole descent
        self._frozen_scores: dict = {}
        # the adaptive-schedule convergence ledger (optim/convergence.py):
        # per-GLOBAL-block scores + visit/skip/cost accounting, persisted
        # as an atomic sidecar so skipping survives restarts. A same-run
        # sidecar wins over a prior run's retrain.json seed.
        from photon_ml_tpu.optim.convergence import ConvergenceLedger

        self._ledger = ConvergenceLedger.load(self._ledger_dir())
        if self._ledger is None and self.ledger_seed:
            self._ledger = ConvergenceLedger.from_json(self.ledger_seed)
        if self._ledger is None:
            self._ledger = ConvergenceLedger()
        # local indices skipped by the LAST update (their coefficients are
        # unchanged, so their score/variance exports reuse cached values —
        # the PR 13 frozen-payload trick, invalidated the moment the block
        # is actually solved again)
        self._adaptive_skipped: set = set()
        self._skipped_scores: dict = {}
        #: every adaptive skip / degrade, recorded as PlanDecisions in the
        #: order they were taken (drivers log them; tests pin no-silent-skip)
        self.skip_decisions: list = []

    def _update_fn(self, ds, local_resid, w0, slab=None):
        return _block_update(
            ds, local_resid, w0, slab,
            task=self.task, optimizer=self.optimizer,
            optimizer_config=self.optimizer_config,
            regularization=self.regularization,
        )

    def _score_fn(self, ds, w):
        return _block_score(
            ds, w,
            task=self.task, optimizer=self.optimizer,
            optimizer_config=self.optimizer_config,
            regularization=self.regularization,
        )

    def _padded_resid(self, local_resid: Array, ds: RandomEffectDataset) -> Array:
        """Block residuals padded to the block's (ladder-canonical) row
        count: padded slots are never gathered (row_index there is -1), so
        zeros keep the solve exact while the residual SHAPE matches the
        shared executable's signature."""
        n_pad = ds.num_rows
        if local_resid.shape[0] == n_pad:
            return local_resid
        return jnp.pad(local_resid, (0, n_pad - local_resid.shape[0]))

    def _make_state(self, dir_path: str) -> SpilledREState:
        """State-object factory — the per-host coordinate overrides it to
        spill files keyed by GLOBAL block id (elastic re-plan transfers)."""
        return SpilledREState(dir=dir_path, shapes=self._shapes)

    # -- adaptive-schedule plumbing (optim/convergence.py) -------------------
    def _ledger_gid(self, i: int) -> int:
        """Ledger key for local block index ``i`` — GLOBAL block id in the
        per-host subclass so entries survive elastic re-plans; identity
        here (single-host manifests own every block)."""
        return int(i)

    def _ledger_dir(self) -> str:
        """Where the convergence-ledger sidecar lives: next to the
        manifest (the durable location re-based by the elastic protocol),
        unless the manifest is a cache-resident immutable entry (only
        cache commits carry meta.json) — then under this run's state root."""
        base = self.manifest.dir
        if os.path.exists(os.path.join(base, "meta.json")):
            return self.state_root
        return base

    def ledger_export(self) -> dict:
        """JSON-safe ledger entries ({gid: entry}) for retrain.json and
        the elastic re-plan ack records."""
        return self._ledger.to_json()

    def _save_ledger(self) -> None:
        try:
            self._ledger.save(self._ledger_dir())
        except OSError:
            # the ledger is an optimization's memory, never load-bearing:
            # an unwritable dir degrades to always-visit after a restart
            pass

    def _record_block_result(self, i: int, res) -> None:
        """Fold one solved block's telemetry into the convergence ledger +
        solve_stats — pure host arithmetic over arrays ``update`` already
        pulled to host, so recording is unconditionally on (bitwise-safe).
        The score proxy is the max per-lane final gradient norm (ladder-pad
        lanes converge at ~0 and never win the max); the cost is the
        summed per-lane iteration count."""
        from photon_ml_tpu.optim.scheduler import solve_stats

        gid = self._ledger_gid(i)
        score = float(np.max(np.asarray(res.grad_norm)))
        executed = int(np.sum(np.asarray(res.iterations)))
        under = (
            self.adaptive is not None and score < self.adaptive.tolerance
        )
        self._ledger.observe(
            gid, score, executed=executed, epoch=self._epoch,
            under_tolerance=under,
        )
        solve_stats.record_block(f"g{gid}", score=score, executed=executed)
        self._adaptive_skipped.discard(i)
        self._skipped_scores.pop(i, None)
        self._save_ledger()

    def _adaptive_partition(self, pending: List[int]) -> "Tuple[List[int], List[int]]":
        """(visit, skip) split of the pending local blocks under the
        adaptive policy: visit order is descending convergence score
        (unknown scores first), skips are the blocks whose score sat under
        tolerance for `patience` consecutive epochs. The decision boundary
        is the ``optim.block_skip`` fault site — an injected fault
        degrades THIS epoch to visit-everything with a recorded decision,
        never a silent skip. Always-visit (adaptive None) returns pending
        unchanged: the default path's visitation is byte-identical to the
        pre-adaptive coordinate."""
        if self.adaptive is None or not pending:
            return pending, []
        from photon_ml_tpu.compile.plan import PlanDecision
        from photon_ml_tpu.resilience import faults

        gid_of = {i: self._ledger_gid(i) for i in pending}
        rank = {g: r for r, g in enumerate(self._ledger.order(gid_of.values()))}
        by_gap = sorted(pending, key=lambda i: rank[gid_of[i]])
        candidates = [
            i for i in by_gap
            if self._ledger.should_skip(self._ledger_gid(i), self.adaptive)
        ]
        if candidates:
            try:
                faults.inject(
                    "optim.block_skip",
                    epoch=self._epoch, blocks=len(candidates),
                )
            except Exception as e:  # noqa: BLE001 — ANY injected fault means the skip decision is untrusted; visiting everything is the safe degrade
                self.skip_decisions.append(PlanDecision(
                    "adaptive", "pinned",
                    f"block-skip fault at epoch {self._epoch} "
                    f"({type(e).__name__}: {e}); degraded to "
                    "visit-everything for this epoch",
                ))
                return by_gap, []
        visit = [i for i in by_gap if i not in candidates]
        return visit, candidates

    def replan_state_dirs(self) -> List[str]:
        """The spill dirs an elastic re-plan must re-base
        (parallel/elastic.py): the INPUT of the last/in-flight update —
        the w0 source every checkpoint written BEFORE that update
        references — plus, when it exists, the last completed update's
        OUTPUT, which a boundary checkpoint taken AFTER the update (a
        drain at a fixed-effect boundary restores from it) references
        instead. A moved block's coefficients are copied into both, so
        the restore is correct no matter which safe boundary drained."""
        dirs: List[str] = []
        for d in (self._last_input_state_dir, self._last_output_state_dir):
            if d is not None and d not in dirs:
                dirs.append(d)
        return dirs

    def _elastic_drain(self, partial=None, where: str = "") -> None:
        """Poll the elastic monitor (local, throttled); a pending
        membership proposal unwinds with ReplanRequired. ``partial`` may be
        a zero-arg callable built only when a drain actually fires."""
        if self.elastic is None:
            return
        from photon_ml_tpu.parallel.elastic import drain_if_replan_pending

        drain_if_replan_pending(self.elastic, partial=partial, where=where)

    # -- coordinate protocol ------------------------------------------------
    @property
    def num_entities(self) -> int:
        return self.manifest.num_entities

    def initial_coefficients(self) -> SpilledREState:
        return self._make_state(os.path.join(self.state_root, "init"))

    def _sub_for(self, ds: RandomEffectDataset,
                 block: Optional[int] = None,
                 slab=None) -> RandomEffectCoordinate:
        return RandomEffectCoordinate(
            dataset=ds,
            task=self.task,
            optimizer=self.optimizer,
            optimizer_config=self.optimizer_config,
            regularization=self.regularization,
            solve_schedule=self.solve_schedule,
            solve_label=(
                "streaming-re" if block is None else f"streaming-re[block {block}]"
            ),
            # selection already happened in _slab_for — never re-resolve
            # the env here (slab=None MEANS "this block stays dense")
            sparse_kernel="off",
            sparse_slab=slab,
        )

    def _slab_for(self, i: int, ds: RandomEffectDataset):
        """This block's sparse slab (None = dense path), built host-side on
        first touch and cached — epochs re-stream the same immutable block
        data, so the slab is epoch-invariant. "auto" races per block; the
        race result is cached per (task, shape, platform) inside
        fused_sparse, so same-ladder blocks race once."""
        if self._sparse_spec is None:
            return None
        if i in self._sparse_slabs:
            return self._sparse_slabs[i]
        from photon_ml_tpu.ops import fused_sparse

        slab = fused_sparse.build_and_select(
            self.task, np.asarray(ds.x), ds.labels, ds.base_offsets,
            ds.weights, self._sparse_spec, f"streaming-re[block {i}]",
            # planner-narrowed race: the predicted family validated
            # against the dense incumbent only (--plan=auto); None = the
            # full per-bucket family race, exactly as before
            candidates=getattr(self.plan, "sparse_candidates", None),
        )
        if slab is not None:
            # cache HOST-resident: the streaming contract keeps device
            # memory O(one block) — device slabs cached per block would
            # grow with the manifest across the first epoch. The upload
            # rides each block call like the block tensors themselves
            slab = fused_sparse.SparseSlab(
                np.asarray(slab.idx), np.asarray(slab.val),
                slab.dim, slab.kernel,
            )
        self._sparse_slabs[i] = slab
        return slab

    def _partial_payload(self, new_state: SpilledREState, done_blocks,
                         inner: Optional[dict] = None) -> dict:
        """Preemption ``partial`` payload: per-block progress (the finished
        blocks' coefficients are ALREADY durable in the epoch dir) plus, for
        a mid-chunk interruption, the in-flight block's scheduler snapshot
        nested with prefixed array keys. ``done_blocks`` lists the LOCAL
        indices of the ACTIVE (non-frozen) blocks finished this epoch;
        ``blocks_done`` (its count) is kept for older payloads, whose
        prefix-of-the-active-order semantics :meth:`_resume_done_locals`
        still honors. The frozen set itself is not persisted because the
        relaunched driver re-derives the identical delta plan from the same
        durable inputs."""
        done = sorted(int(i) for i in done_blocks)
        meta = {
            "kind": "streaming_re",
            "epoch": self._epoch,
            "epoch_dir": new_state.dir,
            "blocks_done": len(done),
            "done_blocks": done,
            "inner": inner["meta"] if inner is not None else None,
        }
        arrays = {}
        if inner is not None:
            arrays = {f"inner.{k}": v for k, v in inner["arrays"].items()}
        return {"meta": meta, "arrays": arrays}

    def _resume_done_locals(self, m: dict, active) -> set:
        """The LOCAL indices already solved this epoch, from a resume
        payload. Explicit ``done_blocks`` wins (an elastic re-plan leaves
        arbitrary done SETS, not prefixes — the per-host subclass maps them
        through global block ids); older payloads carry only the prefix
        count."""
        if m.get("done_blocks") is not None:
            return {int(i) for i in m["done_blocks"]}
        return set(active[: int(m["blocks_done"])])

    def _resume_inner_ok(self, m: dict) -> bool:
        """Whether the nested mid-chunk scheduler snapshot may resume (the
        per-host subclass drops it across a plan-version change: re-solving
        that block whole is bitwise-equal, PR 4/5 pinned)."""
        return True

    def update(
        self, residual_offsets: Array, state: SpilledREState,
        resume: Optional[dict] = None,
    ) -> Tuple[SpilledREState, tuple]:
        """One block resident at a time: load slab, gather the block rows'
        residuals, run the vmapped solve, spill the coefficients, release.
        Returns a NEW state directory; the PREVIOUS epoch's spill stays
        valid (CD may still reference it), while epochs older than that are
        garbage-collected — without GC a C-combo x I-iteration grid would
        leave C*I full coefficient copies on disk, for exactly the
        workloads too big to be casual about storage.

        Block boundaries are PREEMPTION drain points: a request observed
        between blocks raises
        :class:`~photon_ml_tpu.resilience.preemption.Preempted` with this
        coordinate's per-block progress (finished blocks are already spilled
        atomically; a mid-chunk interruption inside a scheduled block nests
        the scheduler's snapshot). Passing that payload back as ``resume``
        continues from the first unfinished block — the completed blocks'
        tracker summaries are not recomputed (``None`` placeholders), the
        coefficients are bitwise those of an uninterrupted update."""
        import shutil

        # the exact spill the incoming (checkpointed) parameters reference:
        # an elastic re-plan copies moved blocks' coefficient files into it,
        # so the session needs its path (parallel/elastic.py)
        self._last_input_state_dir = getattr(state, "dir", None)
        n_blocks = len(self.manifest.blocks)
        active = [i for i in range(n_blocks) if i not in self.frozen_blocks]
        inner_resume = None
        if resume is not None:
            m = resume["meta"]
            if m.get("kind") != "streaming_re":
                raise ValueError(
                    f"resume payload kind {m.get('kind')!r} is not a "
                    "streaming-RE progress snapshot"
                )
            # continue the interrupted epoch IN PLACE: its dir already holds
            # the done blocks (each spilled atomically); no GC here — the
            # previous epoch must survive as this update's input
            self._epoch = int(m["epoch"])
            new_state = self._make_state(m["epoch_dir"])
            done_locals = set(self._resume_done_locals(m, active))
            if m.get("inner") is not None and self._resume_inner_ok(m):
                inner_resume = {
                    "meta": m["inner"],
                    "arrays": {
                        k[len("inner."):]: v
                        for k, v in (resume.get("arrays") or {}).items()
                        if k.startswith("inner.")
                    },
                }
        else:
            # a proposal already pending means the whole update re-runs
            # after the re-plan — drain BEFORE any work (and before the
            # epoch advances)
            self._elastic_drain(where="streaming-RE update entry")
            self._epoch += 1
            for old in range(1, self._epoch - 1):
                old_dir = os.path.join(self.state_root, f"epoch-{old}")
                if (getattr(state, "dir", None) is not None
                        and os.path.abspath(old_dir)
                        == os.path.abspath(state.dir)):
                    # never GC the spill this update READS from — a
                    # re-planned coordinate's epoch numbering jumps past
                    # its input's (initial_epoch), putting it in GC range
                    continue
                shutil.rmtree(old_dir, ignore_errors=True)
            new_state = self._make_state(
                os.path.join(self.state_root, f"epoch-{self._epoch}")
            )
            done_locals = set()
        resid_host = None
        # frozen (delta-unchanged) blocks never solve: their coefficients
        # carry forward bitwise from the warm-seeded incoming state — an
        # atomic per-block copy, no slab read, no solver iterations
        for i in sorted(self.frozen_blocks):
            new_state.write(i, state.block(i))
        # finished blocks were solved and spilled before the interruption
        # (and frozen blocks never solve); tracker summaries are telemetry
        # and are not recomputed — None placeholders, one slot per block
        summaries: List[Optional[object]] = [None] * n_blocks
        pending = [i for i in active if i not in done_locals]
        # adaptive scheduling: reorder the pending blocks by descending
        # convergence score and split off the persistently-converged ones
        # (optim/convergence.py). Skips happen BEFORE the visit loop —
        # coefficients carry forward bitwise like frozen blocks, the
        # ledger + skip decisions are recorded and persisted up front, and
        # the skipped blocks join done_locals so a later preemption's
        # resume payload already counts them
        pending, skipped = self._adaptive_partition(pending)
        if skipped:
            from photon_ml_tpu.compile.plan import PlanDecision
            from photon_ml_tpu.optim.scheduler import solve_stats

            for i in skipped:
                gid = self._ledger_gid(i)
                new_state.write(i, state.block(i))
                self._ledger.record_skip(gid, epoch=self._epoch)
                solve_stats.record_block(f"g{gid}", skipped=True)
                self.skip_decisions.append(PlanDecision(
                    "adaptive", "skipped",
                    f"block g{gid} scored under tolerance "
                    f"{self.adaptive.tolerance:g} for >= "
                    f"{self.adaptive.patience} consecutive epochs; epoch "
                    f"{self._epoch} carries its coefficients forward",
                ))
                self._adaptive_skipped.add(i)
                done_locals.add(i)
            self._save_ledger()
        # pipelined block loop: block k+1 reads from disk + transfers H2D
        # on the background stage while block k's vmapped solve runs —
        # resume streams ONLY the unfinished blocks (a re-plan leaves done
        # SETS, not prefixes, so the pending list is explicit)
        for k, (i, ds, row_sel, _) in enumerate(self.manifest.iter_blocks(
            self.prefetch_depth, indices=pending
        )):
            if isinstance(residual_offsets, jax.Array):
                local_resid = residual_offsets[jnp.asarray(row_sel)]
            else:
                if resid_host is None:
                    resid_host = np.asarray(residual_offsets)
                local_resid = jnp.asarray(resid_host[row_sel])
            w0 = jnp.asarray(state.block(i))
            slab = self._slab_for(i, ds)
            if self.solve_schedule is not None:
                # compacted path: the per-block coordinate routes through
                # the scheduler's process-shared chunk kernels (same-ladder
                # blocks reuse executables; the prefetch pipeline keeps
                # feeding blocks while chunks run)
                try:
                    coefs, res = self._sub_for(ds, block=i, slab=slab).update(
                        self._padded_resid(local_resid, ds), w0,
                        resume=(inner_resume if k == 0 else None),
                    )
                except _preemption.Preempted as e:
                    # mid-chunk inside block i: wrap the scheduler snapshot
                    # with this coordinate's block progress and unwind
                    raise _preemption.Preempted(
                        str(e), site=e.site,
                        partial=self._partial_payload(
                            new_state, done_locals, e.partial
                        ),
                    ) from e
            else:
                coefs, res = self._update_fn(
                    ds, self._padded_resid(local_resid, ds), w0, slab
                )
            new_state.write(i, np.asarray(coefs))
            # pull the tracker to host NOW: keeping the vmapped OptResult
            # as device arrays would pin every block's buffers alive
            summaries[i] = jax.tree.map(np.asarray, res)
            self._record_block_result(i, summaries[i])
            del ds, coefs, res
            done_locals.add(i)
            if len(done_locals) < len(active):
                if _preemption.check("block", block=i, epoch=self._epoch):
                    raise _preemption.Preempted(
                        f"preempted at block boundary ({len(done_locals)}/"
                        f"{len(active)} active blocks, epoch {self._epoch}):"
                        f" {_preemption.reason()}",
                        site="block",
                        partial=self._partial_payload(new_state, done_locals),
                    )
                # elastic drain at the SAME boundary: the partial payload is
                # built only if a proposal is actually pending
                self._elastic_drain(
                    partial=lambda: self._partial_payload(
                        new_state, done_locals
                    ),
                    where=f"block boundary (epoch {self._epoch})",
                )
        self._last_output_state_dir = new_state.dir
        return new_state, tuple(summaries)

    def score(self, state: SpilledREState) -> Array:
        # drain BEFORE the streaming pass (and, in the per-host subclass,
        # before its merge collective): hosts that finished their update
        # without hitting a block-boundary poll converge here
        self._elastic_drain(where="streaming-RE score entry")
        total = np.zeros(self.manifest.num_rows, real_dtype())
        # frozen blocks: coefficients and rows are epoch-invariant, so the
        # first pass's scores serve every later call without touching disk.
        # Adaptive-skipped blocks get the same treatment while skipped:
        # their coefficients are unchanged since the cached pass, and the
        # cache entry is dropped the moment the block is solved again —
        # skipping keeps score/variance export exact (the PR 13 trick).
        stream = []
        for i in range(len(self.manifest.blocks)):
            cached = None
            if i in self.frozen_blocks:
                cached = self._frozen_scores.get(i)
            elif i in self._adaptive_skipped:
                cached = self._skipped_scores.get(i)
            if cached is not None:
                row_sel, vals = cached
                total[row_sel] = vals
            else:
                stream.append(i)
        for i, ds, row_sel, _ in self.manifest.iter_blocks(
            self.prefetch_depth, indices=stream
        ):
            w = jnp.asarray(state.block(i))
            # ladder-padded blocks score their pad rows too (entity_pos -1
            # -> 0); slice back to the block's real rows
            vals = np.asarray(self._score_fn(ds, w))[: len(row_sel)]
            total[row_sel] = vals
            if i in self.frozen_blocks:
                self._frozen_scores[i] = (np.asarray(row_sel), vals)
            elif i in self._adaptive_skipped:
                self._skipped_scores[i] = (np.asarray(row_sel), vals)
            del ds, w
        return jnp.asarray(total)

    def regularization_term(self, state: SpilledREState) -> Array:
        l1 = self.regularization.l1_weight
        l2 = self.regularization.l2_weight
        acc = 0.0
        for i in range(len(self.manifest.blocks)):
            w = state.block(i)
            acc += l1 * float(np.sum(np.abs(w))) + 0.5 * l2 * float(
                np.sum(np.square(w))
            )
        return jnp.asarray(acc, real_dtype())

    # -- driver exports (same shape as BucketedRandomEffectCoordinate) ------
    def stack_sizes(self) -> List[int]:
        """Entity count per block stack, in block order."""
        return [b["num_entities"] for b in self.manifest.blocks]

    def vocab_position_maps(self) -> Tuple[np.ndarray, np.ndarray]:
        """vocab index -> (owning block, tensor position in that block).
        Metadata-only: never loads the data slabs."""
        v = len(self.manifest.vocab)
        block_of = np.full(v, -1, np.int32)
        pos_in_block = np.full(v, -1, np.int32)
        for i in range(len(self.manifest.blocks)):
            m = self.manifest.load_block_meta(i)
            pos_of_dense = _positions_of_dense(m)
            has = pos_of_dense >= 0
            block_of[m.entity_ids[has]] = i
            pos_in_block[m.entity_ids[has]] = pos_of_dense[has]
        return block_of, pos_in_block

    def global_coefficient_stacks(self, state: SpilledREState) -> List[Array]:
        """Per-block (E_b, D_global) back-projected coefficient stacks.
        Coefficient-sized (no sample axis) — fits by assumption."""
        return [
            global_coefficients(
                self.manifest.load_block_meta(i), jnp.asarray(state.block(i))
            )
            for i in range(len(self.manifest.blocks))
        ]

    def entity_means_by_raw_id(self, state: SpilledREState) -> Dict[str, np.ndarray]:
        return self.entity_export_by_raw_id(state)[0]

    def entity_export_by_raw_id(
        self, state: SpilledREState, residual_offsets: Optional[Array] = None
    ):
        """(means, variances) dicts keyed by raw entity id, block-streamed.
        Only the variance branch loads the data slabs (Hessian diagonals
        need the samples); means come from metadata alone."""
        means: Dict[str, np.ndarray] = {}
        variances: Optional[Dict[str, np.ndarray]] = (
            {} if residual_offsets is not None else None
        )
        vocab = self.manifest.vocab
        # the variance branch streams the data slabs (Hessian diagonals need
        # the samples) — pipeline them like update/score; the means-only
        # export stays metadata-only and loads no slab at all
        slabs = (
            self.manifest.iter_blocks(self.prefetch_depth)
            if residual_offsets is not None
            else iter(())
        )
        for i in range(len(self.manifest.blocks)):
            m = self.manifest.load_block_meta(i)
            w = jnp.asarray(state.block(i))
            mean_stack = np.asarray(global_coefficients(m, w))
            var_stack = None
            if residual_offsets is not None:
                _, ds, row_sel, _ = next(slabs)
                sub = self._sub_for(ds)
                local_resid = jnp.asarray(
                    np.asarray(residual_offsets)[row_sel]
                )
                var = sub.coefficient_variances(w, local_resid)
                var_stack = np.asarray(global_coefficients(m, var))
                del ds
            pos_of_dense = _positions_of_dense(m)
            for j, vi in enumerate(m.entity_ids):
                if pos_of_dense[j] >= 0:
                    means[vocab[vi]] = mean_stack[pos_of_dense[j]]
                    if variances is not None:
                        variances[vocab[vi]] = var_stack[pos_of_dense[j]]
        return means, variances
