from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_ml_tpu.algorithm.factored_random_effect import (
    FactoredRandomEffectCoordinate,
    FactoredState,
    MFOptimizationConfig,
)
from photon_ml_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_ml_tpu.algorithm.bucketed_random_effect import (
    BucketedRandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_ml_tpu.algorithm.streaming_fixed_effect import (
    PerHostStreamingFixedEffectCoordinate,
    StreamingFixedEffectCoordinate,
)
from photon_ml_tpu.algorithm.streaming_random_effect import (
    SpilledREState,
    StreamingRandomEffectCoordinate,
    StreamingREManifest,
    plan_entity_blocks,
    write_re_entity_blocks,
)

__all__ = [
    "PerHostStreamingFixedEffectCoordinate",
    "StreamingFixedEffectCoordinate",
    "plan_entity_blocks",
    "BucketedRandomEffectCoordinate",
    "CoordinateDescent",
    "FactoredRandomEffectCoordinate",
    "FactoredState",
    "FixedEffectCoordinate",
    "MFOptimizationConfig",
    "RandomEffectCoordinate",
    "SpilledREState",
    "StreamingRandomEffectCoordinate",
    "StreamingREManifest",
    "write_re_entity_blocks",
]
