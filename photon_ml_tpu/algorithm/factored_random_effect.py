"""Factored random-effect coordinate: alternating latent-space optimization.

Reference spec: algorithm/FactoredRandomEffectCoordinate.scala:36-285 and
optimization/game/FactoredRandomEffectOptimizationProblem.scala:36-138 —
the model is per-entity coefficients v_e in a k-dim latent space plus a
shared latent projection matrix M (k x d, Gaussian-random initialized
WITHOUT an intercept row, FactoredRandomEffectCoordinate.scala:195-201);
updateModel alternates numInnerIterations times:

  (a) project the dataset by the current M and solve every entity's GLM in
      the k-dim projected space (RandomEffectCoordinate.updateModel);
  (b) re-fit M as a single fixed-effect-style GLM whose features are the
      Kronecker products x (x) v_e and whose coefficient vector is the
      flattened M (updateLatentProjectionMatrix :218-253, kronecker
      :267-284), warm-started from the current M.

TPU-native redesign: the Kronecker features are NEVER materialized. A
datum's margin under flattened-M coefficients is <M, v_e x^T>, so the
latent objective is computed with two MXU matmuls per evaluation
(margins = sum_k (X M^T) * V, grad_M = (s * V)^T X with s the pointwise
loss derivative) via jax.value_and_grad on the closed-form margin — the
reference's RDD of (d*k)-wide LabeledPoints becomes an implicit operator.
Scoring = gather M columns for each row's sparse features, dot with v_e.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.game import RandomEffectDataset
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
from photon_ml_tpu.optim.common import OptimizerConfig, OptResult
from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_
from photon_ml_tpu.optim.tron import tron_minimize_
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.projectors import gaussian_random_projection_matrix
from photon_ml_tpu.types import OptimizerType, TaskType, real_dtype

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class MFOptimizationConfig:
    """(numInnerIterations, latentSpaceDimension) —
    optimization/game/MFOptimizationConfiguration.scala:23-55."""

    num_inner_iterations: int = 1
    latent_space_dimension: int = 5

    @staticmethod
    def parse(config_string: str) -> "MFOptimizationConfig":
        """Parse the CLI encoding ``numInnerIterations,latentSpaceDim``."""
        inner, latent = config_string.split(",")
        return MFOptimizationConfig(int(inner), int(latent))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FactoredState:
    """Carried model state: per-entity latent coefficients + shared matrix."""

    v: Array  # (E, k) latent per-entity coefficients
    matrix: Array  # (k, d) latent projection matrix

    def tree_flatten(self):
        return (self.v, self.matrix), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@dataclasses.dataclass
class FactoredRandomEffectCoordinate:
    """Alternating (v, M) optimization over a raw-space RandomEffectDataset.

    ``dataset`` must be built with IDENTITY projection so its local feature
    space is the shard's global d-dim space (the reference likewise factors
    the UNprojected dataset, FactoredRandomEffectCoordinate.scala:147-166).
    """

    dataset: RandomEffectDataset
    task: TaskType
    mf_config: MFOptimizationConfig = dataclasses.field(default_factory=MFOptimizationConfig)
    # per-entity latent solves
    re_optimizer: OptimizerType = OptimizerType.LBFGS
    re_optimizer_config: Optional[OptimizerConfig] = None
    re_regularization: RegularizationContext = dataclasses.field(
        default_factory=RegularizationContext.none
    )
    # latent-matrix fixed-effect-style solve
    latent_optimizer: OptimizerType = OptimizerType.LBFGS
    latent_optimizer_config: Optional[OptimizerConfig] = None
    latent_regularization: RegularizationContext = dataclasses.field(
        default_factory=RegularizationContext.none
    )
    seed: int = 1234567890
    # set under shard_map (entity-sharded dataset): the latent fit's
    # value/grad/Hv become psum reductions over the mesh axis so every
    # device runs the identical replicated-M optimizer trajectory
    axis_name: Optional[str] = None

    def __post_init__(self):
        ds = self.dataset
        if ds.projection_matrix is not None or ds.local_dim != ds.global_dim:
            raise ValueError(
                "FactoredRandomEffectCoordinate requires an IDENTITY-projection "
                f"dataset (one shared local space == the global {ds.global_dim}-dim "
                f"shard space); got local_dim={ds.local_dim}"
                + (", RANDOM projection" if ds.projection_matrix is not None else "")
                + ". Build with RandomEffectDataConfig(projector='IDENTITY')."
            )
        if self.re_optimizer_config is None:
            self.re_optimizer_config = (
                OptimizerConfig.tron_default()
                if self.re_optimizer == OptimizerType.TRON
                else OptimizerConfig.lbfgs_default()
            )
        if self.latent_optimizer_config is None:
            self.latent_optimizer_config = (
                OptimizerConfig.tron_default()
                if self.latent_optimizer == OptimizerType.TRON
                else OptimizerConfig.lbfgs_default()
            )

    # ------------------------------------------------------------------
    @property
    def latent_dim(self) -> int:
        return self.mf_config.latent_space_dimension

    def initial_coefficients(self) -> FactoredState:
        """Zero latent coefficients + Gaussian random initial matrix
        (no intercept row — FactoredRandomEffectCoordinate.scala:195-201).
        Named for the CoordinateDescent coordinate protocol; the "params"
        of this coordinate are the (v, M) FactoredState pytree."""
        ds = self.dataset
        m0 = gaussian_random_projection_matrix(
            self.latent_dim, ds.local_dim, keep_intercept=False, seed=self.seed
        )
        v0 = jnp.zeros((ds.num_entities, self.latent_dim), real_dtype())
        return FactoredState(v=v0, matrix=jnp.asarray(m0))

    # ------------------------------------------------------------------
    def update(
        self, residual_offsets: Array, state: FactoredState
    ) -> Tuple[FactoredState, OptResult]:
        """numInnerIterations alternating updates. Returns the new state and
        the final inner iteration's per-entity OptResult (stacked)."""
        ds = self.dataset
        loss = losses_mod.for_task(self.task)
        obj = GLMObjective(loss)
        norm = NormalizationContext.identity()

        safe_rows = jnp.maximum(ds.row_index, 0)
        gathered = residual_offsets[safe_rows]
        off = ds.base_offsets + jnp.where(ds.row_index >= 0, gathered, 0.0)

        re_l1 = self.re_regularization.l1_weight
        re_l2 = self.re_regularization.l2_weight
        lat_l1 = self.latent_regularization.l1_weight
        lat_l2 = self.latent_regularization.l2_weight
        re_cfg = self.re_optimizer_config
        lat_cfg = self.latent_optimizer_config

        # flatten active slots once for the latent fit
        e, m_cap, d = ds.x.shape
        x_rows = ds.x.reshape(e * m_cap, d)
        y_rows = ds.labels.reshape(-1)
        off_rows = off.reshape(-1)
        w_rows = ds.weights.reshape(-1)  # 0 on padding -> no contribution

        def solve_entities(xp, v0):
            def solve_one(x_e, y_e, off_e, w_e, v0_e):
                batch = GLMBatch(DenseFeatures(x_e), y_e, off_e, w_e)
                vg = lambda wt: obj.value_and_grad(wt, batch, norm, re_l2)
                if self.re_optimizer == OptimizerType.TRON:
                    hvp = lambda wt, vv: obj.hessian_vector(wt, vv, batch, norm, re_l2)
                    return tron_minimize_(vg, hvp, v0_e, re_cfg)
                return lbfgs_minimize_(vg, v0_e, re_cfg, l1_weight=re_l1)

            return jax.vmap(solve_one)(xp, ds.labels, off, ds.weights, v0)

        def _latent_data_value(mf, v):
            mat = mf.reshape(self.latent_dim, d)
            # margin_n = <M, v_{e(n)} x_n^T> = sum_k (x_n M^T)_k * v_k
            v_rows = jnp.repeat(v, m_cap, axis=0)  # (E*M, k)
            margins = jnp.sum((x_rows @ mat.T) * v_rows, axis=-1) + off_rows
            per = loss.loss(margins, y_rows) * w_rows
            return jnp.sum(per)

        def latent_value_and_grad(m_flat, v):
            # data term locally, psum across entity shards (axis_name set),
            # THEN the reg term once on the replicated M — the exact psum
            # placement GLMObjective uses (ops/objective.py:119-143)
            f, g = jax.value_and_grad(_latent_data_value)(m_flat, v)
            if self.axis_name is not None:
                f = jax.lax.psum(f, self.axis_name)
                g = jax.lax.psum(g, self.axis_name)
            f = f + 0.5 * lat_l2 * jnp.sum(jnp.square(m_flat))
            g = g + lat_l2 * m_flat
            return f, g

        def latent_hvp(m_flat, tangent, v):
            g_data = lambda mf: jax.value_and_grad(_latent_data_value)(mf, v)[1]
            hv = jax.jvp(g_data, (m_flat,), (tangent,))[1]
            if self.axis_name is not None:
                hv = jax.lax.psum(hv, self.axis_name)
            return hv + lat_l2 * tangent

        v, mat = state.v, state.matrix
        results = None
        for _ in range(self.mf_config.num_inner_iterations):
            # (a) per-entity solves in the space projected by the current M
            xp = ds.x @ mat.T  # (E, M, k) — one batched MXU matmul
            results = solve_entities(xp, v)
            v = results.coefficients
            # (b) latent-matrix refit, warm-started from the current M
            vg = lambda mf: latent_value_and_grad(mf, v)
            if self.latent_optimizer == OptimizerType.TRON:
                hvp = lambda mf, t: latent_hvp(mf, t, v)
                lat_res = tron_minimize_(vg, hvp, mat.reshape(-1), lat_cfg)
            else:
                lat_res = lbfgs_minimize_(vg, mat.reshape(-1), lat_cfg, l1_weight=lat_l1)
            mat = lat_res.coefficients.reshape(self.latent_dim, d)

        return FactoredState(v=v, matrix=mat), results

    # ------------------------------------------------------------------
    def score(self, state: FactoredState) -> Array:
        """Global (N,) scores: gather M's columns for each row's sparse
        features, dot with the row's entity latent coefficients
        (FactoredRandomEffectCoordinate.score = project then RE-score)."""
        ds = self.dataset
        ep = jnp.maximum(ds.entity_pos, 0)
        cols = jnp.maximum(ds.feat_idx, 0)
        valid = (ds.entity_pos[:, None] >= 0) & (ds.feat_idx >= 0)
        vals = jnp.where(valid, ds.feat_val, 0.0)
        # projected row features: xp_n = sum_j val_nj * M[:, col_nj] -> (N, k)
        m_cols = state.matrix.T[cols]  # (N, K, k)
        xp = jnp.sum(m_cols * vals[:, :, None], axis=1)
        return jnp.sum(xp * state.v[ep], axis=-1)

    # ------------------------------------------------------------------
    def regularization_term(self, state: FactoredState) -> Array:
        """RE reg over latent coefficients + latent problem's reg over M
        (FactoredRandomEffectOptimizationProblem.getRegularizationTermValue)."""
        re_term = self.re_regularization.l1_weight * jnp.sum(jnp.abs(state.v)) + (
            0.5 * self.re_regularization.l2_weight * jnp.sum(jnp.square(state.v))
        )
        lat_term = self.latent_regularization.l1_weight * jnp.sum(
            jnp.abs(state.matrix)
        ) + 0.5 * self.latent_regularization.l2_weight * jnp.sum(jnp.square(state.matrix))
        return re_term + lat_term

    # ------------------------------------------------------------------
    def random_effect_coefficients(self, state: FactoredState) -> Array:
        """Equivalent plain random-effect coefficients in the original space:
        W = V M, one (E, k) @ (k, d) matmul
        (FactoredRandomEffectModel.toRandomEffectModel analogue)."""
        return state.v @ state.matrix
