"""Step checkpointing for GAME coordinate descent.

Reference spec: SURVEY.md §5.4 — the reference has NO mid-run checkpointing
(it leans on Spark lineage recompute); durable state is limited to final
model save plus warm starts. On TPU there is no lineage to lean on, so this
module adds real step checkpoints as a designed upgrade: after each
coordinate update the full descent state (per-coordinate parameters, score
vectors, objective history, step counter) is written atomically; a restart
resumes from the last complete step.

Format: one directory per step (``step-<n>/``) holding an ``arrays.npz``
with every array leaf and a ``meta.json`` with the pytree structure, a
config fingerprint that must match on resume (guards against resuming onto
a different dataset/coordinate setup), and per-array SHA-256 checksums
verified on restore (a bit-rotten step is rejected with an actionable
error and restore falls back to the previous intact step). Writes go to a
temp dir renamed into place, so a crash mid-write never corrupts the
latest checkpoint.

Preemption extensions (resilience/preemption.py):

  * ``CheckpointState.partial`` carries a mid-coordinate payload (the
    convergence scheduler's paused carries, the streaming coordinate's
    per-block progress) so an emergency checkpoint written at a drain
    boundary resumes INSIDE the interrupted coordinate.
  * A state leaf exposing ``__checkpoint_ref__()`` (e.g. the streaming
    coordinate's :class:`~photon_ml_tpu.algorithm.streaming_random_effect.
    SpilledREState`, whose coefficients already live on disk) is stored as
    a JSON reference instead of arrays; restore rebuilds it via the
    template leaf's ``__checkpoint_from_ref__``.
  * The save path is split into :meth:`CoordinateDescentCheckpointer.
    _prepare` (host snapshot — the only part that must be synchronous) and
    ``_commit`` (retry + atomic rename), which
    :class:`photon_ml_tpu.checkpoint_async.AsyncCheckpointer` runs on a
    background thread so the solve never blocks on disk.
  * Under multihost, restore first agrees on the step via a collective min
    (:meth:`~photon_ml_tpu.parallel.multihost.MultihostContext.
    agree_restore_step`) so no host resumes a step another host failed to
    commit.
  * Restore is PLAN-VERSIONED for elastic re-sharding (parallel/
    elastic.py): the per-host spilled-state reference
    (:class:`~photon_ml_tpu.parallel.perhost_streaming.
    PerHostSpilledREState`) records per-GLOBAL-block-id shapes and which
    blocks had written coefficients, so a checkpoint written under entity-
    shard plan v1 restores under plan v2 — the rebuild validates every
    still-owned block per global id (and the presence of every recorded
    coefficient file after the re-base transfer) instead of demanding the
    old positional shape list. The mid-coordinate ``partial`` payload is
    keyed the same way (``done_global_ids``), so a mid-epoch drain resumes
    onto the re-planned owner map.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import tempfile
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

STEP_PREFIX = "step-"
TMP_PREFIX = ".ckpt-"
ARRAYS_FILE = "arrays.npz"
META_FILE = "meta.json"

logger = logging.getLogger(__name__)


def fingerprint(parts: Dict[str, Any]) -> str:
    """Stable hash of the run configuration (coordinate names, row count,
    anything the caller adds); resuming with a different fingerprint fails."""
    blob = json.dumps(parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _leaf_to_host(leaf) -> np.ndarray:
    """Device leaf -> host ndarray. A multi-host-sharded array is not fully
    addressable from one process; every process participates in an
    all-gather (a COLLECTIVE — all hosts must flatten together) so the
    coordinator can write the complete state."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(leaf)


class CheckpointRefError(ValueError):
    """A by-reference leaf could not be rebuilt (wrong kind / stale ref);
    restore treats the step as unusable and falls back."""


def _is_ref_leaf(x: Any) -> bool:
    return hasattr(x, "__checkpoint_ref__")


def rebuild_from_ref(template: Any, ref: Any) -> Any:
    """Rebuild a by-reference state leaf from its stored JSON ref.

    The single entry point of the by-reference restore path: checkpoint
    restore uses it for ``__checkpoint_ref__`` leaves (spilled streaming
    coefficients), and the serving :class:`~photon_ml_tpu.serve.swap.
    ModelSwapper` rolls a live server to a new model through the same
    protocol — the template (the currently-installed leaf) validates the
    ref kind and constructs the replacement; a stale/wrong-kind ref raises
    :class:`CheckpointRefError` so the caller falls back instead of
    installing garbage."""
    if not hasattr(template, "__checkpoint_from_ref__"):
        raise CheckpointRefError(
            f"cannot rebuild {type(template).__name__} from a reference: "
            "the template has no __checkpoint_from_ref__"
        )
    return template.__checkpoint_from_ref__(ref)


def _flatten_state(state: Dict[str, Any]) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Pytree state dict -> (flat arrays, structure description). Leaves
    with a ``__checkpoint_ref__`` protocol (state that is ALREADY durable
    on disk, e.g. spilled streaming coefficients) contribute a JSON ref in
    the structure instead of arrays."""
    arrays: Dict[str, np.ndarray] = {}
    structure: Dict[str, Any] = {}
    for name, value in state.items():
        leaves, treedef = jax.tree_util.tree_flatten(value, is_leaf=_is_ref_leaf)
        refs: Dict[str, Any] = {}
        structure[name] = {
            "num_leaves": len(leaves),
            "treedef": str(treedef),  # compared against the template on restore
            "refs": refs,
        }
        for i, leaf in enumerate(leaves):
            if _is_ref_leaf(leaf):
                refs[str(i)] = leaf.__checkpoint_ref__()
            else:
                arrays[f"{name}.{i}"] = _leaf_to_host(leaf)
    return arrays, structure


def _unflatten_state(
    template: Dict[str, Any], arrays: Dict[str, np.ndarray], structure: Dict[str, Any]
) -> Dict[str, Any]:
    """Rebuild state using the caller's template pytrees for structure."""
    out: Dict[str, Any] = {}
    for name, value in template.items():
        leaves, treedef = jax.tree_util.tree_flatten(value, is_leaf=_is_ref_leaf)
        if name not in structure:
            raise ValueError(f"checkpoint missing state entry {name!r}")
        if structure[name]["num_leaves"] != len(leaves):
            raise ValueError(
                f"checkpoint entry {name!r} has {structure[name]['num_leaves']} "
                f"leaves, template expects {len(leaves)}"
            )
        if structure[name]["treedef"] != str(treedef):
            # same leaf count but different structure (e.g. reordered fields)
            # would silently permute arrays into the wrong slots
            raise ValueError(
                f"checkpoint entry {name!r} structure {structure[name]['treedef']} "
                f"does not match template {str(treedef)}; refusing to resume"
            )
        refs = structure[name].get("refs") or {}
        new_leaves = []
        for i, tmpl_leaf in enumerate(leaves):
            if str(i) in refs:
                if not _is_ref_leaf(tmpl_leaf):
                    raise CheckpointRefError(
                        f"checkpoint entry {name!r} leaf {i} was saved by "
                        "reference but the template leaf has no "
                        "__checkpoint_from_ref__ — coordinate types changed"
                    )
                new_leaves.append(rebuild_from_ref(tmpl_leaf, refs[str(i)]))
            else:
                new_leaves.append(jnp.asarray(arrays[f"{name}.{i}"]))
        out[name] = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return out


def _checksums(arrays: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Per-array SHA-256 over the raw bytes (written into meta; verified on
    restore so silent bit-rot is caught before it poisons a resume)."""
    return {
        k: hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest()
        for k, v in arrays.items()
    }


def _verify_checksums(
    arrays: Dict[str, np.ndarray], expected: Dict[str, str], path: str
) -> None:
    """Raise ValueError naming the first mismatched array (actionable: the
    step directory to delete / the fallback restore will take)."""
    for k, digest in expected.items():
        if k not in arrays:
            raise ValueError(
                f"checkpoint {path} is missing array {k!r} listed in its "
                "meta checksums — truncated or tampered step"
            )
        got = hashlib.sha256(np.ascontiguousarray(arrays[k]).tobytes()).hexdigest()
        if got != digest:
            raise ValueError(
                f"checkpoint {path} array {k!r} fails its SHA-256 check "
                f"({got[:12]} != recorded {digest[:12]}) — bit-rotten step; "
                "restore falls back to the previous intact step (delete "
                f"{path} to silence this warning)"
            )


@dataclasses.dataclass
class CheckpointState:
    """Everything needed to resume mid-descent."""

    step: int  # completed (iteration * num_coordinates + coordinate) updates
    params: Dict[str, Any]  # coordinate name -> params pytree
    scores: Dict[str, Any]  # coordinate name -> (N,) score vector
    total_scores: Any  # (N,)
    objective_history: List[float]
    validation_history: List[Dict[str, float]]
    # mid-coordinate payload from a preemption drain (resilience/preemption):
    # {"meta": JSON-able bookkeeping incl. the in-flight coordinate and
    # resume_step, "arrays": name -> ndarray of paused solver carries} —
    # None for ordinary boundary checkpoints
    partial: Optional[Dict[str, Any]] = None


class CoordinateDescentCheckpointer:
    """Atomic per-step checkpoint writer/reader with retention."""

    def __init__(
        self,
        directory: str,
        run_fingerprint: str = "",
        keep: int = 2,
        save_every: int = 1,
        multihost=None,
    ):
        """``save_every``: checkpoint every k-th coordinate update (the final
        update of a run is always saved) — bounds blocking host I/O when
        per-coordinate solves are fast.

        ``multihost``: a parallel.multihost.MultihostContext. When set, saves
        are multihost-safe: all hosts flatten (the sharded-leaf all-gather is
        a collective), ONLY the coordinator writes, and barriers fence the
        write so no host races past an incomplete checkpoint. ``directory``
        is assumed to be shared (or only read back on the coordinator)."""
        self.directory = directory
        self.run_fingerprint = run_fingerprint
        self.keep = max(keep, 1)
        self.save_every = max(save_every, 1)
        self.multihost = multihost
        if multihost is None or multihost.coordinator_only_io():
            os.makedirs(directory, exist_ok=True)
            self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """Remove ``.ckpt-*`` debris a crashed writer left behind (a temp dir
        never renamed into place is by definition incomplete)."""
        for name in os.listdir(self.directory):
            if name.startswith(TMP_PREFIX):
                stale = os.path.join(self.directory, name)
                logger.warning("removing stale checkpoint temp dir %s", stale)
                shutil.rmtree(stale, ignore_errors=True)

    # ------------------------------------------------------------------
    def _step_dirs(self) -> List[Tuple[int, str]]:
        out = []
        if not os.path.isdir(self.directory):
            # a non-coordinator host with a per-host (non-shared) checkpoint
            # dir that never wrote: no steps, not an error — the collective
            # min in restore() settles what the JOB can resume
            return out
        for name in os.listdir(self.directory):
            if name.startswith(STEP_PREFIX):
                try:
                    step = int(name[len(STEP_PREFIX):])
                except ValueError:
                    continue
                path = os.path.join(self.directory, name)
                if os.path.exists(os.path.join(path, META_FILE)):
                    out.append((step, path))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        dirs = self._step_dirs()
        return dirs[-1][0] if dirs else None

    # ------------------------------------------------------------------
    def _prepare(self, state: CheckpointState) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        """Host snapshot of ``state``: (flat arrays, meta). COLLECTIVE under
        multihost (sharded leaves allgather) — every host must call this
        together; only the commit that follows is coordinator-only."""
        arrays, structure = _flatten_state(
            {"params": state.params, "scores": state.scores, "total": state.total_scores}
        )
        partial_meta = None
        if state.partial is not None:
            partial_meta = state.partial.get("meta") or {}
            for k, v in (state.partial.get("arrays") or {}).items():
                arrays[f"partial.{k}"] = np.asarray(v)
        meta = {
            "step": state.step,
            "fingerprint": self.run_fingerprint,
            "structure": structure,
            "objective_history": state.objective_history,
            "validation_history": state.validation_history,
            # checksums are stamped in _commit: hashing the full model is
            # commit work — coordinator-only, and on the background thread
            # under async saves — not snapshot work every host pays
            "partial": partial_meta,
        }
        return arrays, meta

    def _commit(self, step: int, arrays: Dict[str, np.ndarray], meta: Dict[str, Any]) -> str:
        """Durably write one prepared snapshot (retry + atomic rename) and
        retire old steps. Pure host I/O — safe on a background thread."""
        from photon_ml_tpu import resilience
        from photon_ml_tpu.resilience import faults

        final_dir = os.path.join(self.directory, f"{STEP_PREFIX}{step}")
        meta = dict(meta, checksums=_checksums(arrays))

        def write_once() -> None:
            """One atomic write attempt: fresh temp dir -> rename. The temp
            dir is removed on ANY failure (try/finally, not a broad except)
            so a retry never inherits partial state and a crashed process
            leaves at most an ignorable .ckpt-* directory behind."""
            faults.inject("io.checkpoint_write", step=step, path=final_dir)
            tmp_dir = tempfile.mkdtemp(prefix=TMP_PREFIX, dir=self.directory)
            renamed = False
            try:
                np.savez(os.path.join(tmp_dir, ARRAYS_FILE), **arrays)
                with open(os.path.join(tmp_dir, META_FILE), "w") as f:
                    json.dump(meta, f)
                if os.path.exists(final_dir):
                    shutil.rmtree(final_dir)
                os.replace(tmp_dir, final_dir)
                renamed = True
            finally:
                if not renamed:
                    shutil.rmtree(tmp_dir, ignore_errors=True)

        resilience.call_with_retry(
            write_once,
            resilience.current_config().io_policy,
            describe=f"checkpoint step {step}",
            on_retry=lambda a, e, d: logger.warning(
                "retrying checkpoint step %d (attempt %d): %s", step, a + 2, e
            ),
        )
        self._retire()
        return final_dir

    def save(self, state: CheckpointState) -> str:
        # collective: every host participates in the sharded-leaf all-gather
        arrays, meta = self._prepare(state)
        if self.multihost is not None and not self.multihost.coordinator_only_io():
            # non-coordinators just fence the coordinator's write
            self.multihost.barrier("ckpt-write")
            return os.path.join(self.directory, f"{STEP_PREFIX}{state.step}")
        try:
            final_dir = self._commit(state.step, arrays, meta)
        finally:
            # barrier even when the write fails: non-coordinators are already
            # blocked in their "ckpt-write" barrier — skipping ours would
            # deadlock the whole job until the heartbeat timeout instead of
            # surfacing the coordinator's exception
            if self.multihost is not None:
                self.multihost.barrier("ckpt-write")
        return final_dir

    def wait(self) -> None:
        """Synchronous checkpointer: every save already committed before
        returning — the fence is a no-op (the async wrapper overrides)."""

    def _retire(self) -> None:
        dirs = self._step_dirs()
        for _, path in dirs[: -self.keep]:
            shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------------
    def restore(
        self,
        params_template: Dict[str, Any],
        scores_template: Dict[str, Any],
        total_template: Any,
        max_step: Optional[int] = None,
        agree: bool = True,
    ) -> Optional[CheckpointState]:
        """Load the newest complete checkpoint; None when there is none.

        Crash debris is tolerated: stale ``.ckpt-*`` temp dirs are never
        candidates (only ``step-*`` dirs with a meta file are), and a
        checkpoint whose ``arrays.npz`` is truncated, undecodable (a crash
        on a non-atomic filesystem), or failing its recorded SHA-256
        checksums (silent bit-rot) is skipped with a warning, falling back
        to the next-newest complete step. Reads retry under the active I/O
        policy. Templates supply pytree structure (restored arrays replace
        leaves); a fingerprint mismatch raises instead of silently resuming
        a different run.

        ``max_step`` caps the step considered (newer steps are ignored, not
        deleted). Under multihost (with ``agree=True``, the default) the cap
        defaults to the COLLECTIVE MIN of every host's latest step — no
        host restores a step another host failed to commit; when any host
        has nothing, the whole job starts fresh. The agreement is a
        COLLECTIVE: every host must call restore together (the coordinate-
        descent resume path does). A coordinator-only read-back must pass
        ``agree=False`` or it deadlocks the allgather.
        """
        from photon_ml_tpu import resilience

        if agree and max_step is None and self.multihost is not None:
            max_step = self.multihost.agree_restore_step(self.latest_step())
            if max_step is None:
                return None

        policy = resilience.current_config().io_policy
        for step, path in reversed(self._step_dirs()):
            if max_step is not None and step > max_step:
                continue
            def load_meta() -> dict:
                with open(os.path.join(path, META_FILE)) as f:
                    return json.load(f)

            try:
                meta = resilience.call_with_retry(
                    load_meta, policy, describe=f"read {path} meta"
                )
            except (resilience.RetryError, ValueError) as e:
                logger.warning("skipping unreadable checkpoint %s: %s", path, e)
                continue
            if meta.get("fingerprint") != self.run_fingerprint:
                raise ValueError(
                    f"checkpoint fingerprint {meta.get('fingerprint')!r} does not match "
                    f"this run ({self.run_fingerprint!r}); refusing to resume"
                )

            def load_arrays() -> Dict[str, np.ndarray]:
                with np.load(os.path.join(path, ARRAYS_FILE)) as npz:
                    return {k: npz[k] for k in npz.files}

            try:
                arrays = resilience.call_with_retry(
                    load_arrays, policy, describe=f"read {path} arrays"
                )
                if meta.get("checksums"):
                    # pre-checksum checkpoints (older runs) skip verification
                    _verify_checksums(arrays, meta["checksums"], path)
            except (resilience.RetryError, zipfile.BadZipFile, ValueError, EOFError) as e:
                # truncated/corrupt/bit-rotten arrays.npz: this step is
                # unusable — fall back to the previous intact one
                logger.warning("skipping corrupt checkpoint %s: %s", path, e)
                continue
            try:
                restored = _unflatten_state(
                    {
                        "params": params_template,
                        "scores": scores_template,
                        "total": total_template,
                    },
                    arrays,
                    meta["structure"],
                )
            except CheckpointRefError as e:
                logger.warning("skipping unrestorable checkpoint %s: %s", path, e)
                continue
            partial = None
            if meta.get("partial") is not None:
                partial = {
                    "meta": meta["partial"],
                    "arrays": {
                        k[len("partial."):]: v
                        for k, v in arrays.items()
                        if k.startswith("partial.")
                    },
                }
            return CheckpointState(
                step=int(meta["step"]),
                params=restored["params"],
                scores=restored["scores"],
                total_scores=restored["total"],
                objective_history=list(meta["objective_history"]),
                validation_history=list(meta["validation_history"]),
                partial=partial,
            )
        return None
