"""The delta planner: classify files, coordinates, and entity blocks.

Daily retrains see a file set that is mostly yesterday's file set. The
planner diffs the new inputs against the prior run's
:class:`~photon_ml_tpu.retrain.manifest.RetrainManifest` with the SAME
identity the tensor cache uses (path, size, mtime_ns stat tokens) and
classifies:

  * every **file**: ``unchanged | changed | new | removed``;
  * every **coordinate**: ``unchanged`` (identical inputs + config — the
    prior coefficients ARE the result, carried forward bitwise without
    solving), ``dirty`` (data or config moved — re-solve, warm-started
    from the prior model), or ``new`` (no prior — cold solve);
  * every **entity block** of a dirty streaming random-effect coordinate:
    the prior run's blocking is PINNED (surviving entities keep their
    block; new entities append as new blocks), so a block whose entity
    membership is intact and touches no dirty entity is ``unchanged`` —
    its on-disk payload is reused as-is (only the global row selector is
    recomputed) and its solve is skipped — while ``dirty``/``new`` blocks
    rebuild from the new rows and re-solve warm.

Dirty entities are found by reading ONLY the changed/new files' id columns
(:func:`photon_ml_tpu.io.avro_data.collect_entity_ids`) — cost scales with
the delta, not the dataset. Correctness guard for block reuse: an entity
can lose rows from a changed file without appearing in its new content, so
a candidate-unchanged block is additionally verified by row COUNT in the
new row space (any mismatch demotes it to a rebuilt dirty block — a wrong
warm result is never possible, at worst a wasted rebuild). Every
adjustment is a recorded :class:`~photon_ml_tpu.compile.plan.PlanDecision`
(the PR-12 audit discipline), logged by the driver.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from photon_ml_tpu.compile.plan import PlanDecision

__all__ = [
    "BlockDelta",
    "CoordinateDelta",
    "DeltaPlan",
    "FileDelta",
    "build_delta_streaming_manifest",
    "diff_files",
    "dirty_set_digest",
    "plan_delta",
    "probe_dirty_entities",
]

UNCHANGED = "unchanged"
DIRTY = "dirty"
NEW = "new"


@dataclasses.dataclass(frozen=True)
class FileDelta:
    """Input-file classification vs the prior run (absolute paths)."""

    unchanged: Tuple[str, ...]
    changed: Tuple[str, ...]
    new: Tuple[str, ...]
    removed: Tuple[str, ...]

    @property
    def clean(self) -> bool:
        return not (self.changed or self.new or self.removed)

    def describe(self) -> str:
        return (
            f"{len(self.unchanged)} unchanged / {len(self.changed)} changed "
            f"/ {len(self.new)} new / {len(self.removed)} removed"
        )


@dataclasses.dataclass(frozen=True)
class BlockDelta:
    """One streaming entity block's classification in the delta build."""

    index: int
    status: str  # unchanged | dirty | new
    prior_index: Optional[int] = None
    reason: str = ""


@dataclasses.dataclass(frozen=True)
class CoordinateDelta:
    name: str
    status: str  # unchanged | dirty | new
    reason: str = ""


@dataclasses.dataclass
class DeltaPlan:
    """The resolved retrain plan: what skips, what warms, what runs cold."""

    files: FileDelta
    coordinates: Dict[str, CoordinateDelta]
    # True: inputs, config, and grid are identical to the prior run — the
    # prior model IS this run's result (the driver short-circuits training
    # and re-exports it bitwise)
    short_circuit: bool
    decisions: Tuple[PlanDecision, ...] = ()
    # filled by probe_dirty_entities once the changed files' ids are read
    dirty_entities: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)

    def frozen_coordinates(self) -> Set[str]:
        return {
            n for n, c in self.coordinates.items() if c.status == UNCHANGED
        }

    def describe_decisions(self) -> Tuple[str, ...]:
        return tuple(d.describe() for d in self.decisions)


def diff_files(prior_stats: Dict[str, tuple], new_files: List[str]) -> FileDelta:
    """Stat-token diff (same identity as tensor-cache keys): a file is
    unchanged iff path, size, AND mtime_ns all match the prior record."""
    unchanged, changed, new = [], [], []
    seen = set()
    for path in sorted(new_files):
        ap = os.path.abspath(path)
        seen.add(ap)
        st = os.stat(ap)
        prior = prior_stats.get(ap)
        if prior is None:
            new.append(ap)
        elif prior == (int(st.st_size), int(st.st_mtime_ns)):
            unchanged.append(ap)
        else:
            changed.append(ap)
    removed = sorted(p for p in prior_stats if p not in seen)
    return FileDelta(
        unchanged=tuple(unchanged), changed=tuple(changed),
        new=tuple(new), removed=tuple(removed),
    )


def plan_delta(
    prior,
    new_files: List[str],
    *,
    task: str,
    updating_sequence: List[str],
    ingest_inputs: Dict[str, object],
    combo_configs: Optional[Dict[str, str]] = None,
    eval_identity: Optional[Dict[str, object]] = None,
) -> DeltaPlan:
    """Coordinate-level classification (block-level happens later, inside
    the dirty streaming build, because it needs the new ingest).

    ``combo_configs`` maps coordinate name -> repr of its optimization
    config when the run trains a SINGLE grid combo; pass None for a
    multi-combo grid (freezing is then off — each combo trains its own
    lambda, warm-started — but warm starts stay on).

    ``eval_identity`` (validation file stats + evaluator specs) gates the
    short-circuit ONLY: a changed validation side must re-score — with
    every coordinate still frozen, so the re-score run solves nothing.
    """
    files = diff_files(prior.stat_by_path(), new_files)
    decisions: List[PlanDecision] = []
    identical_env = (
        files.clean
        and task == prior.task
        and ingest_inputs == prior.ingest_inputs
    )
    if not files.clean:
        decisions.append(PlanDecision(
            "retrain", "composed",
            f"input delta: {files.describe()} — changed coordinates "
            "re-solve warm-started from the prior model",
        ))
    if files.clean and ingest_inputs != prior.ingest_inputs:
        decisions.append(PlanDecision(
            "retrain", "pinned",
            "inputs unchanged but the ingest configuration moved — "
            "coefficients warm-start, nothing freezes",
        ))
    if files.clean and task != prior.task:
        decisions.append(PlanDecision(
            "retrain", "pinned",
            f"task changed {prior.task} -> {task}: the prior optimum is a "
            "warm start for a different loss, not a reusable result",
        ))

    coords: Dict[str, CoordinateDelta] = {}
    for name in updating_sequence:
        rec = prior.coordinates.get(name)
        if rec is None:
            coords[name] = CoordinateDelta(
                name, NEW, "coordinate absent from the prior run — cold solve"
            )
            decisions.append(PlanDecision(
                "retrain", "composed",
                f"coordinate {name!r} is new — cold solve",
            ))
            continue
        if not identical_env:
            coords[name] = CoordinateDelta(
                name, DIRTY, "inputs or configuration changed — warm re-solve"
            )
            continue
        cfg = None if combo_configs is None else combo_configs.get(name, "")
        if cfg is not None and cfg == rec.opt_config:
            coords[name] = CoordinateDelta(
                name, UNCHANGED,
                "inputs + config identical to the prior run — prior "
                "coefficients carried forward bitwise, solve skipped",
            )
            decisions.append(PlanDecision(
                "retrain", "subsumed",
                f"coordinate {name!r} unchanged — skipping its solve "
                "(prior coefficients bitwise)",
            ))
        else:
            coords[name] = CoordinateDelta(
                name, DIRTY,
                "optimization grid differs from the prior selected combo — "
                "warm re-solve",
            )

    eval_same = (eval_identity or {}) == (getattr(prior, "eval_identity", {}) or {})
    short = (
        identical_env
        and eval_same
        and list(updating_sequence) == list(prior.updating_sequence)
        and all(c.status == UNCHANGED for c in coords.values())
    )
    if identical_env and not eval_same:
        decisions.append(PlanDecision(
            "retrain", "composed",
            "training side unchanged but the validation inputs/evaluators "
            "moved — re-scoring with every solve still skipped (frozen "
            "coordinates), no wholesale short-circuit",
        ))
    if short:
        decisions.append(PlanDecision(
            "retrain", "subsumed",
            "nothing changed — reusing the prior model wholesale "
            "(0 solves, 0 compiles)",
        ))
    return DeltaPlan(
        files=files, coordinates=coords, short_circuit=short,
        decisions=tuple(decisions),
    )


def probe_dirty_entities(
    files: FileDelta, id_types: List[str]
) -> Dict[str, Set[str]]:
    """Raw entity ids whose data moved: everything appearing in changed or
    new files' CURRENT content. (Entities that only LOST rows from a
    changed file are caught by the per-block row-count guard in the delta
    build — see module doc.)"""
    from photon_ml_tpu.io.avro_data import collect_entity_ids

    touched = list(files.changed) + list(files.new)
    if not touched:
        return {t: set() for t in id_types}
    return collect_entity_ids(touched, id_types)


def dirty_set_digest(dirty_raw: Set[str]) -> str:
    """Stable digest of a dirty-entity set — part of the delta build's
    tensor-cache key (a different dirty set classifies blocks differently,
    so it must address a different cache entry)."""
    h = hashlib.sha256()
    for r in sorted(dirty_raw):
        h.update(r.encode())
        h.update(b"\x00")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# delta streaming-block build
# ---------------------------------------------------------------------------


def _pinned_blocking(
    prior_manifest, vocab: List[str], counts: np.ndarray,
    dirty_raw: Set[str],
) -> Tuple[List[Tuple[np.ndarray, str, Optional[int], str]], np.ndarray, List[str]]:
    """Prior blocking pinned onto the new vocab: per prior block, the
    surviving entities (sorted new dense ids) + classification; returns
    (blocks, assigned mask, degrade reasons). Raw-id order and sorted-dense
    order agree across runs because both vocabs sort raw ids. A prior
    block whose file is unreadable (lost cache entry) contributes no pin —
    its entities fall through to the fresh-blocking leftover and rebuild
    cold, with the reason recorded."""
    raw_to_new = {r: i for i, r in enumerate(vocab)}
    assigned = np.zeros(len(vocab), bool)
    out = []
    degraded: List[str] = []
    for bi in range(len(prior_manifest.blocks)):
        try:
            meta = prior_manifest.load_block_meta(bi)
        except (OSError, KeyError, ValueError) as e:
            degraded.append(
                f"prior block {bi} unreadable ({type(e).__name__}: {e})"
            )
            continue
        prior_raws = [prior_manifest.vocab[v] for v in meta.entity_ids]
        keep = [
            raw_to_new[r]
            for r in prior_raws
            if r in raw_to_new and counts[raw_to_new[r]] > 0
        ]
        if not keep:
            continue  # every entity of this block left the dataset
        ent = np.sort(np.asarray(keep, np.int64))
        assigned[ent] = True
        if len(keep) != len(prior_raws):
            out.append((ent, DIRTY, bi, "entity membership changed"))
        elif any(r in dirty_raw for r in prior_raws):
            out.append((ent, DIRTY, bi, "contains dirty entities"))
        else:
            out.append((ent, UNCHANGED, bi, ""))
    return out, assigned, degraded


def build_delta_streaming_manifest(
    data,
    config,
    out_dir: str,
    prior_manifest,
    dirty_raw: Set[str],
    *,
    bucketer=None,
    block_entities: Optional[int] = None,
    memory_budget_bytes: Optional[int] = None,
    tensor_cache=None,
    cache_key: Optional[str] = None,
):
    """Entity blocks for the NEW data with the prior run's blocking pinned.

    Returns ``(StreamingREManifest, [BlockDelta...])``. Unchanged blocks'
    payload arrays are copied from the prior block files as-is (only
    ``row_sel`` — global row positions — and ``entity_ids`` — dense vocab
    ids — are rewritten for the new row/vocab spaces); dirty and new
    blocks build through the ordinary
    :func:`~photon_ml_tpu.algorithm.streaming_random_effect.
    build_block_payload` path. Any failure to reuse a prior block (file
    vanished, row count moved, ladder changed) demotes it to a rebuilt
    dirty block with a recorded reason — never a wrong warm payload.

    With ``tensor_cache``/``cache_key`` the built directory commits as a
    cache entry exactly like the cold builder; per-block classifications
    ride in the manifest metas (``delta`` key), so a cache hit recovers
    them without rebuilding. The caller's key must include the prior-run
    identity and the dirty-set digest — this function trusts the key.
    """
    from photon_ml_tpu.algorithm.streaming_random_effect import (
        StreamingREManifest,
        build_block_payload,
        plan_entity_blocks,
        write_block_file,
        write_streaming_manifest_json,
        _DATASET_FIELDS,
    )
    from photon_ml_tpu.compile import resolve_bucketer

    bucketer = resolve_bucketer(bucketer)
    spec = f"{bucketer.base}:{bucketer.growth:g}" if bucketer else None

    if tensor_cache is not None and cache_key is not None:
        hit = tensor_cache.get_dir(cache_key)
        if hit is not None:
            manifest = StreamingREManifest.load(hit)
            deltas = [
                BlockDelta(i, b.get("delta", DIRTY), b.get("delta_prior"),
                           b.get("delta_reason", ""))
                for i, b in enumerate(manifest.blocks)
            ]
            return manifest, deltas

    re_id = config.random_effect_id
    ids = data.ids[re_id]
    vocab = data.id_vocabs[re_id]
    counts = np.bincount(ids, minlength=len(vocab))
    # ONE fresh-blocking policy (incl. the either-or sizing default),
    # shared by the leftover planning below and the budget-outgrown
    # re-block path inside the build
    fresh_block_kw = dict(
        global_dim=data.shards[config.feature_shard_id].dim,
        active_upper_bound=config.active_upper_bound,
        block_entities=(
            block_entities
            if (block_entities is not None) != (memory_budget_bytes is not None)
            else 1024
        ),
        memory_budget_bytes=memory_budget_bytes,
    )

    plan: List[Tuple[np.ndarray, str, Optional[int], str]] = []
    degraded: List[str] = []
    if spec == prior_manifest.ladder:
        pinned, assigned, degraded = _pinned_blocking(
            prior_manifest, vocab, counts, dirty_raw
        )
        plan.extend(pinned)
        leftover_counts = np.where(assigned, 0, counts)
    else:
        # ladder change reshapes every padded payload — nothing reuses;
        # classify everything dirty through a fresh blocking
        assigned = np.zeros(len(vocab), bool)
        leftover_counts = counts
    if leftover_counts.any():
        fresh = plan_entity_blocks(leftover_counts, **fresh_block_kw)
        if spec != prior_manifest.ladder:
            status, reason = DIRTY, "shape ladder changed — full rebuild"
        elif degraded:
            # entities orphaned by unreadable prior blocks rebuild cold
            status, reason = DIRTY, "; ".join(degraded)
        else:
            status, reason = NEW, ""
        plan.extend((ent, status, None, reason) for ent in fresh)

    def _build(tmp: str):
        metas = []
        deltas: List[BlockDelta] = []
        idx = 0

        def _emit(payload, st, pi, rsn):
            nonlocal idx
            meta = write_block_file(tmp, f"block-{idx:05d}.npz", payload)
            meta["delta"] = st
            meta["delta_prior"] = pi
            meta["delta_reason"] = rsn
            metas.append(meta)
            deltas.append(BlockDelta(idx, st, pi, rsn))
            idx += 1

        for ent, status, prior_i, reason in plan:
            if status == UNCHANGED:
                payload, why = _reuse_prior_payload(
                    prior_manifest, prior_i, ids, ent, _DATASET_FIELDS
                )
                if payload is not None:
                    _emit(payload, UNCHANGED, prior_i, "")
                    del payload
                    continue
                status, reason = DIRTY, why  # demoted: never a stale payload
            try:
                payload = build_block_payload(
                    data, config, ent, bucketer=bucketer,
                    memory_budget_bytes=memory_budget_bytes,
                    label=f"delta block {idx}",
                )
            except ValueError as e:
                if prior_i is None:
                    raise  # fresh blocks keep the cold builder's contract
                # a pinned block's data GREW past the memory budget (the
                # steady state of daily growth): re-block its entities
                # fresh under the budget instead of failing a retrain a
                # cold run of the same config would survive
                sub_counts = np.zeros_like(counts)
                sub_counts[ent] = counts[ent]
                for sub in plan_entity_blocks(sub_counts, **fresh_block_kw):
                    _emit(
                        build_block_payload(
                            data, config, sub, bucketer=bucketer,
                            memory_budget_bytes=memory_budget_bytes,
                            label=f"delta block {idx}",
                        ),
                        DIRTY, prior_i,
                        f"prior block outgrew the budget ({e}) — re-blocked",
                    )
                continue
            _emit(payload, status, prior_i, reason)
            del payload
        write_streaming_manifest_json(
            tmp, metas,
            num_rows=int(data.num_rows),
            global_dim=int(data.shards[config.feature_shard_id].dim),
            vocab=list(vocab),
            random_effect_id=re_id,
            feature_shard_id=config.feature_shard_id,
            ladder=spec,
        )
        return deltas

    if tensor_cache is not None and cache_key is not None:
        from photon_ml_tpu.resilience import RetryError

        holder: List[List[BlockDelta]] = []
        try:
            entry = tensor_cache.build_dir(
                cache_key, lambda tmp: holder.append(_build(tmp))
            )
            return StreamingREManifest.load(entry), holder[0]
        except RetryError:
            pass  # cache unusable: fall through to the plain build
    os.makedirs(out_dir, exist_ok=True)
    deltas = _build(out_dir)
    return StreamingREManifest.load(out_dir), deltas


def _reuse_prior_payload(
    prior_manifest, prior_i: int, ids: np.ndarray, ent: np.ndarray,
    dataset_fields,
) -> Tuple[Optional[dict], str]:
    """The prior block's payload rewritten into the new row/vocab spaces,
    or (None, reason) when reuse is unsafe. The block's rows all live in
    unchanged files (no member is dirty), so the new row selector aligns
    element-wise with the prior one whenever the COUNT matches — a count
    mismatch means rows were silently lost (e.g. an entity dropped from a
    changed file without appearing in its new content) and the block must
    rebuild."""
    try:
        z = np.load(os.path.join(
            prior_manifest.dir, prior_manifest.blocks[prior_i]["file"]
        ))
        new_row_sel = np.nonzero(np.isin(ids, ent))[0]
        if len(new_row_sel) != len(z["row_sel"]):
            return None, (
                f"row count moved ({len(z['row_sel'])} -> "
                f"{len(new_row_sel)}) — rows left a changed file"
            )
        payload = {f: np.asarray(z[f]) for f in dataset_fields}
        payload["row_sel"] = new_row_sel.astype(np.int64)
        payload["entity_ids"] = np.asarray(ent, np.int64)
        payload["dense_ids"] = np.asarray(z["dense_ids"])
        return payload, ""
    except (OSError, KeyError, ValueError) as e:
        return None, f"prior block unreadable ({type(e).__name__}: {e})"
