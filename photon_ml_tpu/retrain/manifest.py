"""The ``retrain.json`` record one training run leaves for the next.

Every GAME training run writes this next to its saved models (atomic
tmp+rename, like every other commit in the repo). It captures the run's
IDENTITY in the same content-addressed vocabulary the tensor cache uses —
source-file stat tokens (:func:`photon_ml_tpu.io.tensor_cache.
file_stat_token`), the ingest-config inputs and digest, and per-coordinate
cache keys / streaming-manifest locations — plus the model it produced, so
the next run's delta planner (:mod:`photon_ml_tpu.retrain.delta`) can
answer "what changed since yesterday?" from stat calls and one small JSON
read, without touching the data.

Reading the PRIOR run's manifest is the delta loop's single point of
trust, so it carries the ``retrain.delta_plan`` fault site: an injected or
real corruption surfaces as an exception the driver catches and records as
a cold run — a broken prior must cost a cold retrain, never produce a
wrong warm one.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from photon_ml_tpu.resilience import faults

__all__ = [
    "MANIFEST_FORMAT",
    "RETRAIN_MANIFEST",
    "CoordinateRecord",
    "RetrainManifest",
    "load_prior_manifest",
]

RETRAIN_MANIFEST = "retrain.json"
MANIFEST_FORMAT = 1


@dataclasses.dataclass
class CoordinateRecord:
    """One coordinate's identity in the prior run.

    ``kind`` is ``"fixed" | "random" | "streaming_random" | "factored"``.
    ``opt_config`` is the repr of the SELECTED combo's optimization config
    (lambda, optimizer, ...): a config change means the prior coefficients
    are a warm start, not a reusable result. ``streaming_manifest_dir``
    points at the durable entity-block layout the delta build pins its
    blocking to (may live inside a shared tensor-cache entry)."""

    kind: str
    opt_config: str = ""
    cache_key: Optional[str] = None
    streaming_manifest_dir: Optional[str] = None
    # the entity-shard plan version the streaming layout was built/last
    # re-based under (elastic re-sharding, parallel/elastic.py); 1 for
    # single-host layouts. A future multihost delta retrain compares it
    # against the live plan so topology drift is a recorded re-plan, not
    # a silent blanket rebuild.
    shard_plan_version: int = 1
    # the coordinate's convergence ledger at the end of the run
    # (ConvergenceLedger.to_json(), optim/convergence.py): per-block
    # gradient-norm scores and visit/skip counts. A warm delta retrain
    # seeds the next run's adaptive schedule from it so importance
    # ordering survives across runs, not just across epochs. Optional and
    # never load-bearing — a missing/old record just starts cold.
    convergence_ledger: Optional[dict] = None


@dataclasses.dataclass
class RetrainManifest:
    """Everything the next run's planner needs about this run."""

    output_dir: str
    model_dir: str  # the saved best model (model_io layout)
    task: str
    file_stats: List[list]  # [path, size, mtime_ns] per training input
    # config that determines the ingest OUTPUT given the input files,
    # known BEFORE feature maps exist (sections, intercepts, id types,
    # ladder, offheap dir): the planner's cheap pre-ingest equality check
    ingest_inputs: Dict[str, object]
    # digest of the FULL ingest cache config (incl. index-map digests,
    # known only after feature maps build): gates block-level reuse — a
    # feature-space change shifts every gather index, so reuse is off
    ingest_digest: str
    updating_sequence: List[str]
    coordinates: Dict[str, CoordinateRecord]
    # the whole-set ingest tensor-cache key (cache hygiene: the next delta
    # run invalidates it once superseded — it can never hit again)
    data_cache_key: Optional[str] = None
    # validation-side identity (validation file stats + evaluator specs):
    # gates the SHORT-CIRCUIT only — a changed validation set must re-score
    # even when training has nothing to do (coordinate freezing still
    # applies, so the re-score run skips every solve)
    eval_identity: Dict[str, object] = dataclasses.field(default_factory=dict)
    # --plan auto: the run's cost model (compile/cost.py to_json) rides
    # along so warm starts plan from realized costs; None when planning
    # was off or the run recorded nothing (priors stay in force)
    cost_model: Optional[dict] = None
    format: int = MANIFEST_FORMAT

    # ------------------------------------------------------------------
    def save(self, directory: str) -> str:
        path = os.path.join(directory, RETRAIN_MANIFEST)
        payload = dataclasses.asdict(self)
        if payload.get("cost_model") is None:
            # --plan off leaves the manifest bytes exactly as before the
            # planner existed (the off mode's bitwise-identity guarantee)
            payload.pop("cost_model", None)
        with open(path + ".tmp", "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(path + ".tmp", path)
        return path

    @classmethod
    def load(cls, directory: str) -> "RetrainManifest":
        with open(os.path.join(directory, RETRAIN_MANIFEST)) as f:
            raw = json.load(f)
        if int(raw.get("format", -1)) != MANIFEST_FORMAT:
            raise ValueError(
                f"retrain manifest format {raw.get('format')!r} != "
                f"{MANIFEST_FORMAT} — prior run predates/postdates this "
                "planner; retrain cold"
            )
        coords = {
            name: CoordinateRecord(**rec)
            for name, rec in raw.pop("coordinates").items()
        }
        return cls(coordinates=coords, **raw)

    def stat_by_path(self) -> Dict[str, tuple]:
        return {p: (int(size), int(mtime)) for p, size, mtime in self.file_stats}


def load_prior_manifest(prior_dir: str) -> RetrainManifest:
    """The prior run's manifest from its output dir (``--warm-start-from``).

    Carries the ``retrain.delta_plan`` fault site and VALIDATES the model
    reference: a manifest whose saved model has since vanished is as
    useless as a corrupt one. Any failure here raises — the driver catches,
    records the cold-degrade decision, and trains cold."""
    faults.inject("retrain.delta_plan", prior_dir=prior_dir)
    manifest = RetrainManifest.load(prior_dir)
    if not os.path.isdir(manifest.model_dir):
        raise FileNotFoundError(
            f"prior retrain manifest at {prior_dir} references model dir "
            f"{manifest.model_dir}, which no longer exists"
        )
    return manifest
