"""Warm-start coefficient builders from a saved GAME model.

The saved model (io/model_io, reference Avro layout) stores per-entity
coefficient rows in the GLOBAL feature space keyed by raw entity id and
feature NAME — the only representation stable across runs (dense vocab
ids and local projection spaces are run-relative). These builders gather
those rows back into each coordinate's solve space:

  * fixed effect: a (D,) vector aligned to the CURRENT index map by name;
  * in-memory random effect: an (E, D_loc) stack gathered through the new
    dataset's per-entity ``local_to_global`` projection;
  * streaming random effect: a seeded
    :class:`~photon_ml_tpu.algorithm.streaming_random_effect.
    SpilledREState` (one ``coefs-*.npy`` per block).

Exactness: export writes each float32 coefficient as a double and reload
narrows it back — an exact round trip — and the local->global scatter
(:func:`~photon_ml_tpu.algorithm.random_effect.global_coefficients`)
writes disjoint positions per entity, so gathering back through the same
``local_to_global`` reproduces the prior local coefficients BITWISE for
any entity whose projection is unchanged. That is what lets an unchanged
block skip its solve and still export bitwise-identical rows.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.io import model_io
from photon_ml_tpu.types import real_dtype

__all__ = [
    "bucketed_random_effect_init",
    "dense_random_effect_init",
    "fixed_effect_init",
    "random_effect_entity_means",
    "seed_perhost_spilled_state",
    "seed_spilled_state",
]


def fixed_effect_init(model_dir: str, name: str, index_map) -> Optional[np.ndarray]:
    """The prior fixed-effect vector aligned to the CURRENT index map by
    feature name (new features init at 0; dropped features drop), or None
    when the prior model has no such coordinate."""
    base = os.path.join(model_dir, model_io.FIXED_EFFECT, name)
    if not os.path.isdir(base):
        return None
    means, _, _, _ = model_io.load_fixed_effect(model_dir, name, index_map)
    return np.asarray(means, real_dtype())


def random_effect_entity_means(
    model_dir: str, name: str, index_map
) -> Optional[Dict[str, np.ndarray]]:
    """Prior per-entity global-space rows keyed by raw entity id, aligned
    to the CURRENT index map by name; None when the coordinate is absent
    (or is a factored model, whose latent state does not round-trip
    through dense rows — factored coordinates retrain cold)."""
    base = os.path.join(model_dir, model_io.RANDOM_EFFECT, name)
    if not os.path.isdir(base):
        return None
    if model_io.is_factored_random_effect(model_dir, name):
        return None
    means, _, _, _ = model_io.load_random_effect(model_dir, name, index_map)
    return {k: np.asarray(v, real_dtype()) for k, v in means.items()}


def _gather_local(
    row_global: np.ndarray, local_to_global: np.ndarray
) -> np.ndarray:
    """One entity's global-space row gathered into its local solve space
    (-1 projection slots stay 0)."""
    valid = local_to_global >= 0
    out = np.zeros(local_to_global.shape, row_global.dtype)
    out[valid] = row_global[local_to_global[valid]]
    return out


def dense_random_effect_init(
    entity_means: Dict[str, np.ndarray],
    *,
    vocab: List[str],
    pos_of_vocab: np.ndarray,
    local_to_global: np.ndarray,
) -> np.ndarray:
    """(E, D_loc) warm stack for an in-memory random-effect coordinate:
    every entity with a prior row gathers it through its own projection;
    entities new to the model start at 0 (the cold init)."""
    w = np.zeros(local_to_global.shape, real_dtype())
    for vi, raw in enumerate(vocab):
        p = int(pos_of_vocab[vi])
        if p >= 0 and raw in entity_means:
            w[p] = _gather_local(
                entity_means[raw].astype(real_dtype()), local_to_global[p]
            )
    return w


def bucketed_random_effect_init(
    entity_means: Dict[str, np.ndarray], bundle
) -> List[np.ndarray]:
    """Per-bucket warm coefficient stacks for a bucketed random-effect
    coordinate (one ``(E_b, D_loc)`` array per bucket of a
    :class:`~photon_ml_tpu.algorithm.bucketed_random_effect.
    BucketedDatasetBundle`, matching ``initial_coefficients()``'s shapes
    including ladder padding — padded rows stay 0, the cold init).

    Each bucket's prior rows gather through the bucket layout exactly like
    the export walks it (``vocab_position_maps``): bucket rows map dense
    bucket-local ids to tensor positions, dense ids map back to the run's
    vocab, and each positioned entity gathers its prior global row through
    its own ``local_to_global`` projection — so an unchanged entity's
    local coefficients reproduce BITWISE (the module-docstring argument)."""
    stacks: List[np.ndarray] = []
    for entity_ids, ds, dense_ids in zip(
        bundle.buckets, bundle.datasets, bundle.dense_ids
    ):
        # ladder-canonicalized buckets pad entity_pos with -1 rows beyond
        # the real rows dense_ids covers — slice to match (the same walk
        # as BucketedRandomEffectCoordinate.vocab_position_maps)
        entity_pos = np.asarray(ds.entity_pos)[: len(dense_ids)]
        known = entity_pos >= 0
        pos_of_dense = np.full(len(entity_ids), -1, np.int32)
        pos_of_dense[dense_ids[known]] = entity_pos[known]
        local_to_global = np.asarray(ds.local_to_global)
        w = np.zeros((int(ds.num_entities), int(ds.local_dim)), real_dtype())
        for d, vi in enumerate(entity_ids):
            p = int(pos_of_dense[d])
            if p < 0:
                continue
            raw = bundle.vocab[int(vi)]
            if raw in entity_means:
                w[p] = _gather_local(
                    entity_means[raw].astype(real_dtype()),
                    local_to_global[p],
                )
        stacks.append(w)
    return stacks


def seed_perhost_spilled_state(
    manifest, entity_means: Dict[str, np.ndarray], state_dir: str
):
    """The multihost twin of :func:`seed_spilled_state`: a
    :class:`~photon_ml_tpu.parallel.perhost_streaming.PerHostSpilledREState`
    under ``state_dir`` seeded from the prior model for THIS host's owned
    blocks only (files keyed by global block id, so the state survives an
    elastic re-plan). Same metadata-only walk, same bitwise guarantee for
    unchanged blocks; untouched blocks stay unwritten (zeros)."""
    from photon_ml_tpu.algorithm.streaming_random_effect import (
        _positions_of_dense,
    )
    from photon_ml_tpu.parallel.perhost_streaming import (
        PerHostSpilledREState,
    )

    shapes = [(b["num_entities"], b["local_dim"]) for b in manifest.blocks]
    state = PerHostSpilledREState(
        dir=state_dir, shapes=shapes,
        global_ids=[int(g) for g in manifest.global_block_ids],
        plan_version=int(getattr(manifest, "plan_version", 1)),
    )
    for i in range(len(manifest.blocks)):
        meta = manifest.load_block_meta(i)
        pos_of_dense = _positions_of_dense(meta)
        w = np.zeros(shapes[i], real_dtype())
        touched = False
        for j, vi in enumerate(meta.entity_ids):
            raw = manifest.vocab[vi]
            p = int(pos_of_dense[j])
            if p >= 0 and raw in entity_means:
                w[p] = _gather_local(
                    entity_means[raw].astype(real_dtype()),
                    np.asarray(meta.local_to_global[p]),
                )
                touched = True
        if touched:
            state.write(i, w)
    return state


def seed_spilled_state(
    manifest, entity_means: Dict[str, np.ndarray], state_dir: str
):
    """A :class:`SpilledREState` under ``state_dir`` seeded from the prior
    model, one ``coefs-*.npy`` per block of ``manifest`` (metadata-only:
    never loads a data slab). Blocks whose every entity carries a prior
    row — the unchanged blocks — hold the prior coefficients bitwise."""
    from photon_ml_tpu.algorithm.streaming_random_effect import (
        SpilledREState,
        _positions_of_dense,
    )

    shapes = [(b["num_entities"], b["local_dim"]) for b in manifest.blocks]
    state = SpilledREState(dir=state_dir, shapes=shapes)
    for i in range(len(manifest.blocks)):
        meta = manifest.load_block_meta(i)
        pos_of_dense = _positions_of_dense(meta)
        w = np.zeros(shapes[i], real_dtype())
        touched = False
        for j, vi in enumerate(meta.entity_ids):
            raw = manifest.vocab[vi]
            p = int(pos_of_dense[j])
            if p >= 0 and raw in entity_means:
                w[p] = _gather_local(
                    entity_means[raw].astype(real_dtype()),
                    np.asarray(meta.local_to_global[p]),
                )
                touched = True
        if touched:
            state.write(i, w)
        # untouched blocks stay unwritten: SpilledREState serves zeros
    return state
