"""Incremental delta retraining (the daily retrain->swap loop).

Production GLMix retrains daily on data that is mostly yesterday's data —
the per-member/per-item random effects change only where new events
arrived (GLMix, KDD'16), and Snap ML (arXiv:1803.06333) shows hierarchical
reuse of cached state is the dominant lever for GLM training throughput.
This package connects the repo's durable, content-addressed ingredients
(tensor-cache keys, streaming entity-block files, saved models, the warm
serve swap) into a loop that SKIPS unchanged work:

  * :mod:`~photon_ml_tpu.retrain.manifest` — the ``retrain.json`` record a
    training run leaves behind: source-file stat tokens, ingest-config
    identity, per-coordinate cache keys and streaming-manifest locations,
    and the saved model it produced. The next run's delta planner diffs
    against it.
  * :mod:`~photon_ml_tpu.retrain.delta` — the planner: classify every
    input file (``unchanged | changed | new | removed``), every coordinate
    (``unchanged | dirty | new``), and — inside a dirty streaming
    random-effect coordinate — every entity block, pinning the prior
    run's blocking so unchanged blocks are REUSED bitwise (payload arrays
    copied, solve skipped) while only dirty/new blocks rebuild and
    re-solve, warm-started from the prior model.
  * :mod:`~photon_ml_tpu.retrain.warm` — warm-start coefficient builders:
    a saved model's per-entity global-space rows gathered back into each
    coordinate's local solve space (bitwise round trip for unchanged
    entities).

Failure discipline: a corrupted prior manifest, a vanished prior model, or
a lost cache entry degrades to a RECORDED cold solve for the affected
coordinate/block (``retrain.delta_plan`` / ``io.cache_read`` fault sites,
chaos-covered) — never a wrong warm result.
"""

from photon_ml_tpu.retrain.delta import (
    BlockDelta,
    CoordinateDelta,
    DeltaPlan,
    FileDelta,
    build_delta_streaming_manifest,
    diff_files,
    dirty_set_digest,
    plan_delta,
    probe_dirty_entities,
)
from photon_ml_tpu.retrain.manifest import (
    RETRAIN_MANIFEST,
    RetrainManifest,
    load_prior_manifest,
)
from photon_ml_tpu.retrain.warm import (
    bucketed_random_effect_init,
    dense_random_effect_init,
    fixed_effect_init,
    random_effect_entity_means,
    seed_perhost_spilled_state,
    seed_spilled_state,
)

__all__ = [
    "BlockDelta",
    "CoordinateDelta",
    "DeltaPlan",
    "FileDelta",
    "RETRAIN_MANIFEST",
    "RetrainManifest",
    "bucketed_random_effect_init",
    "build_delta_streaming_manifest",
    "dense_random_effect_init",
    "diff_files",
    "dirty_set_digest",
    "fixed_effect_init",
    "load_prior_manifest",
    "plan_delta",
    "probe_dirty_entities",
    "random_effect_entity_means",
    "seed_perhost_spilled_state",
    "seed_spilled_state",
]
