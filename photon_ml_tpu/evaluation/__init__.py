from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    EvaluatorType,
    area_under_roc_curve,
    evaluator_for,
    precision_at_k,
    rmse,
)

__all__ = [
    "Evaluator",
    "EvaluatorType",
    "area_under_roc_curve",
    "evaluator_for",
    "precision_at_k",
    "rmse",
]
