"""Batch model evaluation: the full metric map per trained model.

Reference spec: Evaluation.scala:30-190 — score once with the mean function
(offset included), then compute the metrics applicable to the model family:

  regression facet   : MAE / MSE / RMSE
  binary classifier  : AUROC / AUPR / peak F1
  logistic + Poisson : per-datum log likelihood, and AIC with the
                       small-sample correction term
                       (effective params = |coef| > 1e-9)

Metric keys are string-identical to the reference so downstream consumers
(model selection, diagnostics, reports) interchange.

TPU-native: metrics are computed from dense (N,) score/label vectors via
sort/cumsum kernels on device — no RDD co-grouping.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.types import TaskType

if TYPE_CHECKING:  # pragma: no cover
    from photon_ml_tpu.ops.normalization import NormalizationContext

Array = jax.Array

MEAN_ABSOLUTE_ERROR = "Mean absolute error"
MEAN_SQUARE_ERROR = "Mean square error"
ROOT_MEAN_SQUARE_ERROR = "Root mean square error"
AREA_UNDER_PRECISION_RECALL = "Area under precision/recall"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = "Area under ROC"
PEAK_F1_SCORE = "Peak F1 score"
DATA_LOG_LIKELIHOOD = "Per-datum log likelihood"
AIKAKE_INFORMATION_CRITERION = "Aikake information criterion"
EPSILON = 1e-9

_REGRESSION_TASKS = (TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION)
_CLASSIFIER_TASKS = (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)


def _roc_pr_curves(scores: Array, labels: Array, weights: Optional[Array]):
    """Sorted-descending cumulative weighted TP/FP counts; weight-0 rows
    (padding) contribute nothing."""
    order = jnp.argsort(-scores)
    lab = labels[order]
    w = jnp.ones_like(lab) if weights is None else weights[order]
    tp = jnp.cumsum(w * lab)
    fp = jnp.cumsum(w * (1.0 - lab))
    return tp, fp


def area_under_pr(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """AUPR by trapezoidal integration of (recall, precision) points,
    anchored at (0, p(first point)) like Spark's BinaryClassificationMetrics."""
    tp, fp = _roc_pr_curves(scores, labels, weights)
    pos = jnp.maximum(tp[-1], 1.0)
    recall = tp / pos
    precision = tp / jnp.maximum(tp + fp, EPSILON)
    r = jnp.concatenate([jnp.zeros((1,)), recall])
    p = jnp.concatenate([precision[:1], precision])
    return jnp.sum((r[1:] - r[:-1]) * 0.5 * (p[1:] + p[:-1]))


def peak_f1(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """max_t F1(t) over all score thresholds."""
    tp, fp = _roc_pr_curves(scores, labels, weights)
    pos = jnp.maximum(tp[-1], 1.0)
    precision = tp / jnp.maximum(tp + fp, EPSILON)
    recall = tp / pos
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall, EPSILON)
    return jnp.max(f1)


def _wmean(v: Array, weights: Optional[Array]) -> Array:
    if weights is None:
        return jnp.mean(v)
    return jnp.sum(weights * v) / jnp.maximum(jnp.sum(weights), EPSILON)


def logistic_log_likelihood(
    mean_scores: Array, labels: Array, weights: Optional[Array] = None
) -> Array:
    """Per-datum average of y*log(p) + (1-y)*log(1-p), epsilon-clipped.

    Deviation from Evaluation.logisticRegressionLogLikelihood (:138-148):
    the reference clips log(1-p) to log1p(1-EPSILON) = +log(2), rewarding a
    confidently-wrong prediction; we clip symmetrically to log(EPSILON)."""
    p = mean_scores
    log_p = jnp.log(jnp.maximum(p, EPSILON))
    log_1mp = jnp.where(p > 1.0 - EPSILON, jnp.log(EPSILON), jnp.log1p(-p))
    return _wmean(labels * log_p + (1.0 - labels) * log_1mp, weights)


def poisson_log_likelihood(
    margins: Array, labels: Array, weights: Optional[Array] = None
) -> Array:
    """Per-datum average of y*wTx - exp(wTx) - logGamma(1+y)
    (Evaluation.poissonRegressionLogLikelihood :124-135)."""
    return _wmean(
        labels * margins - jnp.exp(margins) - jax.scipy.special.gammaln(1.0 + labels),
        weights,
    )


def _aic(log_likelihood_per_datum: float, n: float, coefficients: Array) -> float:
    """AICc: 2(k - LL) + 2k(k+1)/(n-k-1), k = #{|coef| > 1e-9}
    (Evaluation.scala:99-116); +inf when the correction denominator is <= 0
    (tiny holdout, n <= k+1)."""
    k = float(jnp.sum(jnp.abs(coefficients) > EPSILON))
    total_ll = n * log_likelihood_per_datum
    base = 2.0 * (k - total_ll)
    denom = n - k - 1.0
    if denom <= 0.0:
        return float("inf")
    return base + 2.0 * k * (k + 1.0) / denom


def evaluate(
    model: GeneralizedLinearModel,
    batch: GLMBatch,
    norm: Optional["NormalizationContext"] = None,
) -> Dict[str, float]:
    """Full metric map for one model on one dataset (Evaluation.evaluate).

    Pass the training ``norm`` when the coefficients live in normalized
    space (i.e. they were not back-transformed via
    ``norm.model_to_original_space``).
    """
    task = model.task
    mean_scores = model.compute_mean_functions(batch, norm)
    labels = batch.labels
    weights = batch.weights  # weight 0 = padding; all metrics honor it
    n = float(jnp.sum(weights > 0.0))
    metrics: Dict[str, float] = {}

    if task in _REGRESSION_TASKS:
        err = mean_scores - labels
        mae = float(_wmean(jnp.abs(err), weights))
        mse = float(_wmean(jnp.square(err), weights))
        metrics[MEAN_ABSOLUTE_ERROR] = mae
        metrics[MEAN_SQUARE_ERROR] = mse
        metrics[ROOT_MEAN_SQUARE_ERROR] = float(jnp.sqrt(mse))

    if task in _CLASSIFIER_TASKS:
        metrics[AREA_UNDER_PRECISION_RECALL] = float(
            area_under_pr(mean_scores, labels, weights)
        )
        from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve

        metrics[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = float(
            area_under_roc_curve(mean_scores, labels, weights)
        )
        metrics[PEAK_F1_SCORE] = float(peak_f1(mean_scores, labels, weights))

    if task == TaskType.LOGISTIC_REGRESSION:
        metrics[DATA_LOG_LIKELIHOOD] = float(
            logistic_log_likelihood(mean_scores, labels, weights)
        )
    elif task == TaskType.POISSON_REGRESSION:
        margins = model.compute_margins(batch, norm)
        metrics[DATA_LOG_LIKELIHOOD] = float(
            poisson_log_likelihood(margins, labels, weights)
        )

    if DATA_LOG_LIKELIHOOD in metrics:
        metrics[AIKAKE_INFORMATION_CRITERION] = _aic(
            metrics[DATA_LOG_LIKELIHOOD], n, model.coefficients.means
        )
    return metrics


# metric orderering: True = larger is better (Evaluation.metricMetadata)
METRIC_LARGER_IS_BETTER: Dict[str, bool] = {
    MEAN_ABSOLUTE_ERROR: False,
    MEAN_SQUARE_ERROR: False,
    ROOT_MEAN_SQUARE_ERROR: False,
    AREA_UNDER_PRECISION_RECALL: True,
    AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS: True,
    PEAK_F1_SCORE: True,
    DATA_LOG_LIKELIHOOD: True,
    AIKAKE_INFORMATION_CRITERION: False,
}


