"""Evaluators — all metrics computed on device with batched primitives.

Reference spec: evaluation/Evaluator.scala:24-75 (evaluate + betterThan),
AreaUnderROCCurveEvaluator (delegating to Spark MLlib), RMSE / loss-style
evaluators (also used as coordinate-descent training objectives),
PrecisionAtKEvaluator.scala:35-85 (group by id, sort desc, positives in
top-K), EvaluatorType.scala.

TPU-native: AUC is an exact weighted Mann-Whitney statistic via one sort +
cumsum + searchsorted (ties get the standard 0.5 credit) — no Spark MLlib,
no host round-trip. Precision@K uses a lexicographic sort + segment
arithmetic instead of groupByKey. Rows with weight 0 are padding and drop
out of every metric automatically.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops import losses as losses_mod

Array = jax.Array


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    RMSE = "RMSE"
    PRECISION_AT_K = "PRECISION_AT_K"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"


# ---------------------------------------------------------------------------
# metric kernels
# ---------------------------------------------------------------------------

def area_under_roc_curve(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """Exact weighted AUROC (Mann-Whitney with tie credit 0.5).

    AUC = sum_pos w_i * (W_neg<s_i + 0.5 * W_neg=s_i) / (W_pos * W_neg)
    """
    if weights is None:
        weights = jnp.ones_like(scores)
    pos_w = weights * labels
    neg_w = weights * (1.0 - labels)

    order = jnp.argsort(scores)
    s_sorted = scores[order]
    cum_neg = jnp.cumsum(neg_w[order])
    lo = jnp.searchsorted(s_sorted, scores, side="left")
    hi = jnp.searchsorted(s_sorted, scores, side="right")
    total0 = jnp.zeros((), scores.dtype)
    below = jnp.where(lo > 0, cum_neg[jnp.maximum(lo - 1, 0)], total0)
    upto = jnp.where(hi > 0, cum_neg[jnp.maximum(hi - 1, 0)], total0)
    equal = upto - below
    numer = jnp.sum(pos_w * (below + 0.5 * equal))
    w_pos = jnp.sum(pos_w)
    w_neg = jnp.sum(neg_w)
    return numer / jnp.maximum(w_pos * w_neg, 1e-30)


def _weighted_mean(v: Array, weights: Optional[Array]) -> Array:
    if weights is None:
        return jnp.mean(v)
    return jnp.sum(v * weights) / jnp.maximum(jnp.sum(weights), 1e-30)


def rmse(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    return jnp.sqrt(_weighted_mean(jnp.square(scores - labels), weights))


def mean_absolute_error(scores, labels, weights=None) -> Array:
    return _weighted_mean(jnp.abs(scores - labels), weights)


def mean_squared_error(scores, labels, weights=None) -> Array:
    return _weighted_mean(jnp.square(scores - labels), weights)


def _loss_mean(loss) -> Callable:
    def fn(scores, labels, weights=None):
        return _weighted_mean(loss.loss(scores, labels), weights)

    return fn


logistic_loss = _loss_mean(losses_mod.logistic)
squared_loss = _loss_mean(losses_mod.squared)
poisson_loss = _loss_mean(losses_mod.poisson)
smoothed_hinge_loss = _loss_mean(losses_mod.smoothed_hinge)


def precision_at_k(
    scores: Array,
    labels: Array,
    group_ids: Array,
    k: int,
    weights: Optional[Array] = None,
) -> Array:
    """Mean over groups of (positives in the group's top-K by score) / K.

    (PrecisionAtKEvaluator.scala:59-78 semantics.) ``group_ids`` are dense
    int ids; rows with weight 0 are excluded.
    """
    if weights is None:
        weights = jnp.ones_like(scores)
    valid = weights > 0.0
    n = scores.shape[0]
    # lexsort: by group asc, then score desc. Build a single sort key.
    big = jnp.where(valid, group_ids, jnp.int32(2**30))
    order = jnp.lexsort((-scores, big))
    g_sorted = big[order]
    l_sorted = labels[order]
    v_sorted = valid[order]
    # rank within group = position - first position of the group
    first_pos = jnp.searchsorted(g_sorted, g_sorted, side="left")
    rank = jnp.arange(n) - first_pos
    in_topk = (rank < k) & v_sorted
    hits = in_topk & (l_sorted > 0.5)
    # per-group hit counts -> mean over distinct valid groups
    num_groups = jnp.sum(
        jnp.concatenate([jnp.array([True]), g_sorted[1:] != g_sorted[:-1]]) & v_sorted
    )
    return jnp.sum(hits) / jnp.maximum(num_groups * k, 1)


# ---------------------------------------------------------------------------
# Evaluator objects (direction-aware comparison, factory)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """Metric + direction (Evaluator.betterThan parity)."""

    etype: EvaluatorType
    fn: Callable
    larger_is_better: bool
    k: Optional[int] = None

    def evaluate(self, scores, labels, weights=None, group_ids=None) -> Array:
        if self.etype == EvaluatorType.PRECISION_AT_K:
            return self.fn(scores, labels, group_ids, self.k, weights)
        return self.fn(scores, labels, weights)

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.larger_is_better else a < b


def evaluator_for(etype: EvaluatorType, k: int = 10) -> Evaluator:
    table = {
        EvaluatorType.AUC: (area_under_roc_curve, True),
        EvaluatorType.RMSE: (rmse, False),
        EvaluatorType.LOGISTIC_LOSS: (logistic_loss, False),
        EvaluatorType.POISSON_LOSS: (poisson_loss, False),
        EvaluatorType.SQUARED_LOSS: (squared_loss, False),
        EvaluatorType.SMOOTHED_HINGE_LOSS: (smoothed_hinge_loss, False),
        EvaluatorType.PRECISION_AT_K: (precision_at_k, True),
    }
    fn, larger = table[etype]
    return Evaluator(etype, fn, larger, k if etype == EvaluatorType.PRECISION_AT_K else None)
