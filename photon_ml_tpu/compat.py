"""Version-gated JAX API shims.

The baked image pins one JAX version; developer machines and CI may run
another. Every cross-version API difference the package depends on is
resolved HERE, once, instead of try/excepting at each call site — part of
the resilience story: an import-time AttributeError in a leaf module would
otherwise take down the whole ``parallel`` package (and every driver that
lazily imports it) on a version skew.

Currently shimmed:

  * ``shard_map`` — stable ``jax.shard_map`` (jax >= 0.6) with the
    ``check_vma`` kwarg, vs ``jax.experimental.shard_map.shard_map`` (older
    jax) where the same knob is spelled ``check_rep``. Callers use the
    modern spelling; the shim translates when running on the older API.
  * ``distributed_is_initialized`` — ``jax.distributed.is_initialized()``
    does not exist on older jax; fall back to probing the internal
    distributed global state for a live client.
  * ``enable_persistent_cache`` — the persistent XLA compilation cache is
    spelled three ways across jax versions (``jax_compilation_cache_dir``
    config + tuning knobs, vs the experimental
    ``compilation_cache.set_cache_dir``); one call resolves whichever this
    jax has, so warm driver runs skip XLA compilation entirely.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

try:  # modern spelling (jax >= 0.6): stable, check_vma kwarg
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _NEEDS_TRANSLATION = False
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEEDS_TRANSLATION = True


def shard_map(f: Callable[..., Any], **kwargs: Any):
    """``jax.shard_map`` facade accepting the modern kwargs on any jax.

    On the legacy API the ``check_rep`` validator has no replication rule
    for ``lax.while_loop`` (NotImplementedError at trace time), which every
    solver kernel here carries — so when translating, validation is turned
    OFF rather than crashing the solve. The modern ``check_vma`` validator
    handles while_loop and stays at the caller's setting; the compensating
    sharded-vs-local equivalence tests (tests/test_checkvma_fence.py
    registry) hold on both APIs.
    """
    if _NEEDS_TRANSLATION:
        kwargs.pop("check_vma", None)
        kwargs["check_rep"] = False
    return _shard_map(f, **kwargs)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` on any jax version."""
    import jax

    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None


def enable_x64():
    """``jax.enable_x64()`` context manager on any jax version (older jax
    spells it ``jax.experimental.enable_x64``)."""
    import jax

    try:
        return jax.enable_x64()
    except AttributeError:
        from jax.experimental import enable_x64 as _enable_x64

        return _enable_x64()


def enable_persistent_cache(path: str) -> bool:
    """Point jax's persistent XLA compilation cache at ``path``.

    Modern jax: the ``jax_compilation_cache_dir`` config option, plus the
    two tuning knobs that default to skipping small/fast entries — both
    zeroed here, because the GLMix solver sites are exactly the many-small-
    executables workload those defaults would exclude (a "warm" run that
    still recompiles every solver kernel reports zero benefit). Older jax:
    ``jax.experimental.compilation_cache.set_cache_dir``. Returns False
    when no spelling exists on this jax (the caller logs and moves on —
    an absent cache must never fail a training run).
    """
    import os

    import jax

    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except AttributeError:
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.set_cache_dir(path)
            return True
        except (ImportError, AttributeError):
            return False
    # cache EVERYTHING: -1 disables the min-entry-size filter; 0 disables
    # the min-compile-seconds filter (knobs absent on some versions)
    for knob, value in (
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_min_compile_time_secs", 0),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):
            pass  # knob not on this jax: defaults still cache solver-sized entries
    try:
        # jax LATCHES cache-used at the first compile of the process; a
        # driver that touched the device before reaching this call (backend
        # probe, data placement) would silently never cache without a reset
        from jax._src import compilation_cache as _cc_internal

        _cc_internal.reset_cache()
    except (ImportError, AttributeError):
        pass  # no latch on this jax: the config alone suffices
    return True


def pallas_tpu_compiler_params(**kwargs: Any):
    """Pallas TPU ``CompilerParams`` across jax versions.

    Newer jax spells the Mosaic compiler-params struct
    ``pallas.tpu.CompilerParams``; 0.4.x spells the same struct
    ``TPUCompilerParams`` (and the very oldest releases only accept a plain
    dict through ``compiler_params=``). Kernel call sites pass the modern
    kwargs (``dimension_semantics=...``) and this resolves whichever
    spelling the running jax has — the fused-GLM Pallas family must
    compile on both the baked image and developer jax. (The fused-sparse
    kernels pass no compiler params: their row-block grid axis carries a
    sequential VMEM accumulator, so the default ordering is required.)
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:  # ancient pallas: a bare dict is the accepted form
        return dict(kwargs)
    return cls(**kwargs)


_FORCE_CPU_FLAG = "--xla_force_host_platform_device_count"


def forced_cpu_device_count(flags: Optional[str] = None) -> Optional[int]:
    """The CPU device count forced through ``XLA_FLAGS``
    (``--xla_force_host_platform_device_count=N``), or ``None`` when the
    flag is absent or malformed. The LAST occurrence wins, matching XLA's
    own parse. Pass ``flags`` to inspect a specific string (a child
    environment under construction); the default reads the process env
    through the one overrides gate."""
    if flags is None:
        from photon_ml_tpu.compile import overrides

        flags = overrides.env_read("XLA_FLAGS", "") or ""
    count = None
    for part in flags.split():
        if part.startswith(_FORCE_CPU_FLAG + "="):
            try:
                count = int(part.split("=", 1)[1])
            except ValueError:
                return None
    return count


def backends_initialized() -> bool:
    """Whether jax has already instantiated a PJRT backend — after which
    ``XLA_FLAGS`` edits are silently ignored. Probes the backend registry
    WITHOUT initializing it; when the registry moved (version skew), the
    conservative answer is True (treat flags as latched)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except (ImportError, AttributeError):
        return True


def force_cpu_devices(n: int) -> bool:
    """Arrange for the host CPU platform to expose ``n`` devices by
    pinning ``--xla_force_host_platform_device_count=n`` into
    ``XLA_FLAGS`` (the multi-device-single-host mesh the psum merge arms
    ride). XLA reads the flag exactly once, at backend instantiation, so:

      * before jax initializes: rewrite the env (replacing any prior
        occurrence of the flag) and return True;
      * after jax initializes: an env edit is a silent no-op — return
        whether the LIVE CPU backend already satisfies the request, so
        the caller knows to skip or re-exec in a fresh subprocess (the
        bench psum arm's structured ``preflight:`` skip).
    """
    import os

    if n < 1:
        raise ValueError(f"force_cpu_devices needs n >= 1, got {n}")
    if backends_initialized():
        import jax

        try:
            return len(jax.devices("cpu")) >= n
        except RuntimeError:  # no CPU platform in this process's config
            return False
    if forced_cpu_device_count() == n:
        return True
    from photon_ml_tpu.compile import overrides

    flags = overrides.env_read("XLA_FLAGS", "") or ""
    parts = [
        p for p in flags.split() if not p.startswith(_FORCE_CPU_FLAG + "=")
    ]
    parts.append(f"{_FORCE_CPU_FLAG}={n}")
    os.environ["XLA_FLAGS"] = " ".join(parts)
    return True


def ensure_cpu_collectives() -> None:
    """Select the Gloo CPU collectives implementation where it is opt-in.

    Older jax ships multiprocess CPU collectives behind
    ``jax_cpu_collectives_implementation`` (default ``none`` -> cross-host
    psums fail with "Multiprocess computations aren't implemented on the
    CPU backend"); newer jax enables a CPU collectives backend by default.
    Harmless on TPU — the option only affects the CPU PJRT client."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # option gone (newer jax: CPU collectives are on by default)
