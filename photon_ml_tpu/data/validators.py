"""Per-task dataset sanity checks.

Reference spec: data/DataValidators.scala —
  linear regression  : finite labels, finite features, finite offsets
  logistic regression: binary labels, finite features, finite offsets
  Poisson regression : finite + non-negative labels, finite features/offsets
  smoothed hinge SVM : binary labels, finite features, finite offsets
``sanity_check_data`` honors DataValidationType: VALIDATE_FULL checks every
row, VALIDATE_SAMPLE a 10% subsample, VALIDATE_DISABLED skips.

TPU-native: the checks are whole-array reductions on device (one fused pass),
not per-row closures.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.types import DataValidationType, TaskType

Array = jax.Array


def _finite(a: Array) -> bool:
    return bool(jnp.all(jnp.isfinite(a)))


def finite_labels(batch: GLMBatch) -> bool:
    return _finite(batch.labels)


def finite_offsets(batch: GLMBatch) -> bool:
    return _finite(batch.offsets)


def finite_features(batch: GLMBatch) -> bool:
    # checking values covers both layouts (dense matrix / sparse values)
    feats = batch.features
    vals = feats.values if hasattr(feats, "values") else feats.matrix
    return _finite(vals)


def binary_labels(batch: GLMBatch) -> bool:
    return bool(jnp.all((batch.labels == 0.0) | (batch.labels == 1.0)))


def non_negative_labels(batch: GLMBatch) -> bool:
    return bool(jnp.all(batch.labels >= 0.0))


def validators_for(task: TaskType) -> Dict[str, object]:
    common = {
        "Finite features": finite_features,
        "Finite offsets": finite_offsets,
    }
    if task == TaskType.LINEAR_REGRESSION:
        return {"Finite labels": finite_labels, **common}
    if task == TaskType.POISSON_REGRESSION:
        return {
            "Finite labels": finite_labels,
            "Non-negative labels": non_negative_labels,
            **common,
        }
    # logistic / smoothed hinge
    return {"Binary labels": binary_labels, **common}


def _subsample(batch: GLMBatch, fraction: float, seed: int = 42) -> GLMBatch:
    n = batch.num_rows
    rng = np.random.default_rng(seed)
    idx = np.nonzero(rng.random(n) < fraction)[0]
    if idx.size == 0:
        idx = np.array([0])
    take = lambda a: a[jnp.asarray(idx)]
    feats = batch.features
    if hasattr(feats, "matrix"):
        from photon_ml_tpu.ops.features import DenseFeatures

        feats = DenseFeatures(take(feats.matrix))
    else:
        from photon_ml_tpu.ops.features import SparseFeatures

        # deliberately DROP any transpose layout: it covers the full row
        # set, and this path row-SAMPLES (stale t_* would re-add dropped
        # rows' contributions)
        feats = SparseFeatures(take(feats.indices), take(feats.values), feats.dim)
    return GLMBatch(feats, take(batch.labels), take(batch.offsets), take(batch.weights))


def sanity_check_data(
    batch: GLMBatch,
    task: TaskType,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    """Raise ValueError listing every failed check (Driver.scala:191-193 use)."""
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    if validation_type == DataValidationType.VALIDATE_SAMPLE:
        batch = _subsample(batch, 0.10)
    failed: List[str] = [
        name for name, fn in validators_for(task).items() if not fn(batch)
    ]
    if failed:
        raise ValueError(f"data validation failed for {task.value}: {', '.join(failed)}")
