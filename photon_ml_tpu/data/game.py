"""GAME data layer: host-side columnar ingest + device-ready entity tensors.

Reference spec (re-designed, not ported):
  * GameDatum (data/GameDatum.scala:33-59): response/offset/weight +
    per-shard feature vectors + id-type -> id map. Here: a columnar
    ``GameData`` in one global row order — the row index replaces Spark's
    ``zipWithUniqueId`` global id, and score vectors are plain dense arrays
    in that order (KeyValueScore join-arithmetic becomes elementwise add).
  * RandomEffectDataSet (data/RandomEffectDataSet.scala:38-380): grouping by
    entity, active/passive split with reservoir caps, balanced partitioner.
    Here: entities become the leading axis of padded tensors
    ``(E, M, D_loc)`` so the per-entity solver vmaps; the balanced
    partitioner (RandomEffectIdPartitioner.scala:29-97) becomes
    sort-by-size + strided interleave so an even slice over the entity axis
    is load-balanced; the active/passive split is a host-side deterministic
    sample (reservoir semantics with a seeded RNG).
  * Per-entity feature projection (projector/IndexMapProjectorRDD.scala:
    30-119): each entity's observed feature set maps to a dense local space
    [0, D_loc); unseen features drop. Stored as ``local_to_global`` gather
    indices, making per-entity dims uniform — the key trick that makes
    per-entity solves vmappable (SURVEY.md §2.4).
  * Pearson feature selection (data/LocalDataSet.scala:118-136): top-k
    features per entity by |corr(feature, label)|, computed vectorized over
    all (entity, feature) pairs at once.

Everything here is one-time ingest work on the host; training touches only
the produced device arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.types import real_dtype

Array = jax.Array


# ---------------------------------------------------------------------------
# host-side columnar containers
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HostFeatures:
    """CSR features for one feature shard (host)."""

    indptr: np.ndarray  # (N+1,) int64
    indices: np.ndarray  # (nnz,) int32
    values: np.ndarray  # (nnz,) float32
    dim: int

    @property
    def num_rows(self) -> int:
        return len(self.indptr) - 1

    def row_slice(self, r: int) -> Tuple[np.ndarray, np.ndarray]:
        s, e = self.indptr[r], self.indptr[r + 1]
        return self.indices[s:e], self.values[s:e]


@dataclasses.dataclass
class GameData:
    """Columnar GAME dataset in one global row order (host).

    ``ids[id_type]`` holds dense entity indices (already mapped from raw id
    strings via ``id_vocabs[id_type]``).
    """

    response: np.ndarray  # (N,) float32
    offset: np.ndarray  # (N,) float32
    weight: np.ndarray  # (N,) float32
    ids: Dict[str, np.ndarray]  # id_type -> (N,) int32 dense entity index
    id_vocabs: Dict[str, List[str]]  # id_type -> raw id per dense index
    shards: Dict[str, HostFeatures]  # feature shard id -> CSR

    @property
    def num_rows(self) -> int:
        return len(self.response)


def game_data_to_arrays(data: GameData):
    """Flatten a GameData into (named arrays, JSON-safe meta) for the
    content-addressed tensor cache (io/tensor_cache.py): a warm run
    reconstructs the decoded columnar dataset without touching Avro."""
    arrays = {
        "response": data.response,
        "offset": data.offset,
        "weight": data.weight,
    }
    for k, v in data.ids.items():
        arrays[f"ids~{k}"] = v
    for k, f in data.shards.items():
        arrays[f"shard~{k}~indptr"] = f.indptr
        arrays[f"shard~{k}~indices"] = f.indices
        arrays[f"shard~{k}~values"] = f.values
    meta = {
        "id_types": sorted(data.ids),
        "shards": {k: int(f.dim) for k, f in data.shards.items()},
        "id_vocabs": {k: list(v) for k, v in data.id_vocabs.items()},
    }
    return arrays, meta


def game_data_from_arrays(arrays, meta) -> GameData:
    """Inverse of :func:`game_data_to_arrays` over a cache hit (arrays are
    mmap-backed; nothing is decoded)."""
    return GameData(
        response=np.asarray(arrays["response"]),
        offset=np.asarray(arrays["offset"]),
        weight=np.asarray(arrays["weight"]),
        ids={k: np.asarray(arrays[f"ids~{k}"]) for k in meta["id_types"]},
        id_vocabs={k: list(v) for k, v in meta["id_vocabs"].items()},
        shards={
            k: HostFeatures(
                indptr=np.asarray(arrays[f"shard~{k}~indptr"]),
                indices=np.asarray(arrays[f"shard~{k}~indices"]),
                values=np.asarray(arrays[f"shard~{k}~values"]),
                dim=int(dim),
            )
            for k, dim in meta["shards"].items()
        },
    )


# ---------------------------------------------------------------------------
# balanced entity ordering (RandomEffectIdPartitioner analogue)
# ---------------------------------------------------------------------------


def balanced_entity_order(active_counts: np.ndarray, num_shards: int) -> np.ndarray:
    """Order entities so equal slices over the entity axis balance work.

    Sort by active-sample count descending, then stride-interleave across
    ``num_shards``: shard s receives sorted positions s, s+S, s+2S, ... This
    is the static-table analogue of the reference's greedy min-heap
    bin-packing (RandomEffectIdPartitioner.scala:64-97) — both put the
    heaviest entities on distinct shards first.

    Returns entity indices in tensor-layout order: the first E/S rows of the
    stacked tensor belong to shard 0, etc.
    """
    e = len(active_counts)
    by_size = np.argsort(-active_counts, kind="stable")
    per_shard: List[List[int]] = [[] for _ in range(num_shards)]
    for pos, ent in enumerate(by_size):
        per_shard[pos % num_shards].append(int(ent))
    # pad shards to equal length with -1 (empty slots)
    cap = max(len(p) for p in per_shard)
    order = []
    for p in per_shard:
        order.extend(p + [-1] * (cap - len(p)))
    return np.asarray(order, np.int64)


# ---------------------------------------------------------------------------
# Pearson-correlation feature selection (vectorized across entities)
# ---------------------------------------------------------------------------


def pearson_feature_scores(
    entity_of_row: np.ndarray,
    labels: np.ndarray,
    feats: HostFeatures,
    row_mask: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """|Pearson corr(feature, label)| per (entity, feature) pair present.

    Returns (pair_entity, pair_feature, pair_score) for every distinct
    (entity, feature) pair among masked-in rows. Sparse-aware: absent
    features are zeros and enter through the n/mean terms.
    (data/LocalDataSet.scala:198-259 semantics, vectorized.)
    """
    n = feats.num_rows
    rows_nnz = np.repeat(np.arange(n), np.diff(feats.indptr))
    keep = row_mask[rows_nnz]
    r = rows_nnz[keep]
    c = feats.indices[keep].astype(np.int64)
    v = feats.values[keep]
    ent = entity_of_row[r].astype(np.int64)
    y = labels[r]

    # per-entity label stats over masked rows
    me = np.max(entity_of_row[row_mask]) + 1 if row_mask.any() else 0
    cnt_e = np.bincount(entity_of_row[row_mask], minlength=me).astype(np.float64)
    sum_y = np.bincount(entity_of_row[row_mask], weights=labels[row_mask], minlength=me)
    sum_y2 = np.bincount(entity_of_row[row_mask], weights=labels[row_mask] ** 2, minlength=me)

    # per-(entity, feature) sums via composite keys
    key = ent * feats.dim + c
    uniq, inv = np.unique(key, return_inverse=True)
    sum_x = np.bincount(inv, weights=v)
    sum_x2 = np.bincount(inv, weights=v.astype(np.float64) ** 2)
    sum_xy = np.bincount(inv, weights=(v * y).astype(np.float64))

    pe = (uniq // feats.dim).astype(np.int64)
    pf = (uniq % feats.dim).astype(np.int64)
    ne = cnt_e[pe]
    mean_x = sum_x / ne
    mean_y = sum_y[pe] / ne
    var_x = sum_x2 / ne - mean_x**2
    var_y = sum_y2[pe] / ne - mean_y**2
    cov = sum_xy / ne - mean_x * mean_y
    denom = np.sqrt(np.maximum(var_x, 0.0) * np.maximum(var_y, 0.0))
    score = np.where(denom > 1e-12, np.abs(cov) / np.maximum(denom, 1e-12), 0.0)
    # features with zero variance (e.g. an intercept column) score 1.0 in the
    # reference convention so they are always kept
    score = np.where(var_x <= 1e-12, 1.0, score)
    return pe, pf, score


# ---------------------------------------------------------------------------
# RandomEffectDataset: device tensors for vmapped per-entity training
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfig:
    """Parity with data/RandomEffectDataConfiguration.scala:42-130."""

    random_effect_id: str  # id type to group by (e.g. "userId")
    feature_shard_id: str
    num_shards: int = 1  # entity-axis shards (mesh slices)
    active_upper_bound: Optional[int] = None  # max active samples per entity
    passive_lower_bound: Optional[int] = None  # min passive rows to keep entity's passive set
    features_to_samples_ratio: Optional[float] = None  # Pearson selection cap
    projector: str = "INDEX_MAP"  # INDEX_MAP | IDENTITY | RANDOM
    random_projection_dim: Optional[int] = None
    # whether the shard's last column is an intercept the RANDOM projection
    # must pass through untouched (ProjectionMatrix.scala isKeepingInterceptTerm)
    random_projection_intercept: bool = True
    seed: int = 7


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RandomEffectDataset:
    """Device-resident, entity-major random-effect training + scoring data.

    Training (active) tensors, entity-major:
      row_index   (E, M) int32  — global row of each active sample (-1 pad)
      x           (E, M, D_loc) float32 — locally-projected dense features
      labels      (E, M), base_offsets (E, M), weights (E, M) (0 = pad)

    Scoring tensors, global row order (covers active + passive rows):
      entity_pos  (N,) int32 — row's entity position in the tensor (-1 none)
      feat_idx    (N, K) int32 — local feature indices (-1 masked)
      feat_val    (N, K) float32

    Projection bookkeeping:
      local_to_global (E, D_loc) int32 — global column per local column (-1 pad)
    """

    row_index: Array
    x: Array
    labels: Array
    base_offsets: Array
    weights: Array
    entity_pos: Array
    feat_idx: Array
    feat_val: Array
    local_to_global: Array
    num_entities: int = dataclasses.field(metadata={"static": True})
    global_dim: int = dataclasses.field(metadata={"static": True})
    # shared RANDOM-projection matrix (k, D_global) when the local space is a
    # random projection; None for INDEX_MAP/IDENTITY. Needed to back-project
    # coefficients to the original space.
    projection_matrix: Optional[Array] = None

    @property
    def num_rows(self) -> int:
        return self.entity_pos.shape[0]

    @property
    def local_dim(self) -> int:
        return self.x.shape[-1]

    def tree_flatten(self):
        children = (
            self.row_index,
            self.x,
            self.labels,
            self.base_offsets,
            self.weights,
            self.entity_pos,
            self.feat_idx,
            self.feat_val,
            self.local_to_global,
            self.projection_matrix,
        )
        return children, (self.num_entities, self.global_dim)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children[:9], aux[0], aux[1], children[9])


_RE_TENSOR_FIELDS = (
    "row_index", "x", "labels", "base_offsets", "weights",
    "entity_pos", "feat_idx", "feat_val", "local_to_global",
)


def _re_dataset_from_cache(entry) -> RandomEffectDataset:
    """Rebuild a RandomEffectDataset from a tensor-cache hit. The cached
    arrays are mmap-backed ``.npy`` slabs; ``jnp.asarray`` faults them in
    page by page on device placement — grouping/projection/padding are all
    skipped."""
    return RandomEffectDataset(
        **{f: jnp.asarray(entry.arrays[f]) for f in _RE_TENSOR_FIELDS},
        num_entities=int(entry.meta["num_entities"]),
        global_dim=int(entry.meta["global_dim"]),
        projection_matrix=(
            jnp.asarray(entry.arrays["projection_matrix"])
            if "projection_matrix" in entry.arrays
            else None
        ),
    )


def build_random_effect_dataset(
    data: GameData,
    config: RandomEffectDataConfig,
    projector=None,
    tensor_cache=None,
    cache_key: Optional[str] = None,
) -> RandomEffectDataset:
    """Host-side build: group, cap, project, pad, ship to device.

    ``projector`` (a ProjectionMatrixProjector) is only consulted when
    ``config.projector == "RANDOM"``; omitted, one is built from
    ``config.random_projection_dim`` and ``config.seed``.

    With a ``tensor_cache`` (:class:`photon_ml_tpu.io.tensor_cache.
    TensorCache`) and ``cache_key`` (the content address of the SOURCE
    inputs + this config, computed by the caller who knows the source
    files), the BUILT padded entity-major tensors are stored as mmap'd
    ``.npy`` slabs and a later call with the same key skips grouping +
    projection + padding entirely. Any config or input change produces a
    different key — a miss — so stale tensors are never served. A
    cache-write failure degrades to the uncached build.
    """
    if tensor_cache is not None and cache_key is not None:
        hit = tensor_cache.get(cache_key)
        if hit is not None:
            return _re_dataset_from_cache(hit)
    ds = _build_random_effect_dataset(data, config, projector)
    if tensor_cache is not None and cache_key is not None:
        from photon_ml_tpu.resilience import RetryError

        arrays = {f: np.asarray(getattr(ds, f)) for f in _RE_TENSOR_FIELDS}
        if ds.projection_matrix is not None:
            arrays["projection_matrix"] = np.asarray(ds.projection_matrix)
        try:
            tensor_cache.put(
                cache_key, arrays,
                meta={"num_entities": ds.num_entities,
                      "global_dim": ds.global_dim},
            )
        except RetryError:
            pass  # an unusable cache must not fail the build it wraps
    return ds


def _build_random_effect_dataset(
    data: GameData, config: RandomEffectDataConfig, projector=None
) -> RandomEffectDataset:
    """The uncached build (see :func:`build_random_effect_dataset`)."""
    ids = data.ids[config.random_effect_id]
    feats = data.shards[config.feature_shard_id]
    n = data.num_rows
    num_entities_raw = int(ids.max()) + 1 if n else 0
    rng = np.random.default_rng(config.seed)

    # ---- active/passive split (reservoir-cap semantics) -------------------
    counts = np.bincount(ids, minlength=num_entities_raw)
    cap = config.active_upper_bound or (int(counts.max()) if n else 1)
    # deterministic "reservoir": random priority per row, keep the cap
    # smallest priorities per entity
    priority = rng.random(n)
    order = np.lexsort((priority, ids))  # group by entity, random within
    sorted_ids = ids[order]
    group_start = np.searchsorted(sorted_ids, np.arange(num_entities_raw), side="left")
    rank = np.arange(n) - group_start[sorted_ids]
    is_active_sorted = rank < cap
    active_mask = np.zeros(n, bool)
    active_mask[order] = is_active_sorted
    # reference re-scales kept weights so the active set represents the full
    # entity (RandomEffectDataSet.scala:298-301)
    active_counts = np.minimum(counts, cap)
    scale = np.ones(num_entities_raw)
    over = counts > cap
    scale[over] = counts[over] / cap

    # ---- per-entity feature selection / local index maps ------------------
    if config.projector == "RANDOM":
        # shared Gaussian random projection (projector/ProjectionMatrixBroadcast
        # .scala:30-96): every entity shares one dense (k, d) matrix, applied
        # host-side to CSR rows; the local space is the k-dim projected space.
        from photon_ml_tpu.projectors import build_projector
        from photon_ml_tpu.types import ProjectorType

        if projector is None:
            projector = build_projector(
                ProjectorType.RANDOM,
                feats.dim,
                config.random_projection_dim,
                keep_intercept=config.random_projection_intercept,
                seed=config.seed,
            )
        d_loc = projector.projected_dim
        local_to_global = np.full((num_entities_raw, d_loc), -1, np.int32)

        def project_rows(row_sel: np.ndarray):
            starts = feats.indptr[row_sel]
            ends = feats.indptr[row_sel + 1]
            lens = (ends - starts).astype(np.int64)
            flat_ptr = (
                np.concatenate([np.arange(s, e) for s, e in zip(starts, ends)])
                if len(row_sel)
                else np.zeros(0, np.int64)
            )
            row_splits = np.concatenate([[0], np.cumsum(lens)])
            dense = projector.project_sparse_features(
                feats.indices[flat_ptr].astype(np.int64), feats.values[flat_ptr], row_splits
            )
            out_idx = np.tile(np.arange(d_loc, dtype=np.int32), (len(row_sel), 1))
            return out_idx, dense.astype(real_dtype())

        return _assemble_random_effect_tensors(
            data, config, ids, feats, n, num_entities_raw, active_mask, active_counts,
            scale, d_loc, local_to_global, project_rows, cap,
            projection_matrix=projector.matrix,
        )
    if config.features_to_samples_ratio is not None:
        pe, pf, score = pearson_feature_scores(ids, data.response, feats, active_mask)
        # keep top ceil(ratio * n_active_e) features per entity
        budget = np.ceil(config.features_to_samples_ratio * active_counts).astype(np.int64)
        sel_order = np.lexsort((-score, pe))
        pe_s, pf_s = pe[sel_order], pf[sel_order]
        start = np.searchsorted(pe_s, np.arange(num_entities_raw), side="left")
        rank_f = np.arange(len(pe_s)) - start[pe_s]
        keep_pair = rank_f < budget[pe_s]
        pair_e, pair_f = pe_s[keep_pair], pf_s[keep_pair]
    else:
        # all features each entity saw in its active rows
        rows_nnz = np.repeat(np.arange(n), np.diff(feats.indptr))
        keep = active_mask[rows_nnz]
        pair_key = ids[rows_nnz[keep]].astype(np.int64) * feats.dim + feats.indices[
            keep
        ].astype(np.int64)
        uniq = np.unique(pair_key)
        pair_e = (uniq // feats.dim).astype(np.int64)
        pair_f = (uniq % feats.dim).astype(np.int64)

    if config.projector == "IDENTITY":
        d_loc = feats.dim
        local_to_global = np.tile(
            np.arange(feats.dim, dtype=np.int32), (num_entities_raw, 1)
        )
    else:  # INDEX_MAP
        # sort pairs by (entity, feature) for deterministic local ordering
        o = np.lexsort((pair_f, pair_e))
        pair_e, pair_f = pair_e[o], pair_f[o]
        ent_start = np.searchsorted(pair_e, np.arange(num_entities_raw), side="left")
        local_idx = np.arange(len(pair_e)) - ent_start[pair_e]
        per_entity_dims = np.bincount(pair_e, minlength=num_entities_raw)
        d_loc = int(per_entity_dims.max()) if len(pair_e) else 1
        d_loc = max(d_loc, 1)
        local_to_global = np.full((num_entities_raw, d_loc), -1, np.int32)
        local_to_global[pair_e, local_idx] = pair_f.astype(np.int32)

    # hashmap (entity, global feature) -> local index for projecting rows
    pair_lookup = dict() if config.projector != "IDENTITY" else None
    if pair_lookup is not None:
        composite = pair_e * feats.dim + pair_f
        pair_lookup = (composite, local_idx)  # sorted composite keys

    def project_rows(row_sel: np.ndarray):
        """Project rows' features into their entity's local space.

        Returns (feat_idx (R, K) int32 with -1 masked, feat_val (R, K)).
        """
        sub_nnz_counts = np.diff(feats.indptr)[row_sel]
        k = int(sub_nnz_counts.max()) if len(row_sel) and sub_nnz_counts.size else 1
        k = max(k, 1)
        out_idx = np.full((len(row_sel), k), -1, np.int32)
        out_val = np.zeros((len(row_sel), k), real_dtype())
        # gather nnz of selected rows
        starts = feats.indptr[row_sel]
        ends = feats.indptr[row_sel + 1]
        lens = (ends - starts).astype(np.int64)
        flat_rows = np.repeat(np.arange(len(row_sel)), lens)
        flat_ptr = np.concatenate(
            [np.arange(s, e) for s, e in zip(starts, ends)]
        ) if len(row_sel) else np.zeros(0, np.int64)
        cols = feats.indices[flat_ptr].astype(np.int64)
        vals = feats.values[flat_ptr]
        slot = np.arange(len(flat_rows)) - np.repeat(
            np.concatenate([[0], np.cumsum(lens)[:-1]]), lens
        )
        if config.projector == "IDENTITY":
            out_idx[flat_rows, slot] = cols.astype(np.int32)
            out_val[flat_rows, slot] = vals
            return out_idx, out_val
        comp = ids[row_sel][flat_rows].astype(np.int64) * feats.dim + cols
        keys, locs = pair_lookup
        pos = np.searchsorted(keys, comp)
        pos_c = np.clip(pos, 0, len(keys) - 1) if len(keys) else np.zeros_like(pos)
        found = len(keys) > 0
        hit = (keys[pos_c] == comp) if found else np.zeros(len(comp), bool)
        out_idx[flat_rows[hit], slot[hit]] = locs[pos_c[hit]].astype(np.int32)
        out_val[flat_rows[hit], slot[hit]] = vals[hit]
        return out_idx, out_val

    return _assemble_random_effect_tensors(
        data, config, ids, feats, n, num_entities_raw, active_mask, active_counts,
        scale, d_loc, local_to_global, project_rows, cap,
    )


def _assemble_random_effect_tensors(
    data, config, ids, feats, n, num_entities_raw, active_mask, active_counts,
    scale, d_loc, local_to_global, project_rows, cap, projection_matrix=None,
):
    """Shared tail of the random-effect build: entity-major training tensors
    + global-row-order scoring tensors, for any local projection."""
    # ---- entity-major training tensors ------------------------------------
    entity_order = balanced_entity_order(active_counts, config.num_shards)
    e_padded = len(entity_order)
    m = int(active_counts.max()) if n else 1
    m = max(min(m, cap), 1)

    row_index = np.full((e_padded, m), -1, np.int32)
    # position of each entity in the tensor layout
    tensor_pos = np.full(num_entities_raw + 1, -1, np.int32)
    valid_ents = entity_order >= 0
    tensor_pos[entity_order[valid_ents]] = np.nonzero(valid_ents)[0].astype(np.int32)

    act_rows = np.nonzero(active_mask)[0]
    act_ids = ids[act_rows]
    o2 = np.lexsort((act_rows, act_ids))
    act_rows_s = act_rows[o2]
    act_ids_s = act_ids[o2]
    astart = np.searchsorted(act_ids_s, np.arange(num_entities_raw), side="left")
    arank = np.arange(len(act_rows_s)) - astart[act_ids_s]
    row_index[tensor_pos[act_ids_s], arank] = act_rows_s.astype(np.int32)

    # densify projected features per active slot
    flat_sel = row_index.reshape(-1)
    valid_slot = flat_sel >= 0
    sel_rows = flat_sel[valid_slot].astype(np.int64)
    pidx, pval = project_rows(sel_rows)
    x = np.zeros((e_padded * m, d_loc), real_dtype())
    rr = np.repeat(np.arange(len(sel_rows)), pidx.shape[1])
    cc = pidx.reshape(-1)
    vv = pval.reshape(-1)
    ok = cc >= 0
    dense_rows = np.nonzero(valid_slot)[0][rr[ok]]
    x[dense_rows, cc[ok]] = vv[ok]
    x = x.reshape(e_padded, m, d_loc)

    def scatter_col(src, fill=0.0):
        out = np.full((e_padded, m), fill, real_dtype())
        out.reshape(-1)[valid_slot] = src[sel_rows]
        return out

    labels_t = scatter_col(data.response)
    offsets_t = scatter_col(data.offset)
    weights_t = scatter_col(data.weight)
    # re-scale active weights where the entity was capped
    weights_t.reshape(-1)[valid_slot] *= scale[ids[sel_rows]].astype(real_dtype())

    # ---- scoring tensors (all rows) ---------------------------------------
    entity_pos_all = tensor_pos[ids].astype(np.int32)
    if config.passive_lower_bound is not None:
        # keep passive rows only for entities with more than lower-bound
        # passive points (RandomEffectDataSet.generatePassiveData:344-351);
        # dropped rows get entity_pos -1 and score 0 for this coordinate
        passive_mask = ~active_mask
        passive_counts = np.bincount(
            ids[passive_mask], minlength=num_entities_raw
        )
        keep_entity = passive_counts > config.passive_lower_bound
        entity_pos_all[passive_mask & ~keep_entity[ids]] = -1
    sc_idx, sc_val = project_rows(np.arange(n, dtype=np.int64))

    # local_to_global above is indexed by RAW entity id; the tensors are laid
    # out in balanced (tensor-position) order — permute to match.
    l2g_tensor = np.full((e_padded, d_loc), -1, np.int32)
    valid_pos = np.nonzero(valid_ents)[0]
    l2g_tensor[valid_pos] = local_to_global[entity_order[valid_ents]]

    return RandomEffectDataset(
        row_index=jnp.asarray(row_index),
        x=jnp.asarray(x),
        labels=jnp.asarray(labels_t),
        base_offsets=jnp.asarray(offsets_t),
        weights=jnp.asarray(weights_t),
        entity_pos=jnp.asarray(entity_pos_all),
        feat_idx=jnp.asarray(sc_idx),
        feat_val=jnp.asarray(sc_val),
        local_to_global=jnp.asarray(l2g_tensor),
        num_entities=e_padded,
        global_dim=feats.dim,
        projection_matrix=projection_matrix,
    )


# ---------------------------------------------------------------------------
# FixedEffect dataset: one GLMBatch over all rows for one shard
# ---------------------------------------------------------------------------


def padded_row_coo(feats: "HostFeatures", pad_col: int = -1):
    """CSR -> padded per-row COO: (cols (N, K), vals (N, K)), K = max nnz/row.

    Padding slots carry ``pad_col`` with value 0. ``pad_col=-1`` pairs with a
    validity mask (cols >= 0); ``pad_col=0`` makes padding a gather-safe
    no-op (value 0). The one conversion shared by validation scoring
    (cli/game_training_driver.py) and device scoring
    (cli/game_scoring_driver.py).
    """
    n = feats.num_rows
    row_nnz = np.diff(feats.indptr)
    k = max(int(row_nnz.max()) if n else 1, 1)
    cols = np.full((n, k), pad_col, np.int32)
    vals = np.zeros((n, k), feats.values.dtype)
    rows = np.repeat(np.arange(n), row_nnz)
    slots = np.arange(len(feats.indices)) - np.repeat(feats.indptr[:-1], row_nnz)
    cols[rows, slots] = feats.indices
    vals[rows, slots] = feats.values
    return cols, vals


def build_fixed_effect_batch(data: GameData, feature_shard_id: str, dense: bool = True):
    """(data/FixedEffectDataSet.scala:31-105 analogue.)"""
    from photon_ml_tpu.io.libsvm import HostDataset, to_batch

    feats = data.shards[feature_shard_id]
    ds = HostDataset(
        labels=data.response,
        indptr=feats.indptr,
        indices=feats.indices,
        values=feats.values,
        dim=feats.dim,
        offsets=data.offset,
        weights=data.weight,
    )
    return to_batch(ds, dense=dense, pad_rows_to=1)
