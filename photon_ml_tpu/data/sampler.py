"""Down-sampling within a coordinate — weight/mask based, on device.

Reference spec: sampler/BinaryClassificationDownSampler.scala:31-60
(negatives kept with prob=rate, weight scaled by 1/rate) and
sampler/DefaultDownSampler.scala:26-45 (uniform sample, weights unscaled...
actually weight scaled by 1/rate for unbiasedness). On Spark this physically
drops rows; on TPU shapes must stay static, so we *zero the weights* of
dropped rows instead — mathematically identical for every objective in this
framework (weight-0 rows contribute nothing) with no re-batching.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.objective import GLMBatch

Array = jax.Array


def down_sample_binary(batch: GLMBatch, rate: float | Array, key: Array) -> GLMBatch:
    """Keep all positives; keep negatives with probability ``rate`` and
    re-weight survivors by 1/rate (unbiased gradient)."""
    u = jax.random.uniform(key, batch.labels.shape)
    is_positive = batch.labels > 0.5
    keep = is_positive | (u < rate)
    scale = jnp.where(is_positive, 1.0, 1.0 / rate)
    new_w = jnp.where(keep, batch.weights * scale, 0.0)
    return GLMBatch(batch.features, batch.labels, batch.offsets, new_w)


def down_sample_default(batch: GLMBatch, rate: float | Array, key: Array) -> GLMBatch:
    """Uniform down-sample: keep each row with probability ``rate``,
    re-weight survivors by 1/rate."""
    u = jax.random.uniform(key, batch.labels.shape)
    keep = u < rate
    new_w = jnp.where(keep, batch.weights / rate, 0.0)
    return GLMBatch(batch.features, batch.labels, batch.offsets, new_w)


def maybe_down_sample(batch: GLMBatch, task, rate, seed: int) -> GLMBatch:
    """Task-dispatching down-sample (GeneralizedLinearOptimizationProblem.
    downSample hook): binary-classification sampler for logistic tasks,
    uniform otherwise; no-op when rate is None or >= 1."""
    if rate is None or rate >= 1.0:
        return batch
    from photon_ml_tpu.types import TaskType

    sampler = (
        down_sample_binary if task == TaskType.LOGISTIC_REGRESSION else down_sample_default
    )
    return sampler(batch, rate, jax.random.PRNGKey(seed))
