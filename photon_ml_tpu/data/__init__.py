from photon_ml_tpu.data.sampler import down_sample_binary, down_sample_default

__all__ = ["down_sample_binary", "down_sample_default"]
