"""GLM driver parameters + command-line parser.

Reference spec: Params.scala:42-205 (param bean + cross-field validation
:175-197) and PhotonMLCmdLineParser.scala / OptionNames.scala:24-59 (flag
names, preserved verbatim for config parity — SURVEY.md Appendix A.1).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional

from photon_ml_tpu.diagnostics.types import DiagnosticMode
from photon_ml_tpu.types import (
    DataValidationType,
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
)

DEFAULT_MAX_ITERATIONS = 80
DEFAULT_TOLERANCE = 1e-6  # Params.scala:74 driver default (optimizer-class default is 1e-7)


class InputFormatType:
    AVRO = "AVRO"
    LIBSVM = "LIBSVM"


class FieldNamesType:
    """io/FieldNamesType.scala parity: the label field is "label" in
    TRAINING_EXAMPLE records and "response" in RESPONSE_PREDICTION ones."""

    TRAINING_EXAMPLE = "TRAINING_EXAMPLE"
    RESPONSE_PREDICTION = "RESPONSE_PREDICTION"


@dataclasses.dataclass
class GLMParams:
    """Typed param container (Params.scala:42-205 parity)."""

    training_data_dir: str = ""
    output_dir: str = ""
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    validating_data_dir: Optional[str] = None
    job_name: str = "photon-ml-tpu"
    regularization_weights: List[float] = dataclasses.field(default_factory=lambda: [0.1, 1.0, 10.0, 100.0])
    regularization_type: RegularizationType = RegularizationType.L2
    elastic_net_alpha: Optional[float] = None
    add_intercept: bool = True
    max_num_iterations: int = DEFAULT_MAX_ITERATIONS
    tolerance: float = DEFAULT_TOLERANCE
    field_names_type: str = FieldNamesType.TRAINING_EXAMPLE
    optimizer_type: OptimizerType = OptimizerType.LBFGS
    enable_optimization_state_tracker: bool = True
    validate_per_iteration: bool = False
    summarization_output_dir: Optional[str] = None
    normalization_type: NormalizationType = NormalizationType.NONE
    coefficient_box_constraints: Optional[str] = None
    data_validation_type: DataValidationType = DataValidationType.VALIDATE_FULL
    diagnostic_mode: DiagnosticMode = DiagnosticMode.NONE
    selected_features_file: Optional[str] = None
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: int = 1
    delete_output_dirs_if_exist: bool = False
    input_file_format: str = InputFormatType.AVRO
    feature_dimension: int = -1
    compute_variance: bool = False
    # out-of-core training: spill the ingested batch to row chunks of this
    # size and stream them through the optimizer (optim/streaming.py — the
    # StorageLevel.scala:22-24 DISK_ONLY answer); 0 = in-memory (default)
    streaming_chunk_rows: int = 0
    # content-addressed cache of the spilled stream chunks (io/tensor_cache):
    # a warm run over unchanged inputs skips decode + re-spill entirely
    tensor_cache_dir: Optional[str] = None
    # persistent XLA compilation cache (photon_ml_tpu.compat shims): a warm
    # run skips compilation entirely — composes with --tensor-cache
    persistent_cache_dir: Optional[str] = None
    # canonical shape ladder (photon_ml_tpu.compile): "off" | "on" |
    # "BASE:GROWTH" — stream-chunk row counts round up a geometric ladder
    # so the tail chunk shares the other chunks' compiled partial
    shape_canonicalization: str = "off"
    # obsolete on TPU (treeAggregate depth, kryo, min partitions) — accepted
    # for CLI compatibility, ignored with a note
    tree_aggregate_depth: int = 1
    use_kryo: bool = True
    min_num_partitions: int = 1

    def validate(self) -> None:
        """Cross-field validation (Params.scala:175-197 parity)."""
        errors = []
        if not self.training_data_dir:
            errors.append("--training-data-directory is required")
        if not self.output_dir:
            errors.append("--output-directory is required")
        if self.optimizer_type == OptimizerType.TRON and self.regularization_type in (
            RegularizationType.L1,
            RegularizationType.ELASTIC_NET,
        ):
            errors.append(
                f"TRON optimizer does not support {self.regularization_type.value} "
                "regularization"
            )
        if self.task_type == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM and (
            self.optimizer_type == OptimizerType.TRON
        ):
            errors.append("smoothed hinge loss is first-order only; use LBFGS")
        if self.regularization_type == RegularizationType.ELASTIC_NET:
            a = self.elastic_net_alpha
            if a is not None and not (0.0 <= a <= 1.0):
                errors.append(f"elastic net alpha must be in [0, 1], got {a}")
        for w in self.regularization_weights:
            if w < 0:
                errors.append(f"negative regularization weight {w}")
        if self.validate_per_iteration and self.validating_data_dir is None:
            errors.append("--validate-per-iteration requires --validating-data-directory")
        if self.streaming_chunk_rows > 0:
            if self.validate_per_iteration:
                errors.append(
                    "--streaming-chunk-rows does not keep per-iteration "
                    "coefficient snapshots (--validate-per-iteration)"
                )
            if self.diagnostic_mode != DiagnosticMode.NONE:
                errors.append(
                    "--streaming-chunk-rows does not support --diagnostic-mode "
                    "(diagnostics need the in-memory batch)"
                )
        try:
            from photon_ml_tpu.compile import resolve_bucketer

            resolve_bucketer(self.shape_canonicalization)
        except ValueError as e:
            errors.append(f"--shape-canonicalization: {e}")
        if self.diagnostic_mode.runs_validate and self.validating_data_dir is None:
            errors.append(
                f"diagnostic mode {self.diagnostic_mode.value} requires "
                "--validating-data-directory"
            )
        if errors:
            raise ValueError("; ".join(errors))


def _bool_flag(v: str) -> bool:
    return v.strip().lower() in ("true", "1", "yes")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu glm",
        description="Train a generalized linear model (reference Driver parity)",
    )
    a = p.add_argument
    a("--training-data-directory", dest="training_data_dir", required=True)
    a("--output-directory", dest="output_dir", required=True)
    a("--task", dest="task_type", required=True,
      choices=[t.value for t in TaskType])
    a("--validating-data-directory", dest="validating_data_dir", default=None)
    a("--job-name", dest="job_name", default="photon-ml-tpu")
    a("--regularization-weights", dest="regularization_weights",
      default="0.1,1,10,100", help="comma-separated lambda list")
    a("--regularization-type", dest="regularization_type", default="L2",
      choices=[t.value for t in RegularizationType])
    a("--elastic-net-alpha", dest="elastic_net_alpha", type=float, default=None)
    a("--intercept", dest="add_intercept", type=_bool_flag, default=True)
    a("--num-iterations", dest="max_num_iterations", type=int,
      default=DEFAULT_MAX_ITERATIONS)
    a("--convergence-tolerance", dest="tolerance", type=float, default=DEFAULT_TOLERANCE)
    a("--format", dest="field_names_type", default=FieldNamesType.TRAINING_EXAMPLE,
      choices=[FieldNamesType.TRAINING_EXAMPLE, FieldNamesType.RESPONSE_PREDICTION])
    a("--optimizer", dest="optimizer_type", default="LBFGS",
      choices=[t.value for t in OptimizerType])
    a("--optimization-tracker", dest="enable_optimization_state_tracker",
      type=_bool_flag, default=True)
    a("--validate-per-iteration", dest="validate_per_iteration",
      type=_bool_flag, default=False)
    a("--summarization-output-dir", dest="summarization_output_dir", default=None)
    a("--normalization-type", dest="normalization_type", default="NONE",
      choices=[t.value for t in NormalizationType])
    a("--coefficient-box-constraints", dest="coefficient_box_constraints", default=None)
    a("--data-validation-type", dest="data_validation_type", default="VALIDATE_FULL",
      choices=[t.value for t in DataValidationType])
    a("--diagnostic-mode", dest="diagnostic_mode", default="NONE",
      choices=[m.value for m in DiagnosticMode])
    a("--selected-features-file", dest="selected_features_file", default=None)
    a("--offheap-indexmap-dir", dest="offheap_indexmap_dir", default=None)
    a("--offheap-indexmap-num-partitions", dest="offheap_indexmap_num_partitions",
      type=int, default=1)
    a("--delete-output-dirs-if-exist", dest="delete_output_dirs_if_exist",
      type=_bool_flag, default=False)
    a("--input-file-format", dest="input_file_format", default=InputFormatType.AVRO,
      choices=[InputFormatType.AVRO, InputFormatType.LIBSVM])
    a("--feature-dimension", dest="feature_dimension", type=int, default=-1)
    a("--compute-variance", dest="compute_variance", type=_bool_flag, default=False)
    # accepted-but-obsolete Spark-era knobs
    a("--kryo", dest="use_kryo", type=_bool_flag, default=True)
    a("--min-partitions", dest="min_num_partitions", type=int, default=1)
    a("--tree-aggregate-depth", dest="tree_aggregate_depth", type=int, default=1)
    a("--streaming-chunk-rows", dest="streaming_chunk_rows", type=int, default=0,
      help="spill the training batch to row chunks of this size and stream "
           "them through the optimizer (out-of-core; 0 = in-memory)")
    a("--tensor-cache", dest="tensor_cache_dir", default=None,
      help="content-addressed on-disk cache of the spilled stream chunks "
           "(keyed by source file stats + ingest config): a warm "
           "--streaming-chunk-rows run skips decode + re-spill")
    a("--persistent-cache", dest="persistent_cache_dir", default=None,
      help="persistent XLA compilation cache dir: warm runs skip "
           "compilation entirely (composes with --tensor-cache)")
    a("--shape-canonicalization", dest="shape_canonicalization", default="off",
      help="round stream-chunk row counts up a geometric ladder of "
           "canonical shapes (masked padding; the tail chunk stops "
           "compiling its own kernel): off | on | BASE:GROWTH")
    return p


def parse_from_command_line(argv: Optional[List[str]] = None) -> GLMParams:
    ns = build_parser().parse_args(argv)
    params = GLMParams(
        training_data_dir=ns.training_data_dir,
        output_dir=ns.output_dir,
        task_type=TaskType(ns.task_type),
        validating_data_dir=ns.validating_data_dir,
        job_name=ns.job_name,
        regularization_weights=[float(w) for w in str(ns.regularization_weights).split(",") if w],
        regularization_type=RegularizationType(ns.regularization_type),
        elastic_net_alpha=ns.elastic_net_alpha,
        add_intercept=ns.add_intercept,
        max_num_iterations=ns.max_num_iterations,
        tolerance=ns.tolerance,
        field_names_type=ns.field_names_type,
        optimizer_type=OptimizerType(ns.optimizer_type),
        enable_optimization_state_tracker=ns.enable_optimization_state_tracker,
        validate_per_iteration=ns.validate_per_iteration,
        summarization_output_dir=ns.summarization_output_dir,
        normalization_type=NormalizationType(ns.normalization_type),
        coefficient_box_constraints=ns.coefficient_box_constraints,
        data_validation_type=DataValidationType(ns.data_validation_type),
        diagnostic_mode=DiagnosticMode(ns.diagnostic_mode),
        selected_features_file=ns.selected_features_file,
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        delete_output_dirs_if_exist=ns.delete_output_dirs_if_exist,
        input_file_format=ns.input_file_format,
        feature_dimension=ns.feature_dimension,
        compute_variance=ns.compute_variance,
        streaming_chunk_rows=ns.streaming_chunk_rows,
        tensor_cache_dir=ns.tensor_cache_dir,
        persistent_cache_dir=ns.persistent_cache_dir,
        shape_canonicalization=ns.shape_canonicalization,
        use_kryo=ns.use_kryo,
        min_num_partitions=ns.min_num_partitions,
        tree_aggregate_depth=ns.tree_aggregate_depth,
    )
    params.validate()
    return params
