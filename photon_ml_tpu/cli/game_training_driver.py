"""GAME (GLMix) training driver.

Reference spec: cli/game/training/Driver.scala:64-537 — prepare feature maps
(:475), load GAME data (:480), build per-coordinate datasets (:485), build
evaluators (:490-508), run the config grid x coordinate descent (:511,
:313-415), save best/all models in the reference's on-disk layout
(:424-463, ModelProcessingUtils layout).

TPU-native: coordinates hold device-resident tensors (entity-major stacks
for random effects); the grid reuses compiled update kernels across combos
with identical shapes; model save goes through io/model_io (Avro wire-format
parity).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent, CoordinateDescentResult
from photon_ml_tpu.algorithm.factored_random_effect import (
    FactoredRandomEffectCoordinate,
    FactoredState,
    MFOptimizationConfig,
)
from photon_ml_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_ml_tpu.cli.game_params import (
    CoordinateOptConfig,
    GameTrainingParams,
    parse_training_params,
)
from photon_ml_tpu.data.game import (
    GameData,
    RandomEffectDataConfig,
    build_fixed_effect_batch,
    build_random_effect_dataset,
    padded_row_coo,
)
from photon_ml_tpu.evaluation.evaluators import Evaluator, evaluator_for
from photon_ml_tpu.io import avro_data
from photon_ml_tpu.io import model_io
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.types import ModelOutputMode, TaskType, real_dtype
from photon_ml_tpu.utils.io_utils import prepare_output_dir
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer

DENSE_DIM_THRESHOLD = 4096
BEST_MODEL_DIR = "best"
ALL_MODELS_DIR = "all"


def _summarize_tracker(tracker) -> str:
    """Per-coordinate convergence summary from the last update's OptResult
    (the reference's per-coordinate OptimizationTracker logging,
    CoordinateDescent.scala:150-156 / RandomEffectOptimizationTracker).

    Distributed solvers trim entity padding at the source
    (``parallel.distributed.trim_entity_tracker``), so every tracker that
    arrives here covers real entities only.
    """
    import numpy as np

    from photon_ml_tpu.optim.common import (
        OptResult,
        summarize_result,
        summarize_stacked_results,
    )

    if tracker is None:
        return ""
    # OptResult IS a NamedTuple — test for it BEFORE the generic tuple
    # (bucketed) case or every tracker would fall into the tuple branch
    if isinstance(tracker, OptResult):
        if np.asarray(tracker.reason).ndim >= 1:
            return summarize_stacked_results(tracker)
        return summarize_result(tracker)
    if isinstance(tracker, tuple):  # bucketed: one OptResult per bucket
        parts = [_summarize_tracker(t) for t in tracker]
        return " | ".join(f"bucket{j}: {s}" for j, s in enumerate(parts) if s)
    return ""


def _input_files(dirs: List[str]) -> List[str]:
    files = []
    for d in dirs:
        if os.path.isfile(d):
            files.append(d)
        else:
            files.extend(
                os.path.join(d, f)
                for f in sorted(os.listdir(d))
                if not f.startswith((".", "_"))
            )
    return files


def resolve_date_range_dirs(
    dirs: List[str],
    date_range: Optional[str],
    days_ago: Optional[str],
) -> List[str]:
    """Expand input dirs into their daily/yyyy/MM/dd subdirs within the
    requested range (IOUtils.scala:85-130 discovery); no range -> unchanged."""
    if not date_range and not days_ago:
        return dirs
    from photon_ml_tpu.utils.date_range import DateRange, expand_date_range_paths

    dr = (
        DateRange.from_string(date_range)
        if date_range
        else DateRange.from_days_ago(days_ago)
    )
    out: List[str] = []
    for d in dirs:
        try:
            out.extend(expand_date_range_paths(d, dr))
        except FileNotFoundError:
            pass  # error only if the union over ALL dirs is empty (IOUtils parity)
    if not out:
        raise FileNotFoundError(
            f"no daily inputs under any of {dirs} within {dr.start}..{dr.end}"
        )
    return out


class GameTrainingDriver:
    """Builds coordinates from params + data, runs the grid, saves models."""

    def __init__(self, params: GameTrainingParams, logger: Optional[PhotonLogger] = None):
        params.validate()
        self.params = params
        from photon_ml_tpu.compile import ExecutionPlan, compile_stats

        # ONE execution plan resolves every orthogonal policy — shape
        # ladder, solve schedule (ladder-bound), sharding mode, sparse
        # selection — and records every composition decision; the
        # coordinates below all read from it instead of re-resolving flags
        self.plan = ExecutionPlan.resolve(
            shape_canonicalization=params.shape_canonicalization,
            solve_compaction=params.solve_compaction,
            adaptive_schedule=params.adaptive_schedule,
            distributed=params.distributed,
            streaming=params.streaming_random_effects,
            bucketed=params.bucketed_random_effects,
            fused_cycle=params.fused_cycle,
            vmapped_grid=params.vmapped_grid,
            plan=params.plan,
            # warm starts inherit the prior run's realized costs; cold runs
            # read back their own sidecar on the next invocation
            cost_model_dir=(params.warm_start_from or params.output_dir),
        )
        self.bucketer = self.plan.bucketer
        self.solve_schedule = self.plan.schedule
        compile_stats.install_xla_listeners()
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(
            os.path.join(params.output_dir, "photon-ml-tpu-game.log")
        )
        self.timer = Timer(self.logger.info)
        self.shard_index_maps: Dict[str, IndexMap] = {}
        self.train_data: Optional[GameData] = None
        self.validation_data: Optional[GameData] = None
        self.re_datasets: Dict[str, object] = {}
        self.bucketed_bundles: Dict[str, object] = {}  # --bucketed-random-effects
        self.streaming_manifests: Dict[str, object] = {}  # --streaming-random-effects
        self.fe_batches: Dict[str, object] = {}
        # combo results: (config map, CoordinateDescentResult, metrics)
        self.results: List[Tuple[Dict[str, CoordinateOptConfig], CoordinateDescentResult, Dict[str, float]]] = []
        self.combo_coords: List[Dict[str, object]] = []  # per-combo coordinates
        self.best_index: int = 0
        # --- incremental delta retraining (photon_ml_tpu.retrain) ---------
        self.retrain_prior = None  # prior run's RetrainManifest (or None)
        self.delta_plan = None  # resolved DeltaPlan (or None: cold run)
        self.block_deltas: Dict[str, list] = {}  # streaming coord -> [BlockDelta]
        self._train_files: List[str] = []
        self._frozen_blocks: Dict[str, frozenset] = {}  # coord -> skip set
        self._warm_fixed: Dict[str, np.ndarray] = {}
        self._warm_dense_re: Dict[str, np.ndarray] = {}
        self._warm_spilled: Dict[str, object] = {}  # coord -> SpilledREState
        self._warm_bucketed: Dict[str, list] = {}  # coord -> per-bucket stacks
        self._warm_means_cache: Dict[str, Optional[dict]] = {}
        self._coord_cache_keys: Dict[str, Optional[str]] = {}
        self._data_cache_key: Optional[str] = None
        self._eval_identity_cache: Optional[Dict[str, object]] = None

    # ------------------------------------------------------------------
    def _shard_ids(self) -> List[str]:
        p = self.params
        shards = {spec.feature_shard_id for spec in p.fixed_effect_data_configs.values()}
        shards |= {cfg.feature_shard_id for cfg in p.random_effect_data_configs.values()}
        return sorted(shards)

    def _train_dirs(self) -> List[str]:
        p = self.params
        return resolve_date_range_dirs(
            p.train_input_dirs, p.train_date_range, p.train_date_range_days_ago
        )

    def _validate_dirs(self) -> List[str]:
        p = self.params
        return resolve_date_range_dirs(
            p.validate_input_dirs or [],
            p.validate_date_range,
            p.validate_date_range_days_ago,
        )

    def prepare_feature_maps(self) -> None:
        """GAMEDriver.prepareFeatureMaps parity: offheap load (:76-82), the
        deprecated NameAndTerm vocabulary path, or whole-dataset scan
        (:49-69) — in that priority order."""
        p = self.params
        paths = _input_files(self._train_dirs())
        nt_container = None
        if p.feature_name_and_term_set_path and not p.offheap_indexmap_dir:
            from photon_ml_tpu.io.name_and_term import NameAndTermFeatureSetContainer

            # resolve sections PER SHARD (incl. the "features" default for
            # unconfigured shards) so no shard silently gets an empty vocab
            all_sections = sorted(
                {
                    s
                    for shard in self._shard_ids()
                    for s in (p.feature_shard_sections.get(shard) or ["features"])
                }
            )
            nt_container = NameAndTermFeatureSetContainer.read_from_text(
                p.feature_name_and_term_set_path, all_sections
            )
        for shard in self._shard_ids():
            if p.offheap_indexmap_dir:
                from photon_ml_tpu.io.offheap import load_shard_index_map

                self.shard_index_maps[shard] = load_shard_index_map(
                    p.offheap_indexmap_dir, shard
                )
            elif nt_container is not None:
                sections = p.feature_shard_sections.get(shard) or ["features"]
                add_intercept = p.feature_shard_intercepts.get(shard, True)
                self.shard_index_maps[shard] = nt_container.index_map(
                    sections, add_intercept
                )
            else:
                sections = p.feature_shard_sections.get(shard) or ["features"]
                keys = avro_data.collect_feature_keys(paths, sections)
                add_intercept = p.feature_shard_intercepts.get(shard, True)
                self.shard_index_maps[shard] = IndexMap.build(keys, add_intercept)
            self.logger.info(
                f"feature shard {shard!r}: {len(self.shard_index_maps[shard])} features"
            )

    # ------------------------------------------------------------------
    def _id_types(self) -> List[str]:
        """Random-effect grouping ids + any id column an evaluator needs
        (e.g. PRECISION@K:documentId)."""
        ids = {cfg.random_effect_id for cfg in self.params.random_effect_data_configs.values()}
        ids |= {id_name for _, _, id_name in self.params.evaluators if id_name}
        return sorted(ids)

    def _next_stream_state_seq(self) -> int:
        self._stream_state_seq = getattr(self, "_stream_state_seq", 0) + 1
        return self._stream_state_seq

    def _tensor_cache(self):
        """The --tensor-cache store (lazy), or None."""
        if not self.params.tensor_cache_dir:
            return None
        if not hasattr(self, "_tensor_cache_obj"):
            from photon_ml_tpu.io.tensor_cache import TensorCache

            self._tensor_cache_obj = TensorCache(self.params.tensor_cache_dir)
        return self._tensor_cache_obj

    def _ingest_cache_config(self) -> Dict[str, object]:
        """The ingest-config part of every tensor-cache key: anything that
        changes the decoded columns or the feature index assignment must
        change the key (a config change is a MISS, never a stale hit) —
        including the canonical shape ladder, which changes the PADDED
        tensors a hit would serve."""
        from photon_ml_tpu.io.tensor_cache import index_map_digest

        p = self.params
        return {
            "sections": p.feature_shard_sections,
            "intercepts": p.feature_shard_intercepts,
            "id_types": self._id_types(),
            "ladder": (
                f"{self.bucketer.base}:{self.bucketer.growth:g}"
                if self.bucketer is not None else None
            ),
            "index_maps": {
                shard: index_map_digest(imap)
                for shard, imap in sorted(self.shard_index_maps.items())
            },
        }

    # --- incremental delta retraining (photon_ml_tpu.retrain) -------------
    def _ingest_inputs(self) -> Dict[str, object]:
        """The PRE-feature-map ingest identity (JSON-safe by construction):
        everything that determines the decoded columns and feature space
        given the input files. Equality with the prior manifest's record
        (plus unchanged files) proves the whole ingest output is identical
        — the delta planner's cheap short-circuit check; the full
        index-map-digest equality (:meth:`_ingest_digest`) gates
        block-level reuse after feature maps build."""
        p = self.params
        return {
            "sections": {k: list(v) for k, v in sorted(
                (p.feature_shard_sections or {}).items())},
            "intercepts": {k: bool(v) for k, v in sorted(
                (p.feature_shard_intercepts or {}).items())},
            "id_types": self._id_types(),
            "ladder": (
                f"{self.bucketer.base}:{self.bucketer.growth:g}"
                if self.bucketer is not None else None
            ),
            "offheap_indexmap_dir": p.offheap_indexmap_dir,
            "name_and_term": p.feature_name_and_term_set_path,
        }

    def _eval_identity(self) -> Dict[str, object]:
        """Validation-side identity (validation file stats + evaluator
        specs): gates the delta short-circuit only — a changed validation
        set must re-score, even when training has nothing left to do.
        Computed ONCE, before the validation files are read (_run_guarded
        snapshots it next to the train stat tokens): like the train side,
        a file overwritten mid-run is recorded with its pre-overwrite
        identity so tomorrow's diff classifies it changed — and a
        validation file deleted mid-run cannot fail the manifest write of
        an otherwise-completed training run."""
        if self._eval_identity_cache is None:
            from photon_ml_tpu.io.tensor_cache import file_stat_token

            p = self.params
            val_files = (
                _input_files(self._validate_dirs())
                if p.validate_input_dirs else []
            )
            self._eval_identity_cache = {
                "validate_files": file_stat_token(val_files),
                "evaluators": [
                    [etype.value, k, id_name]
                    for etype, k, id_name in (p.evaluators or [])
                ],
            }
        return self._eval_identity_cache

    def _ingest_digest(self) -> str:
        """SHA-256 of the FULL ingest cache config (incl. per-shard index
        map digests) — the feature-space identity block reuse requires."""
        import hashlib as _hashlib
        import json as _json

        return _hashlib.sha256(
            _json.dumps(
                self._ingest_cache_config(), sort_keys=True, default=str
            ).encode()
        ).hexdigest()

    def _maybe_plan_delta(self, train_files: List[str]) -> None:
        """Load the prior manifest and resolve the delta plan
        (--warm-start-from). ANY failure reading the prior degrades to a
        recorded cold run — a broken prior must never produce a wrong warm
        result (chaos-covered via the retrain.delta_plan fault site)."""
        p = self.params
        if not p.warm_start_from:
            return
        from photon_ml_tpu import retrain

        try:
            self.retrain_prior = retrain.load_prior_manifest(p.warm_start_from)
            combos = p.config_grid()
            combo_configs = None
            if len(combos) == 1:
                combo_configs = {
                    name: str(combos[0].get(name, CoordinateOptConfig()))
                    for name in p.updating_sequence
                }
            # classification stays INSIDE the guard: a parseable-but-
            # malformed manifest (bad file_stats entries, wrong field
            # shapes) surfaces here, not as a crashed training run
            self.delta_plan = retrain.plan_delta(
                self.retrain_prior,
                train_files,
                task=p.task_type.value,
                updating_sequence=p.updating_sequence,
                ingest_inputs=self._ingest_inputs(),
                combo_configs=combo_configs,
                eval_identity=self._eval_identity(),
            )
        except Exception as e:  # noqa: BLE001 — any unreadable/corrupt/malformed prior (bad JSON, vanished model, bad stat tokens, injected fault) must degrade to a cold run, never propagate into a wrong warm result
            self.retrain_prior = None
            self.delta_plan = None
            self.logger.warn(
                f"--warm-start-from {p.warm_start_from}: prior manifest "
                f"unusable ({type(e).__name__}: {e}) — retraining cold"
            )
            return
        self.logger.info(
            f"delta retrain plan: files {self.delta_plan.files.describe()}; "
            + " ".join(
                f"{n}={c.status}"
                for n, c in self.delta_plan.coordinates.items()
            )
        )
        for line in self.delta_plan.describe_decisions():
            self.logger.info(f"delta retrain: {line}")

    def _dirty_entities(self) -> Dict[str, set]:
        """Raw entity ids whose data moved (probed once from the changed/
        new files' id columns — cost scales with the delta)."""
        if self.delta_plan is None:
            return {}
        if not self.delta_plan.dirty_entities:
            from photon_ml_tpu import retrain

            self.delta_plan.dirty_entities = retrain.probe_dirty_entities(
                self.delta_plan.files, self._id_types()
            )
            for t, s in sorted(self.delta_plan.dirty_entities.items()):
                self.logger.info(
                    f"delta retrain: {len(s)} dirty {t!r} entities"
                )
        return self.delta_plan.dirty_entities

    def prepare_datasets(self) -> None:
        from photon_ml_tpu.data.game import (
            game_data_from_arrays,
            game_data_to_arrays,
        )

        p = self.params
        cache = self._tensor_cache()
        # reuse the file list the delta plan + manifest stat tokens were
        # computed from (one file set for plan, ingest, AND retrain.json
        # — a part file landing between the listings would otherwise be
        # ingested while the plan still says 'unchanged'); the fallback
        # covers direct prepare_datasets() calls outside run()
        train_files = self._train_files or _input_files(self._train_dirs())
        self._train_files = train_files
        train_key = (
            cache.key_for(
                train_files, {"kind": "game_data", **self._ingest_cache_config()}
            )
            if cache is not None
            else None
        )
        self._data_cache_key = train_key
        if (
            cache is not None
            and self.retrain_prior is not None
            and self.retrain_prior.data_cache_key
            and self.retrain_prior.data_cache_key != train_key
        ):
            # cache hygiene: the prior run's whole-set ingest entry can
            # never be addressed again (its file stats are history) —
            # invalidate it so the store stays bounded across daily deltas.
            # Streaming-block entries are deliberately KEPT: the prior
            # manifest dir (which the block reuse below reads) may BE one.
            if cache.invalidate(self.retrain_prior.data_cache_key):
                self.logger.info(
                    "tensor cache: invalidated superseded prior ingest "
                    f"entry {self.retrain_prior.data_cache_key[:12]}"
                )
        hit = cache.get(train_key) if cache is not None else None
        if hit is not None:
            self.train_data = game_data_from_arrays(hit.arrays, hit.meta)
            self.logger.info(
                f"tensor cache HIT {train_key[:12]}: Avro decode skipped"
            )
        else:
            self.train_data = avro_data.read_game_data(
                train_files,
                self.shard_index_maps,
                p.feature_shard_sections,
                self._id_types(),
                shard_intercepts=p.feature_shard_intercepts or None,
            )
            if cache is not None:
                from photon_ml_tpu.resilience import RetryError

                try:
                    arrays, meta = game_data_to_arrays(self.train_data)
                    cache.put(train_key, arrays, meta)
                    self.logger.info(f"tensor cache stored {train_key[:12]}")
                except RetryError as e:
                    self.logger.info(f"tensor cache write failed (uncached): {e}")
        self.logger.info(f"training rows: {self.train_data.num_rows}")
        if p.validate_input_dirs:
            self.validation_data = avro_data.read_game_data(
                _input_files(self._validate_dirs()),
                self.shard_index_maps,
                p.feature_shard_sections,
                self._id_types(),
                shard_intercepts=p.feature_shard_intercepts or None,
                id_vocabs=self.train_data.id_vocabs,
            )
            self.logger.info(f"validation rows: {self.validation_data.num_rows}")

        for name, spec in p.fixed_effect_data_configs.items():
            dense = len(self.shard_index_maps[spec.feature_shard_id]) <= DENSE_DIM_THRESHOLD
            self.fe_batches[name] = build_fixed_effect_batch(
                self.train_data, spec.feature_shard_id, dense=dense
            )
        for name, cfg in p.random_effect_data_configs.items():
            if name in p.factored_configs and cfg.projector != "IDENTITY":
                # the factored coordinate factors the UNprojected dataset
                cfg = RandomEffectDataConfig(
                    **{**cfg.__dict__, "projector": "IDENTITY"}
                )
            if p.streaming_random_effects and name not in p.factored_configs:
                # out-of-core: write the entity blocks to disk ONCE (each
                # block built and released in turn — the full stack never
                # exists); combos stream the same blocks
                from photon_ml_tpu.algorithm.streaming_random_effect import (
                    write_re_entity_blocks,
                )

                budget = (
                    int(p.re_memory_budget_mb * 1e6)
                    if p.re_memory_budget_mb is not None else None
                )
                block_key = (
                    cache.key_for(
                        train_files,
                        {"kind": "streaming_re_blocks", "coord": name,
                         "config": dataclasses.asdict(cfg),
                         "budget": budget,
                         **self._ingest_cache_config()},
                    )
                    if cache is not None else None
                )
                self._coord_cache_keys[name] = block_key
                if self._delta_streaming_build(
                    name, cfg, budget, cache, train_files
                ):
                    continue
                self.streaming_manifests[name] = write_re_entity_blocks(
                    self.train_data, cfg,
                    os.path.join(p.output_dir, "streaming-re", name),
                    # `is None`, not falsy: a (rejected-downstream) zero
                    # budget must not silently pass BOTH sizing modes
                    block_entities=None if budget is not None else 1024,
                    memory_budget_bytes=budget,
                    # "off", never None: the plan consumed the env already
                    bucketer=self.bucketer or "off",
                    tensor_cache=cache,
                    cache_key=block_key,
                )
                self.logger.info(
                    f"streaming RE {name}: "
                    f"{len(self.streaming_manifests[name].blocks)} blocks, "
                    f"max resident slab "
                    f"{self.streaming_manifests[name].max_block_bytes}B"
                )
                continue
            if p.bucketed_random_effects and name not in p.factored_configs:
                # bucketed coordinates own per-bucket stacks — building the
                # single globally-padded stack here would allocate exactly
                # the memory bucketing exists to avoid. Build the shared
                # bundle ONCE; combos reuse it.
                from photon_ml_tpu.algorithm.bucketed_random_effect import (
                    BucketedDatasetBundle,
                )

                self.bucketed_bundles[name] = BucketedDatasetBundle.build(
                    self.train_data, cfg, bucketer=self.bucketer or "off"
                )
                continue
            re_key = (
                cache.key_for(
                    train_files,
                    {"kind": "re_dataset", "coord": name,
                     "config": dataclasses.asdict(cfg),
                     **self._ingest_cache_config()},
                )
                if cache is not None else None
            )
            self._coord_cache_keys[name] = re_key
            if (
                cache is not None
                and self.retrain_prior is not None
                and (prior_rec := self.retrain_prior.coordinates.get(name))
                is not None
                and prior_rec.kind == "random"
                and prior_rec.cache_key
                and prior_rec.cache_key != re_key
            ):
                # superseded in-memory RE dataset entry (warm starts read
                # the saved MODEL, never the cached dataset) — same
                # hygiene as the whole-set ingest entry above
                cache.invalidate(prior_rec.cache_key)
            self.re_datasets[name] = build_random_effect_dataset(
                self.train_data, cfg,
                tensor_cache=cache,
                cache_key=re_key,
            )

    def _load_prior_layout(self, name: str, rec):
        """The prior run's streaming block layout, or None with the
        degrade logged — ONE load-and-degrade contract shared by the
        unchanged-verbatim-reuse and dirty-delta-build paths (a vanished/
        corrupt prior layout costs a recorded cold rebuild, never a
        failed run or stale blocks)."""
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingREManifest,
        )

        try:
            return StreamingREManifest.load(rec.streaming_manifest_dir)
        except Exception as e:  # noqa: BLE001 — a vanished/corrupt prior block layout (lost cache entry) must degrade to a recorded cold build, never fail or warm wrongly
            self.logger.warn(
                f"delta retrain [{name}]: prior block layout at "
                f"{rec.streaming_manifest_dir} unusable "
                f"({type(e).__name__}: {e}) — cold block build"
            )
            return None

    def _delta_streaming_build(
        self, name: str, cfg, budget: Optional[int], cache, train_files,
    ) -> bool:
        """Build ``name``'s entity blocks through the DELTA builder (prior
        blocking pinned, unchanged payloads reused, per-block
        classification recorded) when the plan says the coordinate is
        dirty and the prior run's blocks are reusable. Returns True when
        it handled the build; False falls back to the cold builder with
        the degrade reason logged."""
        p = self.params
        plan = self.delta_plan
        prior = self.retrain_prior
        if plan is None or prior is None:
            return False
        cdelta = plan.coordinates.get(name)
        rec = prior.coordinates.get(name)
        if cdelta is None or rec is None:
            return False
        if (
            cdelta.status == "unchanged"
            and rec.kind == "streaming_random"
            and rec.streaming_manifest_dir
            and prior.ingest_digest == self._ingest_digest()
        ):
            # the whole coordinate is unchanged (clean files + identical
            # ingest): the prior block layout is verbatim THIS run's — no
            # rebuild, no re-decode, just open it (row space and vocab are
            # identical by construction). Falls through to the cold build
            # if the durable layout has since vanished.
            prior_sm = self._load_prior_layout(name, rec)
            if prior_sm is None:
                return False
            self.streaming_manifests[name] = prior_sm
            self._coord_cache_keys[name] = rec.cache_key
            self.logger.info(
                f"delta retrain [{name}]: coordinate unchanged — prior "
                f"block layout reused verbatim ({len(prior_sm.blocks)} "
                "blocks, no rebuild)"
            )
            return True
        if cdelta.status != "dirty":
            return False
        if rec.kind != "streaming_random" or not rec.streaming_manifest_dir:
            self.logger.info(
                f"delta retrain [{name}]: prior coordinate was "
                f"{rec.kind!r}, not streaming — cold block build"
            )
            return False
        if prior.ingest_digest != self._ingest_digest():
            self.logger.info(
                f"delta retrain [{name}]: feature space changed since the "
                "prior run (index-map digests differ) — block reuse off, "
                "cold block build (warm start stays on, by feature name)"
            )
            return False
        from photon_ml_tpu import retrain

        prior_sm = self._load_prior_layout(name, rec)
        if prior_sm is None:
            return False
        dirty_raw = self._dirty_entities().get(cfg.random_effect_id, set())
        delta_key = (
            cache.key_for(
                train_files,
                {"kind": "streaming_re_blocks_delta", "coord": name,
                 "config": dataclasses.asdict(cfg), "budget": budget,
                 "prior": prior.model_dir,
                 "dirty": retrain.dirty_set_digest(dirty_raw),
                 **self._ingest_cache_config()},
            )
            if cache is not None else None
        )
        manifest, deltas = retrain.build_delta_streaming_manifest(
            self.train_data, cfg,
            os.path.join(p.output_dir, "streaming-re", name),
            prior_sm, dirty_raw,
            bucketer=self.bucketer or "off",
            block_entities=None if budget is not None else 1024,
            memory_budget_bytes=budget,
            tensor_cache=cache,
            cache_key=delta_key,
        )
        self.streaming_manifests[name] = manifest
        self.block_deltas[name] = deltas
        if delta_key is not None:
            self._coord_cache_keys[name] = delta_key
        by_status = {"unchanged": 0, "dirty": 0, "new": 0}
        for d in deltas:
            by_status[d.status] = by_status.get(d.status, 0) + 1
        self.logger.info(
            f"delta retrain [{name}]: {len(deltas)} blocks — "
            f"{by_status['unchanged']} unchanged (solve skipped, payload "
            f"reused), {by_status['dirty']} dirty, {by_status['new']} new"
        )
        return True

    # ------------------------------------------------------------------
    def _mesh_context(self):
        """One MeshContext over all visible devices (lazy; --distributed)."""
        if not hasattr(self, "_mesh_ctx"):
            from photon_ml_tpu.parallel import MeshContext, data_mesh

            self._mesh_ctx = MeshContext(data_mesh())
            self.logger.info(
                f"distributed: {self._mesh_ctx.num_devices}-device mesh"
            )
        return self._mesh_ctx

    def _build_coordinates(self, opt_configs: Dict[str, CoordinateOptConfig]) -> Dict[str, object]:
        """Coordinate objects per updating sequence
        (cli/game/training/Driver.scala:344-402). With --distributed, fixed
        effects solve row-sharded, random effects entity-sharded, and
        factored coordinates entity-sharded with a psum'd latent refit over
        the device mesh."""
        p = self.params
        coords: Dict[str, object] = {}
        for name in p.updating_sequence:
            cfg = opt_configs.get(name, CoordinateOptConfig())
            if name in p.fixed_effect_data_configs:
                fe = FixedEffectCoordinate(
                    self.fe_batches[name],
                    GLMOptimizationProblem(
                        task=p.task_type,
                        optimizer=cfg.optimizer,
                        optimizer_config=cfg.optimizer_config(),
                        regularization=cfg.regularization_context(),
                        # variance is computed ONCE at save time from the
                        # final state (coefficient_variances), not per
                        # update inside the CD loop
                    ),
                    down_sampling_rate=(
                        cfg.down_sampling_rate if cfg.down_sampling_rate < 1.0 else None
                    ),
                )
                if p.distributed:
                    from photon_ml_tpu.parallel.distributed import (
                        DistributedFixedEffectCoordinate,
                    )

                    fe = DistributedFixedEffectCoordinate(fe, self._mesh_context())
                coords[name] = fe
            elif name in p.factored_configs:
                spec = p.factored_configs[name]
                fac = FactoredRandomEffectCoordinate(
                    self.re_datasets[name],
                    p.task_type,
                    mf_config=MFOptimizationConfig(
                        spec.mf_num_iterations, spec.latent_dim
                    ),
                    re_optimizer=spec.random_effect.optimizer,
                    re_optimizer_config=spec.random_effect.optimizer_config(),
                    re_regularization=spec.random_effect.regularization_context(),
                    latent_optimizer=spec.latent_factor.optimizer,
                    latent_optimizer_config=spec.latent_factor.optimizer_config(),
                    latent_regularization=spec.latent_factor.regularization_context(),
                )
                if p.distributed:
                    from photon_ml_tpu.parallel.distributed import (
                        DistributedFactoredRandomEffectCoordinate,
                    )

                    fac = DistributedFactoredRandomEffectCoordinate(
                        fac, self._mesh_context()
                    )
                coords[name] = fac
            elif p.streaming_random_effects:
                from photon_ml_tpu.algorithm.streaming_random_effect import (
                    StreamingRandomEffectCoordinate,
                )

                common = dict(
                    task=p.task_type,
                    optimizer=cfg.optimizer,
                    optimizer_config=cfg.optimizer_config(),
                    regularization=cfg.regularization_context(),
                    # the plan threads schedule + sparse selection +
                    # prefetch in one object (compaction and the sparse
                    # race now reach the streaming path)
                    plan=self.plan,
                    # delta retrain: blocks classified unchanged skip
                    # their solves (coefficients carry forward bitwise
                    # from the warm-seeded state; empty/None when cold)
                    frozen_blocks=self._frozen_blocks.get(name),
                    # warm delta retrain seeds the adaptive convergence
                    # ledger from the prior run's record so importance
                    # ordering survives across runs (manifest-sidecar
                    # ledgers, when present, still win inside the coord)
                    ledger_seed=(
                        rec.convergence_ledger
                        if self.retrain_prior is not None
                        and (rec := self.retrain_prior.coordinates.get(name))
                        is not None
                        else None
                    ),
                    # spilled state goes under OUR output dir, never inside
                    # the manifest dir (a --tensor-cache hit points that at
                    # the shared cache entry, which must stay run-agnostic);
                    # unique per coordinate INSTANCE like the coordinate's
                    # own default (grid combos must not share spill dirs)
                    state_root=os.path.join(
                        p.output_dir, "streaming-re-state",
                        f"{name}-{os.getpid()}-{self._next_stream_state_seq()}",
                    ),
                )
                if p.distributed:
                    # entity-sharded streaming (the streaming x distributed
                    # fence is gone): under this single-process driver the
                    # mesh holds one process, so the merges are identities
                    # and results are bitwise the plain streaming run's.
                    # Genuinely multi-process runs MUST use the multihost
                    # driver — its manifests are per-host partitions of an
                    # agreed plan. This driver's manifest holds ALL blocks,
                    # so wiring num_processes>1 here would psum P identical
                    # full score vectors (P-times-counted, silently wrong):
                    # refuse loudly instead.
                    import jax as _jax

                    from photon_ml_tpu.parallel.perhost_streaming import (
                        PerHostStreamingRandomEffectCoordinate,
                    )

                    if _jax.process_count() > 1:
                        raise ValueError(
                            "--streaming-random-effects with --distributed "
                            "under a multi-process runtime requires the "
                            "multihost driver (game_multihost_driver): this "
                            "driver's single-host manifest owns every block "
                            "on every process, so merging would "
                            f"{_jax.process_count()}x-count the scores"
                        )
                    coords[name] = PerHostStreamingRandomEffectCoordinate(
                        manifest=self.streaming_manifests[name],
                        ctx=self._mesh_context(),
                        num_processes=1,
                        **common,
                    )
                else:
                    coords[name] = StreamingRandomEffectCoordinate(
                        manifest=self.streaming_manifests[name], **common
                    )
            elif p.bucketed_random_effects:
                from photon_ml_tpu.algorithm.bucketed_random_effect import (
                    BucketedRandomEffectCoordinate,
                )

                coords[name] = BucketedRandomEffectCoordinate(
                    self.train_data,
                    p.random_effect_data_configs[name],
                    p.task_type,
                    optimizer=cfg.optimizer,
                    optimizer_config=cfg.optimizer_config(),
                    regularization=cfg.regularization_context(),
                    bundle=self.bucketed_bundles[name],
                    mesh_ctx=self._mesh_context() if p.distributed else None,
                    solve_schedule=self.solve_schedule,
                    adaptive=self.plan.adaptive,
                )
            else:
                scheduled_mesh = p.distributed and self.solve_schedule is not None
                re = RandomEffectCoordinate(
                    self.re_datasets[name],
                    p.task_type,
                    optimizer=cfg.optimizer,
                    optimizer_config=cfg.optimizer_config(),
                    regularization=cfg.regularization_context(),
                    solve_schedule=self.solve_schedule,
                    solve_label=name,
                    # distributed solves pin sparse off at the shard level
                    # — don't race/build a slab the solver will discard
                    sparse_kernel="off" if p.distributed else None,
                    # compaction x mesh (the old fence is gone): the
                    # coordinate pads + GSPMD-shards its entity axis and
                    # runs the scheduler's shared chunk kernels over the
                    # sharded arrays — the compaction loop stays host-side
                    # outside the mesh program (the mesh path's allclose
                    # numerical contract, like the shard_map engine)
                    mesh_ctx=self._mesh_context() if scheduled_mesh else None,
                )
                if p.distributed and not scheduled_mesh:
                    # one-shot mesh solves keep the measured shard_map engine
                    from photon_ml_tpu.parallel.distributed import (
                        DistributedRandomEffectSolver,
                    )

                    re = DistributedRandomEffectSolver(re, self._mesh_context())
                coords[name] = re
        return coords

    # ------------------------------------------------------------------
    def _training_loss_fn(self):
        """Training-objective loss evaluator over total scores
        (the loss-evaluator analogue of Driver.scala:185-202)."""
        loss = losses_mod.for_task(self.params.task_type)
        labels = jnp.asarray(self.train_data.response)
        offsets = jnp.asarray(self.train_data.offset)
        weights = jnp.asarray(self.train_data.weight)

        def fn(total_scores):
            return jnp.sum(weights * loss.loss(total_scores + offsets, labels))

        return fn

    # ------------------------------------------------------------------
    def _entity_position_of_vocab(self, name: str) -> np.ndarray:
        """raw-vocab index -> tensor position in coordinate ``name``'s
        stacked coefficients (built from training rows)."""
        cfg = self.params.random_effect_data_configs[name]
        ids = self.train_data.ids[cfg.random_effect_id]
        ds = self.re_datasets[name]
        entity_pos = np.asarray(ds.entity_pos)
        vocab_size = len(self.train_data.id_vocabs[cfg.random_effect_id])
        pos = np.full(vocab_size, -1, np.int32)
        # only rows that carry a real tensor position: dropped-passive rows
        # have entity_pos -1 and must not clobber their entity's mapping
        known = entity_pos >= 0
        pos[ids[known]] = entity_pos[known]
        return pos

    def _validation_scorer(self, coords: Dict[str, object]):
        """coefficients map -> (Nv,) margin scores on validation data.

        Fixed effects score via matvec; random effects back-project to the
        global feature space and gather per validation row (the
        RandomEffectModel.scala:129-158 cogroup as static gathers). Rows of
        unseen entities contribute 0.
        """
        p = self.params
        vdata = self.validation_data
        nv = vdata.num_rows
        fe_feats = {}
        re_info = {}
        for name in p.updating_sequence:
            if name in p.fixed_effect_data_configs:
                spec = p.fixed_effect_data_configs[name]
                dense = len(self.shard_index_maps[spec.feature_shard_id]) <= DENSE_DIM_THRESHOLD
                fe_feats[name] = build_fixed_effect_batch(
                    vdata, spec.feature_shard_id, dense=dense
                ).features
            else:
                cfg = p.random_effect_data_configs[name]
                # padded per-row COO of validation rows in the GLOBAL space
                cols, vals = padded_row_coo(vdata.shards[cfg.feature_shard_id])
                vocab_ids = vdata.ids[cfg.random_effect_id]
                coord = coords.get(name)
                from photon_ml_tpu.algorithm.bucketed_random_effect import (
                    BucketedRandomEffectCoordinate,
                )
                from photon_ml_tpu.algorithm.streaming_random_effect import (
                    StreamingRandomEffectCoordinate,
                )

                if isinstance(
                    coord,
                    (BucketedRandomEffectCoordinate, StreamingRandomEffectCoordinate),
                ):
                    # map each validation row into the CONCATENATED stack:
                    # bucket/block row offset + within-unit tensor position
                    bucket_of, pos_in_bucket = coord.vocab_position_maps()
                    sizes = coord.stack_sizes()
                    offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int32)
                    safe_vid = np.maximum(vocab_ids, 0)
                    b_of = bucket_of[safe_vid]
                    p_in = pos_in_bucket[safe_vid]
                    ent_pos = np.where(
                        (vocab_ids >= 0) & (b_of >= 0) & (p_in >= 0),
                        offsets[np.maximum(b_of, 0)] + p_in,
                        -1,
                    ).astype(np.int32)
                    re_info[name] = (
                        jnp.asarray(cols), jnp.asarray(vals),
                        ("bucketed", coord, jnp.asarray(ent_pos)),
                    )
                else:
                    pos_of_vocab = self._entity_position_of_vocab(name)
                    ent_pos = np.where(
                        vocab_ids >= 0, pos_of_vocab[np.maximum(vocab_ids, 0)], -1
                    ).astype(np.int32)
                    re_info[name] = (
                        jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(ent_pos)
                    )

        def scorer(params_map):
            from photon_ml_tpu.algorithm.random_effect import global_coefficients

            total = jnp.zeros((nv,), jnp.float32)
            for name in p.updating_sequence:
                w = params_map[name]
                if name in fe_feats:
                    total = total + fe_feats[name].matvec(w)
                else:
                    cols, vals, info = re_info[name]
                    if isinstance(info, tuple) and info and info[0] == "bucketed":
                        # concatenate the per-bucket stacks once: entity
                        # position = bucket row offset + within-bucket pos,
                        # then the SAME single gather as the plain path
                        _, coord, ent_pos = info
                        wg = jnp.concatenate(
                            coord.global_coefficient_stacks(w), axis=0
                        )
                    else:
                        ent_pos = info
                        ds = self.re_datasets[name]
                        if isinstance(w, FactoredState):
                            wg = w.v @ w.matrix  # (E, D_global): IDENTITY local space
                        else:
                            # distributed solves pad the entity axis; slice back
                            wg = global_coefficients(ds, w[: ds.num_entities])
                    safe_pos = jnp.maximum(ent_pos, 0)
                    safe_cols = jnp.maximum(cols, 0)
                    gathered = wg[safe_pos[:, None], safe_cols]
                    valid = (ent_pos[:, None] >= 0) & (cols >= 0)
                    total = total + jnp.sum(
                        jnp.where(valid, gathered * vals, 0.0), axis=-1
                    )
            return total + jnp.asarray(vdata.offset)

        return scorer

    def _validation_evaluators(self) -> Dict[str, Tuple[Evaluator, dict]]:
        p = self.params
        vdata = self.validation_data
        labels = jnp.asarray(vdata.response)
        weights = jnp.asarray(vdata.weight)
        out: Dict[str, Tuple[Evaluator, dict]] = {}
        specs = p.evaluators or _default_evaluators(p.task_type)
        for etype, k, id_name in specs:
            ev = evaluator_for(etype, k or 10)
            kwargs = {"labels": labels, "weights": weights}
            if id_name is not None:
                kwargs["group_ids"] = jnp.asarray(vdata.ids[id_name])
            key = etype.value if k is None else f"{etype.value}@{k}"
            out[key] = (ev, kwargs)
        return out

    # --- warm starts (photon_ml_tpu.retrain.warm) ----------------------
    def _prior_entity_means(self, name: str):
        """Prior per-entity global rows for coordinate ``name`` (cached;
        None when the prior model lacks it or it is factored)."""
        if name not in self._warm_means_cache:
            from photon_ml_tpu import retrain

            cfg = self.params.random_effect_data_configs[name]
            self._warm_means_cache[name] = retrain.random_effect_entity_means(
                self.retrain_prior.model_dir, name,
                self.shard_index_maps[cfg.feature_shard_id],
            )
        return self._warm_means_cache[name]

    def _prepare_warm_starts(self) -> None:
        """Build every coordinate's warm-start state from the prior model
        (once; combos share them) and resolve the frozen-block sets.
        Paths without a warm representation (factored latent state,
        bucketed stacks, distributed padded shards) stay cold with a
        logged reason — a recorded decision, never a silent wrong warm."""
        if self.retrain_prior is None or self.delta_plan is None:
            return
        p = self.params
        if p.distributed:
            self.logger.info(
                "delta retrain: --distributed solvers manage their own "
                "sharded/padded state — warm starts off (cold solves)"
            )
            return
        from photon_ml_tpu import retrain

        prior = self.retrain_prior
        combos = p.config_grid()
        single = combos[0] if len(combos) == 1 else None
        for name in p.updating_sequence:
            cdelta = self.delta_plan.coordinates.get(name)
            if cdelta is None or cdelta.status == "new":
                continue
            if name in p.factored_configs:
                self.logger.info(
                    f"delta retrain [{name}]: factored latent state does "
                    "not round-trip through dense rows — cold solve"
                )
                continue
            if name in p.fixed_effect_data_configs:
                spec = p.fixed_effect_data_configs[name]
                w = retrain.fixed_effect_init(
                    prior.model_dir, name,
                    self.shard_index_maps[spec.feature_shard_id],
                )
                if w is not None:
                    self._warm_fixed[name] = w
                continue
            if p.bucketed_random_effects and name in self.bucketed_bundles:
                means = self._prior_entity_means(name)
                if means is None:
                    self.logger.info(
                        f"delta retrain [{name}]: prior model has no "
                        "reusable coefficients for this bucketed "
                        "coordinate — cold solve"
                    )
                    continue
                self._warm_bucketed[name] = retrain.bucketed_random_effect_init(
                    means, self.bucketed_bundles[name]
                )
                self.logger.info(
                    f"delta retrain [{name}]: warm-starting "
                    f"{len(self._warm_bucketed[name])} bucket stacks from "
                    "the prior model (gathered through the bucket layout)"
                )
                continue
            means = self._prior_entity_means(name)
            if means is None:
                self.logger.info(
                    f"delta retrain [{name}]: prior model has no reusable "
                    "coefficients for this coordinate — cold solve"
                )
                continue
            cfg = p.random_effect_data_configs[name]
            if name in self.streaming_manifests:
                seed_dir = os.path.join(p.output_dir, "retrain-warm", name)
                self._warm_spilled[name] = retrain.seed_spilled_state(
                    self.streaming_manifests[name], means, seed_dir
                )
                deltas = self.block_deltas.get(name)
                rec = prior.coordinates.get(name)
                cfg_now = (
                    str(single.get(name, CoordinateOptConfig()))
                    if single is not None else None
                )
                if deltas and rec is not None and cfg_now == rec.opt_config:
                    self._frozen_blocks[name] = frozenset(
                        d.index for d in deltas if d.status == "unchanged"
                    )
                    self.logger.info(
                        f"delta retrain [{name}]: freezing "
                        f"{len(self._frozen_blocks[name])}/{len(deltas)} "
                        "unchanged blocks (solves skipped, coefficients "
                        "bitwise from the prior model)"
                    )
                elif deltas:
                    self.logger.info(
                        f"delta retrain [{name}]: optimization grid "
                        "differs from the prior selected combo — no block "
                        "freezing (warm start only)"
                    )
            else:
                ds = self.re_datasets[name]
                self._warm_dense_re[name] = retrain.dense_random_effect_init(
                    means,
                    vocab=self.train_data.id_vocabs[cfg.random_effect_id],
                    pos_of_vocab=self._entity_position_of_vocab(name),
                    local_to_global=np.asarray(ds.local_to_global),
                )

    def _warm_init(self) -> Optional[Dict[str, object]]:
        """The per-coordinate warm-start params dict (shared across
        combos; CD copies donated leaves per combo), or None when cold."""
        out: Dict[str, object] = {}
        for n, w in self._warm_fixed.items():
            out[n] = jnp.asarray(w)
        for n, w in self._warm_dense_re.items():
            out[n] = jnp.asarray(w)
        for n, stacks in self._warm_bucketed.items():
            # per-bucket stacks mirror initial_coefficients()'s tuple
            out[n] = tuple(jnp.asarray(w) for w in stacks)
        out.update(self._warm_spilled)
        return out or None

    def _frozen_coordinate_names(self, warm_init) -> set:
        """Coordinates the plan froze AND we could warm-seed — freezing
        without the prior coefficients would freeze zeros."""
        if self.delta_plan is None:
            return set()
        frozen = self.delta_plan.frozen_coordinates()
        out = {n for n in frozen if warm_init is not None and n in warm_init}
        for n in sorted(frozen - out):
            self.logger.warn(
                f"delta retrain [{n}]: classified unchanged but no warm "
                "state could be built — re-solving instead of freezing"
            )
        if out and self.plan.cycle_fusion == "full":
            self.logger.info(
                "delta retrain: --fused-cycle compiles every coordinate "
                "into one program — frozen coordinates re-solve warm "
                "instead of skipping"
            )
            return set()
        return out

    # ------------------------------------------------------------------
    def _vmapped_grid_blocker(self, combos) -> Optional[str]:
        """Why --vmapped-grid cannot apply, or None when it can: the grid
        must vary ONLY per-coordinate lambda on plain fixed/random
        coordinates, with no orthogonal machinery that cannot nest under
        vmap (sharding) or that needs per-combo static coordinates."""
        p = self.params
        if len(combos) < 2:
            return "grid has a single combo"
        if p.distributed:
            return "--distributed (shard_map cannot nest under the combo vmap)"
        if p.bucketed_random_effects:
            return "--bucketed-random-effects (static per-bucket lambdas)"
        if p.streaming_random_effects:
            return "--streaming-random-effects (host streaming cannot vmap)"
        if p.factored_configs:
            return "factored coordinates (lambda lives in nested configs)"
        if p.compute_variance:
            return "--compute-variance (save-time Hessians need per-combo statics)"
        # --checkpoint-dir no longer blocks the grid: run_grid lands
        # PER-CYCLE checkpoints (params/scores/total lane pytree at every
        # iteration boundary) — only per-UPDATE granularity is inherently
        # unavailable (updates live inside the compiled cycle)
        if p.divergence_guard != "off":
            return "--divergence-guard (per-update host gate cannot enter the compiled cycle)"
        if self.solve_schedule is not None:
            return "--solve-compaction (chunk pauses re-enter the host per update)"
        import dataclasses as _dc

        for name in p.updating_sequence:
            # compare configs with lambda zeroed: any OTHER field differing
            # blocks the vmap (and a future CoordinateOptConfig field
            # automatically participates in this check)
            non_lambda = {
                _dc.replace(c.get(name, CoordinateOptConfig()), reg_weight=0.0)
                for c in combos
            }
            if len(non_lambda) > 1:
                return f"combos vary beyond lambda for coordinate {name!r}"
        return None

    def _grid_cd(self, combos, loss_fn):
        """(coords, CoordinateDescent, evaluators, primary) for the
        traced-lambda grid — built once so every combo reuses the single
        compiled cycle."""
        coords = self._build_coordinates(combos[0])
        scorer = None
        evaluators = None
        primary = None
        if self.validation_data is not None:
            scorer = self._validation_scorer(coords)
            evaluators = self._validation_evaluators()
            if evaluators:
                primary = next(iter(evaluators))
        cd = CoordinateDescent(coords, loss_fn, scorer, evaluators)
        return coords, cd, evaluators, primary

    def _grid_lambdas(self, combos):
        return {
            name: jnp.asarray(
                [c.get(name, CoordinateOptConfig()).reg_weight for c in combos],
                real_dtype(),
            )
            for name in self.params.updating_sequence
        }

    def _make_checkpointer(self, combo_index: int, opt_configs, grid: bool = False):
        """Per-combo checkpointer (async-wrapped under --checkpoint-async);
        None without --checkpoint-dir. Grid and per-combo runs fingerprint
        differently — their step granularities must never cross-resume."""
        p = self.params
        if not p.checkpoint_dir:
            return None
        from photon_ml_tpu.checkpoint import (
            CoordinateDescentCheckpointer,
            fingerprint,
        )
        from photon_ml_tpu.checkpoint_async import maybe_async

        return maybe_async(
            CoordinateDescentCheckpointer(
                os.path.join(p.checkpoint_dir, f"combo-{combo_index}"),
                # num_iterations intentionally excluded: extending a
                # finished run with more iterations IS the resume case
                run_fingerprint=fingerprint(
                    {
                        "coordinates": p.updating_sequence,
                        "num_rows": self.train_data.num_rows,
                        "combo": combo_index,
                        "configs": {k: str(v) for k, v in opt_configs.items()},
                        **({"grid": True} if grid else {}),
                    }
                ),
            ),
            p.checkpoint_async,
        )

    @staticmethod
    def _close_checkpointer(checkpointer) -> None:
        """Fence + stop an async checkpointer (no-op for the sync one):
        every commit durable — and any background failure surfaced —
        before models are saved or the run retires."""
        if checkpointer is not None and hasattr(checkpointer, "close"):
            checkpointer.close()

    def _train_shared_compile_grid(self, combos, loss_fn,
                                   init_params=None) -> None:
        """All grid combos through the traced-lambda grid API
        (CoordinateDescent.run_grid): ONE compiled cycle serves every
        combo; results and best_index land in self.results exactly like
        the per-combo rebuild path. With --checkpoint-dir each combo
        checkpoints per cycle and resumes from its last complete
        iteration. ``init_params`` (delta retrain) seeds EVERY lambda lane
        from the prior run's selected model — the PR-2 warm-start hook
        generalized to per-coordinate GAME warm starts."""
        p = self.params
        coords, cd, evaluators, primary = self._grid_cd(combos, loss_fn)
        lam = self._grid_lambdas(combos)
        checkpointers = (
            [
                self._make_checkpointer(i, combos[i], grid=True)
                for i in range(len(combos))
            ]
            if p.checkpoint_dir
            else None
        )
        from photon_ml_tpu.utils.profiling import maybe_trace

        try:
            with self.timer.measure("shared-compile-grid"), maybe_trace("game-grid"):
                grid_results = cd.run_grid(
                    lam, p.num_iterations, self.train_data.num_rows,
                    init_params=init_params,
                    checkpointers=checkpointers,
                )
        finally:
            for ck in checkpointers or ():
                self._close_checkpointer(ck)
        best_value: Optional[float] = None
        for i, (opt_configs, result) in enumerate(zip(combos, grid_results)):
            metrics = result.validation_history[-1] if result.validation_history else {}
            self.combo_coords.append(coords)
            self.results.append((opt_configs, result, metrics))
            self.logger.info(
                f"combo {i} (grid): objective={result.objective_history[-1]:.6g} "
                + " ".join(f"{k}={v:.6g}" for k, v in metrics.items())
            )
            if primary is not None and metrics:
                ev = evaluators[primary][0]
                value = metrics[primary]
                if best_value is None or ev.better_than(value, best_value):
                    best_value = value
                    self.best_index = i

    # ------------------------------------------------------------------
    def train(self) -> None:
        p = self.params
        loss_fn = self._training_loss_fn()
        combos = p.config_grid()
        primary: Optional[str] = None
        best_value: Optional[float] = None
        self._prepare_warm_starts()
        warm_init = self._warm_init()
        frozen = self._frozen_coordinate_names(warm_init)

        if p.vmapped_grid in ("true", "auto"):
            # the batched G-lane variant this flag once selected lost the
            # measured race on every platform three rounds running and was
            # REMOVED (VERDICT r4 #9); the flag now always routes through
            # the sequential shared-compile grid API — exactly what the old
            # auto-selector picked every time it measured
            blocker = (
                "delta-frozen coordinates (the per-coordinate skip lives "
                "outside the compiled grid cycle)"
                if frozen else self._vmapped_grid_blocker(combos)
            )
            if blocker is None:
                self.logger.info(
                    "--vmapped-grid: training through the shared-compile "
                    "grid (the batched G-lane variant was removed; "
                    "sequential won every measured race)"
                    + (" — every lane warm-started from the prior model"
                       if warm_init else "")
                )
                self._train_shared_compile_grid(
                    combos, loss_fn, init_params=warm_init
                )
                return
            else:
                self.logger.warn(
                    f"--vmapped-grid requested but falling back to the "
                    f"per-combo rebuild grid: {blocker}"
                )

        for i, opt_configs in enumerate(combos):
            coords = self._build_coordinates(opt_configs)
            scorer = None
            evaluators = None
            if self.validation_data is not None:
                scorer = self._validation_scorer(coords)
                evaluators = self._validation_evaluators()
                if primary is None and evaluators:
                    primary = next(iter(evaluators))
            checkpointer = self._make_checkpointer(i, opt_configs)
            guard = None
            if p.divergence_guard != "off":
                from photon_ml_tpu.resilience import DivergenceGuard

                guard = DivergenceGuard(mode=p.divergence_guard)
            self.combo_coords.append(coords)
            cd = CoordinateDescent(
                coords, loss_fn, scorer, evaluators,
                # full-cycle fusion only when the plan resolved it so:
                # under compaction/streaming the flag promotes to per-solve
                # fusion (cycle_fusion="solve", the device scheduler loop)
                # and the descent loop itself stays host-side
                fused_cycle=self.plan.cycle_fusion == "full",
                divergence_guard=guard,
            )
            from photon_ml_tpu.utils.profiling import maybe_trace

            try:
                with self.timer.measure(f"combo-{i}"), maybe_trace(f"game-combo-{i}"):
                    result = cd.run(
                        p.num_iterations, self.train_data.num_rows,
                        checkpointer,
                        initial_params=warm_init,
                        frozen=frozen,
                    )
            finally:
                # async fence: every commit durable (and any background
                # commit failure surfaced) before this combo retires —
                # on the preemption path the emergency save already fenced
                self._close_checkpointer(checkpointer)
            metrics = result.validation_history[-1] if result.validation_history else {}
            self.results.append((opt_configs, result, metrics))
            self.logger.info(
                f"combo {i}: objective={result.objective_history[-1]:.6g} "
                + " ".join(f"{k}={v:.6g}" for k, v in metrics.items())
            )
            for ev in result.guard_events:
                self.logger.warn(
                    f"combo {i}: divergence guard {ev.action} at coordinate "
                    f"{ev.coordinate!r} step {ev.step} ({ev.detail})"
                )
            for cname, tracker in result.trackers.items():
                summary = _summarize_tracker(tracker)
                if summary:
                    self.logger.info(f"combo {i} [{cname}] {summary}")
            if primary is not None and metrics:
                ev = evaluators[primary][0]
                value = metrics[primary]
                if best_value is None or ev.better_than(value, best_value):
                    best_value = value
                    self.best_index = i

    # ------------------------------------------------------------------
    def _entity_means_global(self, name: str, coefficients) -> Dict[str, np.ndarray]:
        """Stacked coefficients -> {raw entity id: dense global-space row}."""
        from photon_ml_tpu.algorithm.random_effect import global_coefficients

        cfg = self.params.random_effect_data_configs[name]
        ds = self.re_datasets[name]
        if isinstance(coefficients, FactoredState):
            wg = np.asarray(coefficients.v @ coefficients.matrix)
            return self._rows_by_raw_id(name, wg)
        # distributed solves pad the entity axis; slice back to E
        coeffs = jnp.asarray(coefficients)[: ds.num_entities]
        return self._rows_by_raw_id(
            name, np.asarray(global_coefficients(ds, coeffs))
        )

    def _rows_by_raw_id(self, name: str, rows: np.ndarray) -> Dict[str, np.ndarray]:
        """(E, D_global) stack -> {raw entity id: row} via the vocab map."""
        cfg = self.params.random_effect_data_configs[name]
        pos_of_vocab = self._entity_position_of_vocab(name)
        vocab = self.train_data.id_vocabs[cfg.random_effect_id]
        out: Dict[str, np.ndarray] = {}
        for vi, raw in enumerate(vocab):
            tp = pos_of_vocab[vi]
            if tp >= 0:
                out[raw] = rows[tp]
        return out

    def _entity_latent_factors(self, name: str, state: FactoredState) -> Dict[str, np.ndarray]:
        """FactoredState.v rows keyed by raw entity id (for LatentFactorAvro)."""
        cfg = self.params.random_effect_data_configs[name]
        v = np.asarray(state.v)
        pos_of_vocab = self._entity_position_of_vocab(name)
        vocab = self.train_data.id_vocabs[cfg.random_effect_id]
        out: Dict[str, np.ndarray] = {}
        for vi, raw in enumerate(vocab):
            tp = pos_of_vocab[vi]
            if tp >= 0:
                out[raw] = v[tp]
        return out

    def save_models(self, output_dir: str, result: CoordinateDescentResult,
                    combo_index: Optional[int] = None) -> None:
        p = self.params

        def _wants_variances(name) -> bool:
            """THE --compute-variance gate, shared by every save branch
            (RandomEffectOptimizationProblem isComputingVariance parity)."""
            if not p.compute_variance or combo_index is None:
                return False
            cfg = p.random_effect_data_configs.get(name)
            if cfg is not None and cfg.projector == "RANDOM":
                # a diagonal variance does not survive a dense random
                # back-projection; the reference has the same limitation
                self.logger.warn(
                    f"[{name}] variances skipped: RANDOM-projected space"
                )
                return False
            return True

        def _variances_for(name, coeffs):
            """Per-coordinate 1/H_jj at the final state; residual = total
            minus this coordinate's own score."""
            if not _wants_variances(name):
                return None
            coord = self.combo_coords[combo_index].get(name)
            if coord is None or not hasattr(coord, "coefficient_variances"):
                return None
            resid = result.total_scores - coord.score(coeffs)
            return coord.coefficient_variances(coeffs, resid)

        for name in p.updating_sequence:
            coeffs = result.coefficients[name]
            if name in p.fixed_effect_data_configs:
                spec = p.fixed_effect_data_configs[name]
                fe_var = _variances_for(name, coeffs)
                model_io.save_fixed_effect(
                    output_dir,
                    name,
                    p.task_type,
                    np.asarray(coeffs),
                    self.shard_index_maps[spec.feature_shard_id],
                    variances=None if fe_var is None else np.asarray(fe_var),
                    feature_shard_id=spec.feature_shard_id,
                )
            else:
                from photon_ml_tpu.algorithm.bucketed_random_effect import (
                    BucketedRandomEffectCoordinate,
                )
                from photon_ml_tpu.algorithm.streaming_random_effect import (
                    StreamingRandomEffectCoordinate,
                )

                if p.bucketed_random_effects or p.streaming_random_effects:
                    if combo_index is None or not (
                        0 <= combo_index < len(self.combo_coords)
                    ):
                        raise ValueError(
                            "save_models on a bucketed/streaming random-"
                            "effects run needs the combo_index of the result "
                            "being saved (the per-bucket/per-block "
                            "coefficients are extracted through that combo's "
                            "coordinate objects)"
                        )
                    coord = self.combo_coords[combo_index].get(name)
                else:
                    coord = None
                cfg = p.random_effect_data_configs[name]
                entity_variances = None
                if isinstance(
                    coord,
                    (BucketedRandomEffectCoordinate, StreamingRandomEffectCoordinate),
                ):
                    resid = (
                        result.total_scores - coord.score(coeffs)
                        if _wants_variances(name)
                        else None
                    )
                    entity_means, entity_variances = coord.entity_export_by_raw_id(
                        coeffs, resid
                    )
                else:
                    entity_means = self._entity_means_global(name, coeffs)
                    if not isinstance(coeffs, FactoredState):
                        re_var = _variances_for(name, coeffs)
                        if re_var is not None:
                            from photon_ml_tpu.algorithm.random_effect import (
                                global_coefficients,
                            )

                            ds = self.re_datasets[name]
                            # mesh-scheduled coordinates compute variances
                            # over their PADDED entity axis; slice back to
                            # this (unpadded) dataset's extent, same as
                            # the means path above
                            entity_variances = self._rows_by_raw_id(
                                name,
                                np.asarray(global_coefficients(
                                    ds, re_var[: ds.num_entities]
                                )),
                            )
                model_io.save_random_effect(
                    output_dir,
                    name,
                    p.task_type,
                    entity_means,
                    self.shard_index_maps[cfg.feature_shard_id],
                    random_effect_id=cfg.random_effect_id,
                    feature_shard_id=cfg.feature_shard_id,
                    num_files=p.num_output_files_re_model,
                    entity_variances=entity_variances,
                )
                if isinstance(coeffs, FactoredState):
                    # persist the factored STRUCTURE too (latent coefficients
                    # + shared matrix, LatentFactorAvro — AvroUtils.scala:
                    # 244-266): the projected-back coefficients above are for
                    # scoring compat, but alone they cannot reconstruct the
                    # model (VERDICT r2 missing #3)
                    model_io.save_factored_random_effect(
                        output_dir,
                        name,
                        self._entity_latent_factors(name, coeffs),
                        np.asarray(coeffs.matrix),
                        random_effect_id=cfg.random_effect_id,
                        feature_shard_id=cfg.feature_shard_id,
                        num_files=p.num_output_files_re_model,
                        index_map=self.shard_index_maps[cfg.feature_shard_id],
                    )

    # ------------------------------------------------------------------
    def _resilience_config(self):
        """Process-wide ingest resilience settings from the driver flags
        (corrupt-shard policy + I/O retry/backoff), installed for the whole
        run so every read path — feature scan, dataset load, checkpoint —
        behaves consistently."""
        import dataclasses

        from photon_ml_tpu import resilience

        p = self.params
        # flags override attempts/base-delay; the rest of the policy keeps
        # the env-tunable defaults (PHOTON_IO_RETRY_MAX_DELAY / _DEADLINE)
        return resilience.ResilienceConfig(
            on_corrupt=p.on_corrupt,
            corrupt_skip_budget=p.corrupt_skip_budget,
            io_policy=dataclasses.replace(
                resilience.RetryPolicy.io_default(),
                max_attempts=p.io_retries,
                base_delay=p.io_retry_base_delay,
            ),
        )

    def run(self, restart: bool = False) -> None:
        """``restart=True`` (a supervised relaunch after a preemption)
        keeps the existing output dir: the streaming entity blocks, spilled
        coordinate state, and logs written by the interrupted attempt are
        exactly what the checkpoint's by-reference entries resume from."""
        from photon_ml_tpu import resilience

        with resilience.resilience_scope(self._resilience_config()):
            self._run_guarded(restart)

    def _run_guarded(self, restart: bool = False) -> None:
        p = self.params
        if restart:
            os.makedirs(p.output_dir, exist_ok=True)
        else:
            prepare_output_dir(p.output_dir, p.delete_output_dir_if_exists)
        if p.persistent_cache_dir:
            from photon_ml_tpu import compat

            if compat.enable_persistent_cache(p.persistent_cache_dir):
                self.logger.info(
                    f"persistent XLA compilation cache: {p.persistent_cache_dir}"
                )
            else:
                self.logger.warn(
                    "--persistent-cache requested but this jax has no "
                    "compilation-cache API; compiling uncached"
                )
        self.logger.info(self.plan.describe())
        for line in self.plan.describe_decisions():
            self.logger.info(f"execution plan: {line}")
        try:
            train_files = _input_files(self._train_dirs())
            self._train_files = train_files
            # stat tokens captured NOW — before ingest — so the manifest
            # describes the files this run is ABOUT to read (the tensor
            # cache's own discipline): a file overwritten mid-training is
            # recorded with its pre-overwrite identity and tomorrow's
            # delta run classifies it CHANGED, never wrongly frozen
            from photon_ml_tpu.io.tensor_cache import file_stat_token

            self._train_file_stats = file_stat_token(train_files)
            self._eval_identity()  # snapshot the validation side pre-read too
            self._maybe_plan_delta(train_files)
            if self.delta_plan is not None and self.delta_plan.short_circuit:
                # nothing changed: the prior model IS this run's result —
                # re-export it bitwise, skip ingest and training entirely
                with self.timer.measure("delta-short-circuit"):
                    self._short_circuit_run()
                self._log_run_summaries()
                return
            with self.timer.measure("prepare-feature-maps"):
                self.prepare_feature_maps()
            with self.timer.measure("prepare-datasets"):
                self.prepare_datasets()
            with self.timer.measure("train"):
                self.train()
            if p.model_output_mode != ModelOutputMode.NONE:
                best_dir = os.path.join(p.output_dir, BEST_MODEL_DIR)
                self.save_models(
                    best_dir, self.results[self.best_index][1], self.best_index
                )
                self.logger.info(
                    f"saved best model (combo {self.best_index}) to {best_dir}"
                )
                if p.model_output_mode == ModelOutputMode.ALL:
                    for i, (_, result, _) in enumerate(self.results):
                        self.save_models(
                            os.path.join(p.output_dir, ALL_MODELS_DIR, str(i)),
                            result,
                            i,
                        )
                self._record_realized_costs()
                self._write_retrain_manifest(best_dir)
                self._export_store(best_dir)
            elif p.warm_start_from or p.export_serve_store:
                self.logger.warn(
                    "--model-output-mode NONE: no saved model, so no "
                    "retrain manifest / serving store can be written"
                )
            self._log_run_summaries()
        finally:
            if self._own_logger:
                self.logger.close()

    def _log_run_summaries(self) -> None:
        p = self.params
        self.logger.info(self.timer.summary())
        from photon_ml_tpu.compile import compile_stats

        self.logger.info(compile_stats.summary())
        if self.solve_schedule is not None or self.plan.adaptive is not None:
            from photon_ml_tpu.optim.scheduler import solve_stats

            self.logger.info(solve_stats.summary())
        if self.plan.adaptive is not None:
            # every adaptive skip/degrade is a recorded decision; surface
            # them in the log like the plan's own composition decisions
            for combo in self.combo_coords:
                for name, coord in combo.items():
                    for dec in getattr(coord, "skip_decisions", ()) or ():
                        self.logger.info(f"[{name}] {dec.describe()}")
        if p.tensor_cache_dir:
            from photon_ml_tpu.io.tensor_cache import cache_stats

            self.logger.info(cache_stats.summary())
        if p.persistent_cache_dir and compile_stats.xla_cache_misses == 0:
            self.logger.info(
                "persistent cache fully warm: zero new XLA compiles"
            )

    # --- delta-retrain output side (photon_ml_tpu.retrain) --------------
    def _short_circuit_run(self) -> None:
        """All-unchanged rerun: copy the prior model forward bitwise and
        re-export — 0 solves, 0 new XLA compiles, no ingest."""
        import shutil

        p = self.params
        prior = self.retrain_prior
        best_dir = os.path.join(p.output_dir, BEST_MODEL_DIR)
        if os.path.abspath(prior.model_dir) != os.path.abspath(best_dir):
            shutil.copytree(prior.model_dir, best_dir, dirs_exist_ok=True)
        self.logger.info(
            "delta retrain: inputs, configuration, and grid identical to "
            f"the prior run — prior model reused wholesale at {best_dir} "
            "(0 solves, 0 new XLA compiles)"
        )
        self._write_retrain_manifest(best_dir, short_circuit=True)
        self._export_store(best_dir)

    def _record_realized_costs(self) -> None:
        """Close the planner loop (--plan auto): attach this run's realized
        costs — from the same stats registries the planner predicts over —
        to the plan's decisions, fold them into the cost model, and persist
        the ``cost-model.json`` sidecar beside ``retrain.json`` so the next
        run (or ``fleetctl status --plan``) starts from observed reality.
        No-op under --plan off: the sidecar only exists when planning is on."""
        if getattr(self.plan, "plan_mode", "off") != "auto":
            return
        from photon_ml_tpu.compile import compile_stats
        from photon_ml_tpu.compile.cost import TRACE_COST

        p = self.params
        from photon_ml_tpu.optim.scheduler import solve_stats

        sched_cost = solve_stats.realized_plan_cost()
        if sched_cost is not None:
            self.plan.record_realized("schedule", sched_cost)
            # sharding's realized burden is the same executed-iteration
            # ledger the lanes produced, minus the pause tariff
            self.plan.record_realized(
                "sharding",
                float(solve_stats.totals()["executed_lane_iterations"]),
            )
        traces = compile_stats.total_traces()
        if traces:
            self.plan.record_realized("ladder", TRACE_COST * float(traces))
        # blocking realized = per-block imbalance from the best combo's
        # convergence ledgers (the quantity reblock_recommendation gates on)
        block_costs = self._ledger_block_costs()
        if block_costs:
            self.plan.record_realized(
                "blocking", max(block_costs) / max(1e-9, min(block_costs))
            )
        path = self.plan.save_cost_model(p.output_dir)
        if path:
            self.logger.info(f"plan cost model written: {path}")
            for dec in self.plan.decisions:
                if dec.realized_cost is not None:
                    self.logger.info(dec.describe())

    def _plan_cost_model_json(self) -> Optional[dict]:
        """The plan's cost model for retrain.json — None under --plan off
        (the manifest field stays absent, bitwise-identical to before)."""
        if getattr(self.plan, "plan_mode", "off") != "auto":
            return None
        model = self.plan.cost_model
        return model.to_json() if model is not None else None

    def _ledger_block_costs(self) -> list:
        """Best-combo per-block observed costs (empty when no coordinate
        kept a convergence ledger) — the planner's blocking-drift signal."""
        costs: list = []
        if not self.combo_coords:
            return costs
        if not (0 <= self.best_index < len(self.combo_coords)):
            return costs
        for coord in self.combo_coords[self.best_index].values():
            ledger = getattr(coord, "_ledger", None)
            observed = getattr(ledger, "observed_costs", None)
            if callable(observed):
                try:
                    costs.extend(float(c) for c in observed().values())
                except Exception:  # lint: broad-except — blocking drift is advisory telemetry; a malformed ledger on one coordinate must never fail the training run
                    continue
        return costs

    def _write_retrain_manifest(self, best_dir: str,
                                short_circuit: bool = False) -> None:
        """Leave this run's ``retrain.json`` for the next run's planner."""
        from photon_ml_tpu.io.tensor_cache import file_stat_token
        from photon_ml_tpu.retrain import RetrainManifest
        from photon_ml_tpu.retrain.manifest import CoordinateRecord

        p = self.params
        # pre-ingest stat tokens (captured in _run_guarded); re-stat'ing
        # here would record a mid-run overwrite as this run's identity
        file_stats = getattr(self, "_train_file_stats", None)
        if file_stats is None:
            file_stats = file_stat_token(
                self._train_files or _input_files(self._train_dirs())
            )
        if short_circuit:
            prior = self.retrain_prior
            manifest = RetrainManifest(
                output_dir=os.path.abspath(p.output_dir),
                model_dir=os.path.abspath(best_dir),
                task=p.task_type.value,
                file_stats=file_stats,
                ingest_inputs=self._ingest_inputs(),
                # inputs identical by construction: the prior's digests and
                # durable block layouts remain this run's identity too
                ingest_digest=prior.ingest_digest,
                updating_sequence=list(p.updating_sequence),
                coordinates=dict(prior.coordinates),
                data_cache_key=prior.data_cache_key,
                eval_identity=self._eval_identity(),
                cost_model=self._plan_cost_model_json(),
            )
        else:
            combos = p.config_grid()
            sel = combos[self.best_index] if self.results else combos[0]
            coords: Dict[str, CoordinateRecord] = {}
            for name in p.updating_sequence:
                if name in p.fixed_effect_data_configs:
                    kind = "fixed"
                elif name in p.factored_configs:
                    kind = "factored"
                elif name in self.streaming_manifests:
                    kind = "streaming_random"
                elif p.bucketed_random_effects:
                    kind = "bucketed"
                else:
                    kind = "random"
                sm = self.streaming_manifests.get(name)
                # the best combo's convergence ledger rides along so the
                # next run's adaptive schedule starts warm (None when the
                # coordinate kind has no ledger or the run kept none)
                ledger = None
                if self.combo_coords and 0 <= self.best_index < len(
                    self.combo_coords
                ):
                    coord = self.combo_coords[self.best_index].get(name)
                    export = getattr(coord, "ledger_export", None)
                    if callable(export):
                        ledger = export() or None
                coords[name] = CoordinateRecord(
                    kind=kind,
                    opt_config=str(sel.get(name, CoordinateOptConfig())),
                    cache_key=self._coord_cache_keys.get(name),
                    streaming_manifest_dir=(
                        os.path.abspath(sm.dir) if sm is not None else None
                    ),
                    shard_plan_version=int(
                        getattr(sm, "plan_version", 1) if sm is not None else 1
                    ),
                    convergence_ledger=ledger,
                )
            manifest = RetrainManifest(
                output_dir=os.path.abspath(p.output_dir),
                model_dir=os.path.abspath(best_dir),
                task=p.task_type.value,
                file_stats=file_stats,
                ingest_inputs=self._ingest_inputs(),
                ingest_digest=self._ingest_digest(),
                updating_sequence=list(p.updating_sequence),
                coordinates=coords,
                data_cache_key=self._data_cache_key,
                eval_identity=self._eval_identity(),
                cost_model=self._plan_cost_model_json(),
            )
        path = manifest.save(p.output_dir)
        self.logger.info(f"retrain manifest written: {path}")

    def _export_store(self, best_dir: str) -> None:
        """--export-serve-store: the trained model as an mmap'd serving
        store — what a live ScoringServer/fleet hot-swaps in (the
        retrain->swap loop's handoff artifact)."""
        p = self.params
        if not p.export_serve_store:
            return
        from photon_ml_tpu.compile import ShapeBucketer
        from photon_ml_tpu.serve.model_store import build_model_store

        with self.timer.measure("export-serve-store"):
            build_model_store(
                best_dir, p.export_serve_store,
                bucketer=self.bucketer or ShapeBucketer(),
                store_dtype=p.store_dtype,
            )
        self.logger.info(
            f"serving store exported: {p.export_serve_store} "
            f"(dtype {p.store_dtype}; swap it into a live server via "
            "serve.swap.ModelSwapper / the fleet generation barrier)"
        )


def _default_evaluators(task: TaskType):
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType

    default = {
        TaskType.LOGISTIC_REGRESSION: EvaluatorType.AUC,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: EvaluatorType.AUC,
        TaskType.LINEAR_REGRESSION: EvaluatorType.RMSE,
        TaskType.POISSON_REGRESSION: EvaluatorType.POISSON_LOSS,
    }[task]
    return [(default, None, None)]


def main(argv: Optional[List[str]] = None) -> GameTrainingDriver:
    import logging
    import sys

    from photon_ml_tpu.resilience import preemption

    params = parse_training_params(argv)

    def run_once(attempt: int) -> GameTrainingDriver:
        driver = GameTrainingDriver(params)
        driver.run(restart=attempt > 0)
        return driver

    def on_restart(attempt: int, e: preemption.Preempted) -> None:
        logging.getLogger(__name__).warning(
            "preempted (%s); relaunching from the latest checkpoint "
            "(restart %d/%d)", e, attempt, params.max_restarts
        )

    # SIGTERM/SIGINT become cooperative preemption requests for the whole
    # run; the loops drain to the nearest safe boundary, write an emergency
    # checkpoint, and either relaunch in-process (--max-restarts) or exit
    # with the distinct preemption code for tools/run_supervised.py
    with preemption.signal_scope():
        try:
            return preemption.run_with_restarts(
                run_once, params.max_restarts, on_restart=on_restart
            )
        except preemption.Preempted as e:
            print(
                f"photon-ml-tpu: preempted ({e}); emergency checkpoint "
                f"{e.checkpoint_path or '(no --checkpoint-dir)'}; "
                f"exiting {preemption.PREEMPT_EXIT_CODE}",
                file=sys.stderr,
            )
            raise SystemExit(preemption.PREEMPT_EXIT_CODE) from e


if __name__ == "__main__":
    main()
