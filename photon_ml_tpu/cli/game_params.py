"""GAME driver parameters: delimited-string configs + command-line parsers.

Reference spec: cli/game/training/Params.scala:196-395 and the config string
grammars (SURVEY.md Appendix A.2/A.3):

  per-coordinate optimization config (GLMOptimizationConfiguration.scala:41-75):
      maxIter,tol,regWeight,downSamplingRate,optimizer,regType
  coordinate map: "name:cfg|name2:cfg2", grid alternatives ';'-separated
  fixed-effect data config (FixedEffectDataConfiguration.scala): "name:shardId,minPartitions"
  random-effect data config (RandomEffectDataConfiguration.scala:60-124):
      "name:reId,shardId,numPartitions,activeUB,passiveLB,featureRatio,projector[=dim]"
  feature shard map: "shard1:sec1,sec2|shard2:sec3"
  factored config (MFOptimizationConfiguration.scala): REcfg:latentCfg:mfIters,latentDim
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu.data.game import RandomEffectDataConfig
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import (
    ModelOutputMode,
    OptimizerType,
    RegularizationType,
    TaskType,
)


@dataclasses.dataclass(frozen=True)
class CoordinateOptConfig:
    """One coordinate's solve configuration (GLMOptimizationConfiguration
    parity; the reference default is TRON(20, 1e-5), no reg, no sampling)."""

    optimizer: OptimizerType = OptimizerType.TRON
    max_iterations: int = 20
    tolerance: float = 1e-5
    reg_weight: float = 0.0
    reg_type: RegularizationType = RegularizationType.NONE
    down_sampling_rate: float = 1.0

    @staticmethod
    def parse(s: str) -> "CoordinateOptConfig":
        parts = [p.strip() for p in s.split(",")]
        if len(parts) != 6:
            raise ValueError(
                f"Parsing {s!r} failed: expected 6 comma-separated parts "
                "(maxIter,tol,regWeight,downSamplingRate,optimizer,regType)"
            )
        max_iter, tol, reg_w, rate = (
            int(parts[0]), float(parts[1]), float(parts[2]), float(parts[3])
        )
        if not (0.0 < rate <= 1.0):
            raise ValueError(f"Unexpected downSamplingRate: {rate}")
        return CoordinateOptConfig(
            optimizer=OptimizerType(parts[4].upper()),
            max_iterations=max_iter,
            tolerance=tol,
            reg_weight=reg_w,
            reg_type=RegularizationType(parts[5].upper()),
            down_sampling_rate=rate,
        )

    def optimizer_config(self) -> OptimizerConfig:
        return OptimizerConfig(max_iterations=self.max_iterations, tolerance=self.tolerance)

    def regularization_context(self) -> RegularizationContext:
        if self.reg_type == RegularizationType.L1:
            return RegularizationContext.l1(self.reg_weight)
        if self.reg_type == RegularizationType.L2:
            return RegularizationContext.l2(self.reg_weight)
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return RegularizationContext.elastic_net(self.reg_weight, 0.5)
        return RegularizationContext.none()


def parse_coordinate_config_map(s: str) -> Dict[str, CoordinateOptConfig]:
    """"name:cfg|name2:cfg2" -> map."""
    out: Dict[str, CoordinateOptConfig] = {}
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, cfg = chunk.split(":", 1)
        out[name.strip()] = CoordinateOptConfig.parse(cfg)
    return out


def parse_coordinate_config_grid(s: Optional[str]) -> List[Dict[str, CoordinateOptConfig]]:
    """';'-separated grid of coordinate config maps; empty -> [{}]."""
    if not s:
        return [{}]
    return [parse_coordinate_config_map(chunk) for chunk in s.split(";") if chunk.strip()]


@dataclasses.dataclass(frozen=True)
class FixedEffectDataSpec:
    feature_shard_id: str
    min_partitions: int = 1  # obsolete on TPU, accepted for parity


def parse_fixed_effect_data_configs(s: Optional[str]) -> Dict[str, FixedEffectDataSpec]:
    out: Dict[str, FixedEffectDataSpec] = {}
    if not s:
        return out
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, cfg = chunk.split(":", 1)
        parts = [p.strip() for p in cfg.split(",")]
        if len(parts) != 2:
            raise ValueError(
                f"Parsing {cfg!r} failed: expected featureShardId,minPartitions"
            )
        out[name.strip()] = FixedEffectDataSpec(parts[0], int(parts[1]))
    return out


def parse_random_effect_data_configs(s: Optional[str]) -> Dict[str, RandomEffectDataConfig]:
    """RandomEffectDataConfiguration.scala:60-124 grammar; negative bounds
    mean unbounded; projector RANDOM takes '=dim'."""
    out: Dict[str, RandomEffectDataConfig] = {}
    if not s:
        return out
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, cfg = chunk.split(":", 1)
        parts = [p.strip() for p in cfg.split(",")]
        if len(parts) != 7:
            raise ValueError(
                f"Parsing {cfg!r} failed: expected reId,shardId,numPartitions,"
                "activeUpperBound,passiveLowerBound,featureRatio,projector"
            )
        active_ub = int(parts[3])
        passive_lb = int(parts[4])
        ratio = float(parts[5])
        proj = parts[6].split("=")
        proj_type = proj[0].upper()
        proj_dim = None
        if proj_type == "RANDOM":
            if len(proj) != 2:
                raise ValueError(
                    "RANDOM projector needs a dimension: RANDOM=projectedSpaceDimension"
                )
            proj_dim = int(proj[1])
        out[name.strip()] = RandomEffectDataConfig(
            random_effect_id=parts[0],
            feature_shard_id=parts[1],
            num_shards=max(int(parts[2]), 1),
            active_upper_bound=active_ub if active_ub >= 0 else None,
            passive_lower_bound=passive_lb if passive_lb >= 0 else None,
            features_to_samples_ratio=ratio if ratio >= 0 else None,
            projector=proj_type,
            random_projection_dim=proj_dim,
        )
    return out


@dataclasses.dataclass(frozen=True)
class FactoredSpec:
    """Factored random effect: RE config + latent config + (mfIters, latentDim)
    (FactoredRandomEffectOptimizationProblem parity)."""

    random_effect: CoordinateOptConfig
    latent_factor: CoordinateOptConfig
    mf_num_iterations: int
    latent_dim: int


def parse_factored_config_map(s: Optional[str]) -> Dict[str, FactoredSpec]:
    """"name:REcfg:latentCfg:mfIters,latentDim|..." (the reference nests three
    config strings per coordinate, ':'-separated)."""
    out: Dict[str, FactoredSpec] = {}
    if not s:
        return out
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, re_cfg, latent_cfg, mf_cfg = chunk.split(":", 3)
        mf_parts = [p.strip() for p in mf_cfg.split(",")]
        if len(mf_parts) != 2:
            raise ValueError(f"Parsing {mf_cfg!r} failed: expected mfIters,latentDim")
        out[name.strip()] = FactoredSpec(
            CoordinateOptConfig.parse(re_cfg),
            CoordinateOptConfig.parse(latent_cfg),
            int(mf_parts[0]),
            int(mf_parts[1]),
        )
    return out


def parse_shard_sections(s: Optional[str]) -> Dict[str, List[str]]:
    """"shard1:sec1,sec2|shard2:sec3" -> shard -> section field list."""
    out: Dict[str, List[str]] = {}
    if not s:
        return out
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        shard, secs = chunk.split(":", 1)
        out[shard.strip()] = [x.strip() for x in secs.split(",") if x.strip()]
    return out


def parse_shard_intercepts(s: Optional[str]) -> Dict[str, bool]:
    """"shard1:true|shard2:false"."""
    out: Dict[str, bool] = {}
    if not s:
        return out
    for chunk in s.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        shard, flag = chunk.split(":", 1)
        out[shard.strip()] = flag.strip().lower() in ("true", "1", "yes")
    return out


def parse_evaluators(s: Optional[str]) -> List[Tuple[EvaluatorType, Optional[int], Optional[str]]]:
    """Comma list; precision@K spelled "PRECISION@K:idName" with K an int
    (EvaluatorType.scala withName parity). Returns (type, k, id name)."""
    out: List[Tuple[EvaluatorType, Optional[int], Optional[str]]] = []
    if not s:
        return out
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        up = tok.upper()
        if up.startswith("PRECISION@"):
            body = tok.split("@", 1)[1]
            if ":" in body:
                k_s, id_name = body.split(":", 1)
            else:
                k_s, id_name = body, None
            out.append((EvaluatorType.PRECISION_AT_K, int(k_s), id_name))
        else:
            out.append((EvaluatorType(up), None, None))
    return out


@dataclasses.dataclass
class GameTrainingParams:
    """cli/game/training/Params.scala parity."""

    train_input_dirs: List[str] = dataclasses.field(default_factory=list)
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    output_dir: str = ""
    updating_sequence: List[str] = dataclasses.field(default_factory=list)
    validate_input_dirs: Optional[List[str]] = None
    # daily/yyyy/MM/dd input discovery (IOUtils.scala:85-130); range XOR days-ago
    train_date_range: Optional[str] = None
    train_date_range_days_ago: Optional[str] = None
    validate_date_range: Optional[str] = None
    validate_date_range_days_ago: Optional[str] = None
    feature_shard_sections: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    feature_shard_intercepts: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # deprecated NameAndTerm vocabulary path (GAMEDriver.scala:49-69 default
    # path; off-heap maps are preferred — io/name_and_term.py)
    feature_name_and_term_set_path: Optional[str] = None
    num_iterations: int = 1
    fixed_effect_opt_grid: List[Dict[str, CoordinateOptConfig]] = dataclasses.field(
        default_factory=lambda: [{}]
    )
    random_effect_opt_grid: List[Dict[str, CoordinateOptConfig]] = dataclasses.field(
        default_factory=lambda: [{}]
    )
    factored_configs: Dict[str, FactoredSpec] = dataclasses.field(default_factory=dict)
    fixed_effect_data_configs: Dict[str, FixedEffectDataSpec] = dataclasses.field(
        default_factory=dict
    )
    random_effect_data_configs: Dict[str, RandomEffectDataConfig] = dataclasses.field(
        default_factory=dict
    )
    compute_variance: bool = False
    model_output_mode: ModelOutputMode = ModelOutputMode.BEST
    num_output_files_re_model: int = 1
    delete_output_dir_if_exists: bool = False
    application_name: str = "photon-ml-tpu-game"
    offheap_indexmap_dir: Optional[str] = None
    evaluators: List[Tuple[EvaluatorType, Optional[int], Optional[str]]] = dataclasses.field(
        default_factory=list
    )
    # step-checkpoint directory (designed upgrade — the reference has no
    # mid-run checkpointing, SURVEY.md §5.4); resume is automatic
    checkpoint_dir: Optional[str] = None
    # commit checkpoints on a background thread (checkpoint_async.py): the
    # solve never blocks on disk; wait() fences before model save / exit
    checkpoint_async: bool = False
    # in-process restart supervisor (resilience/preemption.py): on a
    # cooperative preemption (SIGTERM / PHOTON_PREEMPT_AT), relaunch from
    # the latest checkpoint up to N times before exiting with the distinct
    # preemption code (75)
    max_restarts: int = 0
    # shard fixed-effect rows + random-effect entities over all visible
    # devices (jax.sharding Mesh; collectives ride ICI)
    distributed: bool = False
    # compile each full coordinate-descent iteration as one XLA program
    # (fewer host dispatches; iteration-granular checkpoints)
    fused_cycle: bool = False
    # size-bucketed per-entity solves (algorithm/bucketed_random_effect):
    # per-bucket padding on skewed entity distributions; composes with
    # --distributed (each bucket entity-shards over the mesh)
    bucketed_random_effects: bool = False
    # out-of-core random effects (algorithm/streaming_random_effect): the
    # entity-major stacks live on disk as entity blocks, one block resident
    # per evaluation; coefficients spill between updates. Budget in MB caps
    # the resident block slab (reference DISK_ONLY analogue)
    streaming_random_effects: bool = False
    re_memory_budget_mb: Optional[float] = None
    # content-addressed tensor cache (io/tensor_cache.py): built ingest
    # tensors (decoded GAME columns, padded RE stacks, streaming entity
    # blocks) are stored keyed by SHA-256 of source file stats + ingest
    # config, so a re-run / warm-started grid over unchanged inputs skips
    # Avro decode + grouping + padding entirely
    tensor_cache_dir: Optional[str] = None
    # persistent XLA compilation cache (photon_ml_tpu.compat shims): warm
    # driver runs skip XLA compilation entirely — composes with
    # --tensor-cache for a fully warm restart (cached tensors + cached
    # executables)
    persistent_cache_dir: Optional[str] = None
    # incremental delta retraining (photon_ml_tpu.retrain): the prior run's
    # OUTPUT dir (it holds retrain.json + the saved model). The delta
    # planner diffs the new inputs against it; unchanged coordinates/blocks
    # skip their solves bitwise, dirty work warm-starts from the prior
    # model, and an all-unchanged rerun short-circuits to the prior model
    # wholesale. A missing/corrupt prior degrades to a recorded cold run.
    warm_start_from: Optional[str] = None
    # export the trained best model as an mmap'd serving store
    # (serve/model_store.py) right after save — the artifact a live
    # ScoringServer/fleet hot-swaps in (the retrain->swap loop's handoff)
    export_serve_store: Optional[str] = None
    # slab storage policy for --export-serve-store (serve/quantize.py):
    # f32 (bitwise default) | bf16 | int8 (per-row absmax scales); the
    # quantized dtypes carry a pinned export-verified error budget
    store_dtype: str = "f32"
    # canonical shape ladder (photon_ml_tpu.compile): "off" | "on" |
    # "BASE:GROWTH" — dynamic dims (entity blocks/buckets, chunk rows)
    # round up a geometric ladder with masked padding so N near-identical
    # shapes share ~log(N) compiled solver executables
    shape_canonicalization: str = "off"
    # convergence-compacted random-effect solves (optim/scheduler.py):
    # "off" | "on" | CHUNK | "device[:CHUNK]" — the vmapped per-entity
    # solve runs in chunks of CHUNK iterations, unconverged lanes are
    # repacked into ladder-sized batches between chunks, results are
    # BITWISE-equal to the one-shot kernel. "device" fuses the whole
    # chunk→compact→resume cycle into one XLA program per ladder rung
    # (optim/fused_schedule.py): host dispatches drop to O(#rungs), still
    # bitwise. None defers to PHOTON_SOLVE_CHUNK (default off).
    solve_compaction: Optional[str] = None
    # gap-guided adaptive solve scheduling (optim/convergence.py): "off" |
    # "on" | TOL | "TOL:K" — streaming/bucketed random-effect coordinates
    # visit blocks in descending convergence-score order and skip a block
    # whose gradient-norm score stayed under TOL for K consecutive epochs
    # (coefficients carried forward bitwise, every skip a recorded
    # PlanDecision). Off = bitwise-identical visitation to today. None
    # defers to PHOTON_ADAPTIVE_SCHEDULE (default off).
    adaptive_schedule: Optional[str] = None
    # cost-based query planner (compile/cost.py): "off" | "auto" — under
    # auto, knobs left UNSET (ladder, solve chunk, sparse family, prefetch
    # depth, blocking) are chosen by the cost model from workload
    # statistics and the cost-model.json sidecar's realized-cost feedback;
    # explicit flags/envs always win. Off = today's behavior bitwise.
    # None defers to PHOTON_PLAN (default off).
    plan: Optional[str] = None
    # non-"false": train the lambda grid through the traced-lambda grid API
    # (CoordinateDescent.run_grid — ONE compiled cycle serves every combo;
    # the batched G-lane vmapped variant this flag once selected lost every
    # measured race and was removed, VERDICT r4 #9). Falls back to the
    # per-combo rebuild when combos differ beyond lambda or the run uses
    # distributed/bucketed/factored coordinates, checkpoints, or variance.
    vmapped_grid: str = "false"
    # --- resilience (photon_ml_tpu.resilience) ------------------------
    # corrupt Avro shard policy: "raise" fails fast on the first bad block;
    # "skip" drops bad blocks (resyncing on the sync marker) up to the
    # budget below per part file
    on_corrupt: str = "raise"
    corrupt_skip_budget: int = 16
    # retry/backoff for every filesystem read/write (Avro blocks, index
    # maps, checkpoints): attempt count and base backoff delay (seconds)
    io_retries: int = 4
    io_retry_base_delay: float = 0.05
    # non-finite gate on coordinate-descent updates: "off" keeps the fully
    # async dispatch (one fewer host sync per update); "rollback" restores
    # the coordinate's last good state; "skip_cycle" additionally abandons
    # the rest of the iteration
    divergence_guard: str = "off"

    def validate(self) -> None:
        errors = []
        # normalize the vmapped_grid mode (bool accepted for backcompat with
        # programmatic construction; anything else must be a known mode)
        if isinstance(self.vmapped_grid, bool):
            self.vmapped_grid = "true" if self.vmapped_grid else "false"
        if self.vmapped_grid not in ("false", "true", "auto"):
            errors.append(
                f"vmapped_grid must be 'false', 'true', or 'auto', "
                f"got {self.vmapped_grid!r}"
            )
        if not self.train_input_dirs:
            errors.append("--train-input-dirs is required")
        if not self.output_dir:
            errors.append("--output-dir is required")
        if not self.updating_sequence:
            errors.append("--updating-sequence is required")
        known = (
            set(self.fixed_effect_data_configs)
            | set(self.random_effect_data_configs)
            | set(self.factored_configs)
        )
        for name in self.updating_sequence:
            if name not in known:
                errors.append(f"coordinate {name!r} has no data configuration")
        if self.num_iterations < 1:
            errors.append("--num-iterations must be >= 1")
        if self.train_date_range and self.train_date_range_days_ago:
            errors.append(
                "--train-date-range and --train-date-range-days-ago are exclusive"
            )
        if self.validate_date_range and self.validate_date_range_days_ago:
            errors.append(
                "--validate-date-range and --validate-date-range-days-ago are exclusive"
            )
        if self.re_memory_budget_mb is not None and self.re_memory_budget_mb <= 0:
            errors.append("--re-memory-budget-mb must be positive")
        if self.on_corrupt not in ("raise", "skip"):
            errors.append(
                f"--on-corrupt must be 'raise' or 'skip', got {self.on_corrupt!r}"
            )
        if self.corrupt_skip_budget < 0:
            errors.append("--corrupt-skip-budget must be >= 0")
        if self.io_retries < 1:
            errors.append("--io-retries must be >= 1")
        if self.io_retry_base_delay < 0:
            errors.append("--io-retry-base-delay must be >= 0")
        if self.divergence_guard not in ("off", "rollback", "skip_cycle"):
            errors.append(
                "--divergence-guard must be 'off', 'rollback', or "
                f"'skip_cycle', got {self.divergence_guard!r}"
            )
        # policy composition is resolved ONCE by the execution plan
        # (photon_ml_tpu.compile.plan): the old pairwise fence lattice is
        # gone — compaction composes with --distributed (GSPMD-sharded
        # chunk kernels) and with streaming (owner-computes per-host block
        # compaction), streaming subsumes --bucketed-random-effects with a
        # recorded decision, compaction under --fused-cycle promotes to
        # the on-device rung loop (streaming gets one fused solve per
        # block — cycle_fusion="solve"), and only the genuinely
        # impossible pairs (--vmapped-grid true with chunk pauses;
        # --adaptive-schedule's host-ordered block visits under
        # --fused-cycle) still error, raised by the plan itself so parser
        # and drivers share one rule set.
        # (--checkpoint-dir composes with streaming: the spilled state
        # checkpoints BY REFERENCE, SpilledREState.__checkpoint_ref__.)
        # a broken spec is reported AND normalized to "off" so the plan's
        # spec-independent fence checks below still run — validate() keeps
        # its report-everything-at-once contract
        ladder_spec = self.shape_canonicalization
        try:
            from photon_ml_tpu.compile import resolve_bucketer

            resolve_bucketer(ladder_spec)
        except ValueError as e:
            errors.append(f"--shape-canonicalization: {e}")
            ladder_spec = "off"
        compaction_spec = self.solve_compaction
        try:
            from photon_ml_tpu.optim.scheduler import resolve_schedule

            resolve_schedule(compaction_spec)
        except ValueError as e:
            errors.append(f"--solve-compaction: {e}")
            compaction_spec = "off"
        adaptive_spec = self.adaptive_schedule
        try:
            from photon_ml_tpu.optim.convergence import resolve_adaptive

            resolve_adaptive(adaptive_spec)
        except ValueError as e:
            errors.append(f"--adaptive-schedule: {e}")
            adaptive_spec = "off"
        plan_spec = self.plan
        try:
            from photon_ml_tpu.compile.overrides import resolve_plan_mode

            resolve_plan_mode(plan_spec)
        except ValueError as e:
            errors.append(str(e))
            plan_spec = "off"
        try:
            from photon_ml_tpu.compile.plan import ExecutionPlan

            ExecutionPlan.resolve(
                shape_canonicalization=ladder_spec,
                solve_compaction=compaction_spec,
                adaptive_schedule=adaptive_spec,
                distributed=self.distributed,
                streaming=self.streaming_random_effects,
                bucketed=self.bucketed_random_effects,
                fused_cycle=self.fused_cycle,
                vmapped_grid=self.vmapped_grid,
                plan=plan_spec,
            )
        except ValueError as e:
            errors.append(str(e))
        if self.max_restarts < 0:
            errors.append("--max-restarts must be >= 0")
        if self.checkpoint_async and not self.checkpoint_dir:
            errors.append("--checkpoint-async needs --checkpoint-dir")
        try:
            from photon_ml_tpu.serve.quantize import validate_store_dtype

            validate_store_dtype(self.store_dtype)
        except ValueError as e:
            errors.append(f"--store-dtype: {e}")
        if self.warm_start_from:
            import os as _os

            if _os.path.abspath(self.warm_start_from) == _os.path.abspath(
                self.output_dir
            ):
                errors.append(
                    "--warm-start-from must point at a PRIOR run's output "
                    "dir, not this run's --output-dir (preparing the "
                    "output dir would destroy the prior model the warm "
                    "start reads)"
                )
        if errors:
            raise ValueError("; ".join(errors))

    def config_grid(self) -> List[Dict[str, CoordinateOptConfig]]:
        """Cartesian product over the fixed/random grids, merged per combo
        (cli/game/training/Driver.scala:330-337 grid semantics)."""
        combos = []
        for fe, re in itertools.product(self.fixed_effect_opt_grid, self.random_effect_opt_grid):
            merged = dict(fe)
            merged.update(re)
            combos.append(merged)
        return combos


def _store_dtype_choices() -> List[str]:
    """The ONE source of truth for the --store-dtype argparse choices —
    lazy like the validate() imports so parser construction stays cheap."""
    from photon_ml_tpu.serve.quantize import STORE_DTYPES

    return list(STORE_DTYPES)


def build_training_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu game-training",
        description="GAME (GLMix) training driver",
    )
    a = p.add_argument
    a("--train-input-dirs", required=True, help="comma-separated input dirs")
    a("--task-type", required=True, choices=[t.value for t in TaskType])
    a("--output-dir", required=True)
    a("--updating-sequence", required=True, help="comma-separated coordinate names")
    a("--validate-input-dirs", default=None)
    a("--train-date-range", default=None, help="yyyyMMdd-yyyyMMdd")
    a("--train-date-range-days-ago", default=None, help="e.g. 90-1")
    a("--validate-date-range", default=None)
    a("--validate-date-range-days-ago", default=None)
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections", default=None)
    a("--feature-shard-id-to-intercept-map", dest="shard_intercepts", default=None)
    a("--feature-name-and-term-set-path", dest="name_and_term_path", default=None,
      help="deprecated NameAndTerm vocabulary dir (one text subdir per "
           "section); overrides the whole-dataset feature scan")
    a("--num-iterations", type=int, default=1)
    a("--fixed-effect-optimization-configurations", dest="fe_opt", default=None)
    a("--random-effect-optimization-configurations", dest="re_opt", default=None)
    a("--factored-random-effect-optimization-configurations", dest="factored_opt", default=None)
    a("--fixed-effect-data-configurations", dest="fe_data", default=None)
    a("--random-effect-data-configurations", dest="re_data", default=None)
    a("--compute-variance", default="false")
    a("--model-output-mode", default="BEST", choices=[m.value for m in ModelOutputMode])
    a("--num-output-files-for-random-effect-model", dest="num_re_files", type=int, default=1)
    a("--delete-output-dir-if-exists", default="false")
    a("--application-name", default="photon-ml-tpu-game")
    a("--offheap-indexmap-dir", default=None)
    a("--offheap-indexmap-num-partitions", type=int, default=1)
    a("--evaluator-type", dest="evaluators", default=None)
    # accepted-but-obsolete Spark partitioning knob (Params.scala:229-233):
    # parsed for spark-submit command compatibility, ignored on TPU
    a("--min-partitions-for-validation", type=int, default=1)
    a("--checkpoint-dir", default=None)
    a("--checkpoint-async", default="false",
      help="commit checkpoints on a background thread through the same "
           "retry/atomic-rename path (the solve never blocks on disk; a "
           "wait() fence makes everything durable before model save, "
           "process exit, and supervised relaunch)")
    a("--max-restarts", type=int, default=0,
      help="on a cooperative preemption (SIGTERM/SIGINT or "
           "PHOTON_PREEMPT_AT), relaunch in-process from the latest "
           "checkpoint up to N times before exiting with the distinct "
           "preemption exit code (75)")
    a("--distributed", default="false")
    a("--fused-cycle", default="false",
      help="compile each full coordinate-descent iteration as ONE XLA "
           "program (fewer host dispatches; iteration-granular checkpoints)")
    a("--bucketed-random-effects", default="false",
      help="partition random-effect entities into size buckets (per-bucket "
           "padding on skewed entity distributions; composes with "
           "--distributed)")
    a("--streaming-random-effects", default="false",
      help="out-of-core random effects: entity-block stacks stream from "
           "disk, one block resident per evaluation (DISK_ONLY analogue). "
           "Composes with --distributed: entities hash-partition across "
           "hosts, each host streams only the blocks it owns "
           "(owner-computes; the multihost driver runs it per process)")
    a("--re-memory-budget-mb", default=None,
      help="cap the resident random-effect block slab (MB); implies "
           "--streaming-random-effects")
    a("--tensor-cache", dest="tensor_cache_dir", default=None,
      help="content-addressed on-disk cache of built ingest tensors "
           "(keyed by source file stats + ingest config): warm runs skip "
           "Avro decode + grouping + padding; any input/config change is "
           "a miss")
    a("--persistent-cache", dest="persistent_cache_dir", default=None,
      help="persistent XLA compilation cache dir: warm driver runs skip "
           "compilation entirely (composes with --tensor-cache for a "
           "fully warm restart)")
    a("--warm-start-from", dest="warm_start_from", default=None,
      help="prior run's output dir (holds retrain.json + the saved "
           "model): delta retraining — unchanged coordinates/entity "
           "blocks skip their solves bitwise, dirty work re-solves "
           "warm-started from the prior model, an all-unchanged rerun "
           "reuses the prior model wholesale; a missing/corrupt prior "
           "degrades to a recorded cold run")
    a("--export-serve-store", dest="export_serve_store", default=None,
      help="after save, export the best model as an mmap'd serving store "
           "at this dir (serve/model_store.py) — the artifact a live "
           "scoring server hot-swaps in")
    a("--store-dtype", default="f32", choices=_store_dtype_choices(),
      help="slab storage policy for --export-serve-store: f32 keeps the "
           "bitwise-to-the-driver contract; bf16/int8 (per-row absmax "
           "scales) halve/quarter the slab bytes under a pinned, "
           "export-verified quantization-error budget")
    a("--shape-canonicalization", default="off",
      help="round dynamic dims (entity blocks/buckets, chunk rows) up a "
           "geometric ladder of canonical shapes with masked padding, so "
           "N near-identical shapes share ~log(N) compiled executables: "
           "off | on | BASE:GROWTH (e.g. 8:2)")
    a("--solve-compaction", default=None,
      help="convergence-compacted random-effect solves: run the vmapped "
           "per-entity solve in chunks, repacking unconverged lanes into "
           "ladder-sized batches between chunks (bitwise-equal results, "
           "straggler lanes stop burning whole-batch iterations): "
           "off | on | CHUNK | device[:CHUNK] (the whole "
           "chunk-compact-resume cycle inside ONE XLA program per ladder "
           "rung — host dispatches drop to O(#rungs), results stay "
           "bitwise). Default defers to PHOTON_SOLVE_CHUNK. Composes with "
           "--distributed (GSPMD-sharded chunk kernels), "
           "--bucketed-random-effects, --streaming-random-effects incl. "
           "the multihost per-host path (per-block owner-computes "
           "compaction), and --fused-cycle (promotes to the device loop); "
           "only --vmapped-grid true cannot pause at chunk boundaries")
    a("--adaptive-schedule", default=None,
      help="gap-guided adaptive solve scheduling for streaming/bucketed "
           "random effects: visit blocks in descending convergence-score "
           "order and, in tolerance mode, skip blocks whose gradient-norm "
           "score stayed under TOL for K consecutive epochs (coefficients "
           "carried forward bitwise, every skip a recorded plan decision): "
           "off | on | TOL | TOL:K (e.g. 1e-5:2). Default defers to "
           "PHOTON_ADAPTIVE_SCHEDULE. The per-block ledger persists in the "
           "streaming manifest and retrain.json, and feeds observed block "
           "costs into elastic re-plans; pinned to always-visit for "
           "non-streaming/bucketed coordinates, fenced with --fused-cycle "
           "and --vmapped-grid true")
    a("--plan", default=None,
      help="cost-based query planner: off | auto. Under auto, knobs left "
           "unset (shape ladder, solve-chunk size, sparse family, "
           "prefetch depth, blocking) are chosen by the cost model "
           "(compile/cost.py) from workload statistics, corrected by the "
           "realized-cost feedback persisted in the cost-model.json "
           "sidecar beside retrain.json; every choice is a recorded "
           "PlanDecision with predicted AND realized cost. Explicit flags "
           "and env knobs always win over the planner. Default defers to "
           "PHOTON_PLAN (off = today's behavior, bitwise)")
    a("--vmapped-grid", default="false",
      help="train the lambda grid through the shared-compile grid API (ONE "
           "compiled cycle serves every combo; lambda-only grids on plain "
           "fixed/random coordinates). The batched G-lane variant this flag "
           "once selected was removed after losing every measured race; "
           "'auto' and truthy values now both route here")
    a("--on-corrupt", default="raise", choices=["raise", "skip"],
      help="corrupt Avro block policy: fail fast, or skip bad blocks "
           "(resyncing on the sync marker) within --corrupt-skip-budget")
    a("--corrupt-skip-budget", type=int, default=16,
      help="max corrupt blocks skipped per part file before raising")
    a("--io-retries", type=int, default=4,
      help="attempts for every filesystem read/write (exponential backoff)")
    a("--io-retry-base-delay", type=float, default=0.05,
      help="base backoff delay in seconds between I/O retries")
    a("--divergence-guard", default="off",
      choices=["off", "rollback", "skip_cycle"],
      help="non-finite gate on coordinate updates: rollback restores the "
           "last good state, skip_cycle also abandons the iteration "
           "(costs one host sync per update)")
    return p


def _truthy(v) -> bool:
    return str(v).strip().lower() in ("true", "1", "yes")


def parse_training_params(argv: Optional[List[str]] = None) -> GameTrainingParams:
    ns = build_training_parser().parse_args(argv)
    params = GameTrainingParams(
        train_input_dirs=[d for d in ns.train_input_dirs.split(",") if d],
        task_type=TaskType(ns.task_type),
        output_dir=ns.output_dir,
        updating_sequence=[c.strip() for c in ns.updating_sequence.split(",") if c.strip()],
        validate_input_dirs=(
            [d for d in ns.validate_input_dirs.split(",") if d]
            if ns.validate_input_dirs
            else None
        ),
        train_date_range=ns.train_date_range,
        train_date_range_days_ago=ns.train_date_range_days_ago,
        validate_date_range=ns.validate_date_range,
        validate_date_range_days_ago=ns.validate_date_range_days_ago,
        feature_shard_sections=parse_shard_sections(ns.shard_sections),
        feature_shard_intercepts=parse_shard_intercepts(ns.shard_intercepts),
        feature_name_and_term_set_path=ns.name_and_term_path,
        num_iterations=ns.num_iterations,
        fixed_effect_opt_grid=parse_coordinate_config_grid(ns.fe_opt),
        random_effect_opt_grid=parse_coordinate_config_grid(ns.re_opt),
        factored_configs=parse_factored_config_map(ns.factored_opt),
        fixed_effect_data_configs=parse_fixed_effect_data_configs(ns.fe_data),
        random_effect_data_configs=parse_random_effect_data_configs(ns.re_data),
        compute_variance=_truthy(ns.compute_variance),
        model_output_mode=ModelOutputMode(ns.model_output_mode),
        num_output_files_re_model=ns.num_re_files,
        delete_output_dir_if_exists=_truthy(ns.delete_output_dir_if_exists),
        application_name=ns.application_name,
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        evaluators=parse_evaluators(ns.evaluators),
        checkpoint_dir=ns.checkpoint_dir,
        checkpoint_async=_truthy(ns.checkpoint_async),
        max_restarts=ns.max_restarts,
        distributed=_truthy(ns.distributed),
        fused_cycle=_truthy(ns.fused_cycle),
        bucketed_random_effects=_truthy(ns.bucketed_random_effects),
        streaming_random_effects=(
            _truthy(ns.streaming_random_effects)
            or ns.re_memory_budget_mb is not None
        ),
        re_memory_budget_mb=(
            float(ns.re_memory_budget_mb)
            if ns.re_memory_budget_mb is not None else None
        ),
        tensor_cache_dir=ns.tensor_cache_dir,
        persistent_cache_dir=ns.persistent_cache_dir,
        warm_start_from=ns.warm_start_from,
        export_serve_store=ns.export_serve_store,
        store_dtype=ns.store_dtype,
        shape_canonicalization=ns.shape_canonicalization,
        solve_compaction=ns.solve_compaction,
        adaptive_schedule=ns.adaptive_schedule,
        plan=ns.plan,
        vmapped_grid=(
            "auto" if str(ns.vmapped_grid).lower() == "auto"
            else "true" if _truthy(ns.vmapped_grid) else "false"
        ),
        on_corrupt=ns.on_corrupt,
        corrupt_skip_budget=ns.corrupt_skip_budget,
        io_retries=ns.io_retries,
        io_retry_base_delay=ns.io_retry_base_delay,
        divergence_guard=ns.divergence_guard,
    )
    params.validate()
    return params


@dataclasses.dataclass
class GameScoringParams:
    """cli/game/scoring/Params.scala parity."""

    input_dirs: List[str] = dataclasses.field(default_factory=list)
    game_model_input_dir: str = ""
    output_dir: str = ""
    game_model_id: str = ""
    date_range: Optional[str] = None
    date_range_days_ago: Optional[str] = None
    random_effect_id_types: List[str] = dataclasses.field(default_factory=list)
    feature_shard_sections: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    feature_shard_intercepts: Dict[str, bool] = dataclasses.field(default_factory=dict)
    num_output_files_for_scores: int = 1
    delete_output_dir_if_exists: bool = False
    application_name: str = "photon-ml-tpu-game-scoring"
    offheap_indexmap_dir: Optional[str] = None
    evaluators: List[Tuple[EvaluatorType, Optional[int], Optional[str]]] = dataclasses.field(
        default_factory=list
    )
    host_scoring: bool = False  # NumPy oracle path (device path is default)
    # resilience knobs (same semantics as GameTrainingParams)
    on_corrupt: str = "raise"
    corrupt_skip_budget: int = 16
    io_retries: int = 4

    def validate(self) -> None:
        errors = []
        if not self.input_dirs:
            errors.append("--input-dirs is required")
        if not self.game_model_input_dir:
            errors.append("--game-model-input-dir is required")
        if not self.output_dir:
            errors.append("--output-dir is required")
        if self.date_range and self.date_range_days_ago:
            errors.append("--date-range and --date-range-days-ago are exclusive")
        if self.on_corrupt not in ("raise", "skip"):
            errors.append(
                f"--on-corrupt must be 'raise' or 'skip', got {self.on_corrupt!r}"
            )
        if self.corrupt_skip_budget < 0:
            errors.append("--corrupt-skip-budget must be >= 0")
        if self.io_retries < 1:
            errors.append("--io-retries must be >= 1")
        if errors:
            raise ValueError("; ".join(errors))


def build_scoring_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu game-scoring", description="GAME scoring driver"
    )
    a = p.add_argument
    a("--input-dirs", required=True)
    a("--game-model-input-dir", required=True)
    a("--output-dir", required=True)
    a("--game-model-id", default="")
    a("--date-range", default=None, help="yyyyMMdd-yyyyMMdd")
    a("--date-range-days-ago", default=None, help="e.g. 90-1")
    a("--random-effect-id-set", dest="re_id_set", default=None)
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections", default=None)
    a("--feature-shard-id-to-intercept-map", dest="shard_intercepts", default=None)
    a("--num-output-files-for-scores", type=int, default=1)
    a("--delete-output-dir-if-exists", default="false")
    a("--application-name", default="photon-ml-tpu-game-scoring")
    a("--offheap-indexmap-dir", default=None)
    a("--offheap-indexmap-num-partitions", type=int, default=1)
    a("--evaluator-type", dest="evaluators", default=None)
    # accepted-but-obsolete Spark partitioning knob (scoring Params.scala):
    # parsed for spark-submit command compatibility, ignored on TPU
    a("--min-partitions-for-random-effect-model", type=int, default=1)
    a("--host-scoring", default="false",
      help="force the NumPy host scoring path (device scoring's parity oracle)")
    a("--on-corrupt", default="raise", choices=["raise", "skip"],
      help="corrupt Avro block policy during scoring reads")
    a("--corrupt-skip-budget", type=int, default=16,
      help="max corrupt blocks skipped per part file before raising")
    a("--io-retries", type=int, default=4,
      help="attempts for every filesystem read (exponential backoff)")
    return p


@dataclasses.dataclass
class GameServeParams:
    """Online scoring server parameters (photon_ml_tpu.serve). A designed
    upgrade — the reference has no serving path; its scoring Driver is
    batch-only."""

    # model source: a prebuilt serve store, or a saved GAME model dir the
    # driver exports into one at --model-store-dir first
    model_store_dir: str = ""
    game_model_input_dir: Optional[str] = None
    feature_shard_sections: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    # micro-batching (serve/batcher.py): coalesce concurrent requests up to
    # this many rows / this long a wait onto one ladder-canonical batch
    max_batch_rows: int = 128
    max_wait_ms: float = 2.0
    # canonical shape ladder — defaults ON for serving (a server lives or
    # dies by executable reuse across arbitrary request shapes)
    shape_canonicalization: str = "on"
    # persistent XLA cache: a warm server start compiles NOTHING
    persistent_cache_dir: Optional[str] = None
    # warmup: pre-score every (rows, nnz) ladder rung at startup; nnz cap
    # per shard for the warmed rungs (requests wider than this pay one
    # compile on first sight)
    warmup: bool = True
    warm_nnz: Optional[int] = None
    # fail startup unless the warm start compiled nothing new in XLA
    # (requires --persistent-cache and a prior run to have filled it)
    assert_warm: bool = False
    # export the model store from --game-model-input-dir then exit
    build_store_only: bool = False
    num_store_partitions: int = 1
    # slab storage policy when THIS driver exports the store (f32 | bf16 |
    # int8); an already-built store serves at whatever dtype it was
    # exported with (logged at startup next to the footprint gauges)
    store_dtype: str = "f32"
    log_path: Optional[str] = None

    def validate(self) -> None:
        errors = []
        if not self.model_store_dir:
            errors.append("--model-store-dir is required")
        try:
            from photon_ml_tpu.serve.quantize import validate_store_dtype

            validate_store_dtype(self.store_dtype)
        except ValueError as e:
            errors.append(f"--store-dtype: {e}")
        if self.build_store_only and not self.game_model_input_dir:
            errors.append("--build-store-only needs --game-model-input-dir")
        if self.max_batch_rows < 1:
            errors.append("--max-batch-rows must be >= 1")
        if self.max_wait_ms < 0:
            errors.append("--max-wait-ms must be >= 0")
        if self.num_store_partitions < 1:
            errors.append("--num-store-partitions must be >= 1")
        if self.warm_nnz is not None and self.warm_nnz < 1:
            errors.append("--warm-nnz must be >= 1")
        if self.assert_warm and not self.persistent_cache_dir:
            errors.append(
                "--assert-warm needs --persistent-cache (zero new compiles "
                "is only achievable from a filled persistent cache)"
            )
        if self.assert_warm and not self.warmup:
            errors.append(
                "--assert-warm needs warmup: with --no-warmup nothing "
                "compiles at startup, so 'zero new compiles' would hold "
                "vacuously while every first request pays a compile"
            )
        try:
            from photon_ml_tpu.compile import resolve_bucketer

            resolve_bucketer(self.shape_canonicalization)
        except ValueError as e:
            errors.append(f"--shape-canonicalization: {e}")
        if errors:
            raise ValueError("; ".join(errors))


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu game-serve",
        description="persistent online GAME scoring server (JSON-lines on "
        "stdin/stdout; photon_ml_tpu.serve)",
    )
    a = p.add_argument
    a("--model-store-dir", required=True,
      help="mmap'd serving store (serve/model_store.py layout); built here "
           "from --game-model-input-dir when absent")
    a("--game-model-input-dir", default=None,
      help="saved GAME model dir (reference Avro layout) to export into "
           "the store when the store does not exist yet")
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections",
      default=None)
    a("--max-batch-rows", type=int, default=128,
      help="micro-batch row cap: concurrent requests coalesce up to this "
           "many rows per device call")
    a("--max-wait-ms", type=float, default=2.0,
      help="micro-batch window: the first request of an idle window waits "
           "at most this long for company (a saturated queue never waits)")
    a("--shape-canonicalization", default="on",
      help="batch-shape ladder: off | on | BASE:GROWTH (default ON — every "
           "request shape rounds up to a warmed canonical executable)")
    a("--persistent-cache", dest="persistent_cache_dir", default=None,
      help="persistent XLA compilation cache dir: a warm server start "
           "compiles nothing (asserted when --assert-warm)")
    a("--no-warmup", action="store_true",
      help="skip the startup ladder warmup (first requests then compile)")
    a("--warm-nnz", type=int, default=None,
      help="nnz-per-row cap the warmup assumes (default 64, clamped to the "
           "feature dim)")
    a("--assert-warm", default="false",
      help="fail startup unless zero new XLA compiles after warmup")
    a("--build-store-only", default="false",
      help="export --game-model-input-dir into --model-store-dir, then exit")
    a("--num-store-partitions", type=int, default=1,
      help="pmix partitions for the store's feature/entity lookups")
    a("--store-dtype", default="f32", choices=_store_dtype_choices(),
      help="slab storage policy when exporting the store here: f32 "
           "(bitwise default) | bf16 | int8 with per-row absmax scales, "
           "under a pinned export-verified quantization-error budget")
    a("--log-path", default=None, help="log file (default: stderr only)")
    return p


@dataclasses.dataclass
class GameFleetParams:
    """Sharded serving fleet parameters (photon_ml_tpu.serve.fleet). One
    driver, three modes: export the sharded stores, run one replica, or
    run the router."""

    fleet_dir: str = ""
    # export mode: shard --game-model-input-dir into fleet_dir
    build_fleet_stores: bool = False
    game_model_input_dir: Optional[str] = None
    num_fleet_replicas: int = 2
    num_buckets: int = 64
    # build mode: slab storage policy for EVERY replica store (recorded in
    # fleet.json; a mixed-dtype fleet is refused at load)
    store_dtype: str = "f32"
    # replica mode: serve this replica's shard store over TCP
    replica_id: Optional[int] = None
    port: int = 0
    host: str = "127.0.0.1"
    # router mode: scatter/gather over these replica addresses
    replica_addresses: List[str] = dataclasses.field(default_factory=list)
    heartbeat_dir: Optional[str] = None
    heartbeat_deadline_s: float = 5.0
    request_timeout_s: float = 30.0
    hedge_ms: Optional[float] = None
    # shared serving knobs (the PR 6 surface)
    feature_shard_sections: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    max_batch_rows: int = 128
    max_wait_ms: float = 2.0
    shape_canonicalization: str = "on"
    persistent_cache_dir: Optional[str] = None
    warmup: bool = True
    warm_nnz: Optional[int] = None
    log_path: Optional[str] = None

    def mode(self) -> str:
        if self.build_fleet_stores:
            return "build"
        if self.replica_id is not None:
            return "replica"
        return "router"

    def validate(self) -> None:
        errors = []
        if not self.fleet_dir:
            errors.append("--fleet-dir is required")
        if self.build_fleet_stores and not self.game_model_input_dir:
            errors.append("--build-fleet-stores needs --game-model-input-dir")
        if self.num_fleet_replicas < 1:
            errors.append("--num-fleet-replicas must be >= 1")
        if self.num_buckets < self.num_fleet_replicas:
            errors.append("--num-buckets must be >= --num-fleet-replicas")
        if self.replica_id is not None and not (
            0 <= self.replica_id < self.num_fleet_replicas
        ):
            errors.append(
                "--replica-id must be in [0, --num-fleet-replicas)"
            )
        if self.replica_id is not None and self.build_fleet_stores:
            errors.append("--replica-id and --build-fleet-stores are exclusive")
        if (
            self.mode() == "router"
            and len(self.replica_addresses) != self.num_fleet_replicas
        ):
            errors.append(
                "router mode needs exactly --num-fleet-replicas "
                "--replica-addresses entries"
            )
        if self.max_batch_rows < 1:
            errors.append("--max-batch-rows must be >= 1")
        if self.max_wait_ms < 0:
            errors.append("--max-wait-ms must be >= 0")
        if self.hedge_ms is not None and self.hedge_ms <= 0:
            errors.append("--hedge-ms must be > 0")
        if self.heartbeat_deadline_s <= 0:
            errors.append("--heartbeat-deadline-s must be > 0")
        try:
            from photon_ml_tpu.serve.quantize import validate_store_dtype

            validate_store_dtype(self.store_dtype)
        except ValueError as e:
            errors.append(f"--store-dtype: {e}")
        try:
            from photon_ml_tpu.compile import resolve_bucketer

            resolve_bucketer(self.shape_canonicalization)
        except ValueError as e:
            errors.append(f"--shape-canonicalization: {e}")
        if errors:
            raise ValueError("; ".join(errors))


def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu game-serve-fleet",
        description="sharded GAME serving fleet (photon_ml_tpu.serve.fleet): "
        "export sharded stores, run a replica, or run the router",
    )
    a = p.add_argument
    a("--fleet-dir", required=True,
      help="fleet export dir (fleet.json + replica-<r>/ shard stores)")
    a("--build-fleet-stores", default="false",
      help="export --game-model-input-dir into --fleet-dir sharded stores, "
           "then exit")
    a("--game-model-input-dir", default=None,
      help="saved GAME model dir to shard-export in build mode")
    a("--num-fleet-replicas", type=int, default=2,
      help="replica count the plan partitions entities across")
    a("--num-buckets", type=int, default=64,
      help="consistent-hash bucket count (granularity of the balanced "
           "blocking; must be >= the replica count)")
    a("--store-dtype", default="f32", choices=_store_dtype_choices(),
      help="build mode: slab storage policy for every replica store "
           "(one dial per fleet, recorded in fleet.json; mixed-dtype "
           "fleets are refused at load)")
    a("--replica-id", type=int, default=None,
      help="run THIS replica (serves its shard store over TCP until a "
           "shutdown message)")
    a("--port", type=int, default=0,
      help="replica TCP port (0 = ephemeral; the bound address is printed "
           "as a READY line)")
    a("--host", default="127.0.0.1", help="replica bind host")
    a("--replica-addresses", default="",
      help="router mode: comma-separated host:port per replica, in "
           "replica-id order")
    a("--heartbeat-dir", default=None,
      help="shared dir for replica heartbeats (PR 5 machinery); the router "
           "stops dispatching to a replica whose heartbeat goes stale")
    a("--heartbeat-deadline-s", type=float, default=5.0,
      help="heartbeat age beyond which the router treats a replica as dead")
    a("--request-timeout-s", type=float, default=30.0,
      help="per sub-request call timeout (failures degrade, never hang)")
    a("--hedge-ms", type=float, default=None,
      help="fire a backup fixed-only sub-request if the owner has not "
           "replied within this window (off by default)")
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections",
      default=None)
    a("--max-batch-rows", type=int, default=128)
    a("--max-wait-ms", type=float, default=2.0)
    a("--shape-canonicalization", default="on")
    a("--persistent-cache", dest="persistent_cache_dir", default=None)
    a("--no-warmup", action="store_true")
    a("--warm-nnz", type=int, default=None)
    a("--log-path", default=None)
    return p


def parse_fleet_params(argv: Optional[List[str]] = None) -> GameFleetParams:
    ns = build_fleet_parser().parse_args(argv)
    params = GameFleetParams(
        fleet_dir=ns.fleet_dir,
        build_fleet_stores=_truthy(ns.build_fleet_stores),
        game_model_input_dir=ns.game_model_input_dir,
        num_fleet_replicas=ns.num_fleet_replicas,
        num_buckets=ns.num_buckets,
        store_dtype=ns.store_dtype,
        replica_id=ns.replica_id,
        port=ns.port,
        host=ns.host,
        replica_addresses=[
            s.strip() for s in (ns.replica_addresses or "").split(",")
            if s.strip()
        ],
        heartbeat_dir=ns.heartbeat_dir,
        heartbeat_deadline_s=ns.heartbeat_deadline_s,
        request_timeout_s=ns.request_timeout_s,
        hedge_ms=ns.hedge_ms,
        feature_shard_sections=parse_shard_sections(ns.shard_sections),
        max_batch_rows=ns.max_batch_rows,
        max_wait_ms=ns.max_wait_ms,
        shape_canonicalization=ns.shape_canonicalization,
        persistent_cache_dir=ns.persistent_cache_dir,
        warmup=not ns.no_warmup,
        warm_nnz=ns.warm_nnz,
        log_path=ns.log_path,
    )
    params.validate()
    return params


def parse_serve_params(argv: Optional[List[str]] = None) -> GameServeParams:
    ns = build_serve_parser().parse_args(argv)
    params = GameServeParams(
        model_store_dir=ns.model_store_dir,
        game_model_input_dir=ns.game_model_input_dir,
        feature_shard_sections=parse_shard_sections(ns.shard_sections),
        max_batch_rows=ns.max_batch_rows,
        max_wait_ms=ns.max_wait_ms,
        shape_canonicalization=ns.shape_canonicalization,
        persistent_cache_dir=ns.persistent_cache_dir,
        warmup=not ns.no_warmup,
        warm_nnz=ns.warm_nnz,
        assert_warm=_truthy(ns.assert_warm),
        build_store_only=_truthy(ns.build_store_only),
        num_store_partitions=ns.num_store_partitions,
        store_dtype=ns.store_dtype,
        log_path=ns.log_path,
    )
    params.validate()
    return params


def parse_scoring_params(argv: Optional[List[str]] = None) -> GameScoringParams:
    ns = build_scoring_parser().parse_args(argv)
    params = GameScoringParams(
        input_dirs=[d for d in ns.input_dirs.split(",") if d],
        game_model_input_dir=ns.game_model_input_dir,
        output_dir=ns.output_dir,
        game_model_id=ns.game_model_id,
        date_range=ns.date_range,
        date_range_days_ago=ns.date_range_days_ago,
        random_effect_id_types=(
            [t.strip() for t in ns.re_id_set.split(",") if t.strip()]
            if ns.re_id_set
            else []
        ),
        feature_shard_sections=parse_shard_sections(ns.shard_sections),
        feature_shard_intercepts=parse_shard_intercepts(ns.shard_intercepts),
        num_output_files_for_scores=ns.num_output_files_for_scores,
        delete_output_dir_if_exists=_truthy(ns.delete_output_dir_if_exists),
        application_name=ns.application_name,
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        evaluators=parse_evaluators(ns.evaluators),
        host_scoring=_truthy(ns.host_scoring),
        on_corrupt=ns.on_corrupt,
        corrupt_skip_budget=ns.corrupt_skip_budget,
        io_retries=ns.io_retries,
    )
    params.validate()
    return params
