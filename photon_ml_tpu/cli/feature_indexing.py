"""Feature indexing job: build partitioned name->index maps from data.

Reference spec: FeatureIndexingJob.scala:59-350 — scan the dataset for
distinct (name, term) keys per feature shard (+ intercept), hash-partition,
and write partitioned index stores the drivers later load via
--offheap-indexmap-dir. The PalDB-per-partition layout is replaced by the
IndexMap partitioned build (same hash-partition + global-offset semantics,
io/index_map.py) persisted as one JSON file per shard:

    <output>/feature-index.json              (single/global map)
    <output>/feature-index-<shard>.json      (per feature shard)
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from photon_ml_tpu.cli.game_params import parse_shard_intercepts, parse_shard_sections
from photon_ml_tpu.io import avro_data
from photon_ml_tpu.io.index_map import IndexMap


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu feature-indexing",
        description="Build feature index maps (FeatureIndexingJob parity)",
    )
    a = p.add_argument
    a("--data-input-dirs", required=True, help="comma-separated input dirs")
    a("--partition-num", type=int, default=1, help="hash partitions in the map")
    a("--output-dir", required=True)
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections", default=None)
    a("--feature-shard-id-to-intercept-map", dest="shard_intercepts", default=None)
    a("--add-intercept", default="true")
    a("--format", dest="store_format", default="JSON", choices=["JSON", "OFFHEAP"],
      help="JSON index file, or the native memory-mapped pmix store "
      "(the PalDB-analogue off-heap format)")
    return p


def main(argv: Optional[List[str]] = None) -> List[str]:
    ns = build_parser().parse_args(argv)
    paths = []
    for d in ns.data_input_dirs.split(","):
        if not d:
            continue
        if os.path.isfile(d):
            paths.append(d)
        else:
            paths.extend(
                os.path.join(d, f)
                for f in sorted(os.listdir(d))
                if not f.startswith((".", "_"))
            )
    os.makedirs(ns.output_dir, exist_ok=True)
    add_intercept_default = str(ns.add_intercept).strip().lower() in ("true", "1", "yes")

    offheap = ns.store_format == "OFFHEAP"
    partitions = max(ns.partition_num, 1)

    def emit(keys: List[str], add_intercept: bool, shard: Optional[str]) -> str:
        if offheap:
            from photon_ml_tpu.io.offheap import build_offheap_store

            out = (
                os.path.join(ns.output_dir, shard) if shard else ns.output_dir
            )
            build_offheap_store(out, keys, add_intercept, partitions)
            count = len(keys) + int(add_intercept)
        else:
            imap = IndexMap.build(keys, add_intercept, partitions)
            out = os.path.join(
                ns.output_dir,
                f"feature-index-{shard}.json" if shard else "feature-index.json",
            )
            imap.save(out)
            count = len(imap)
        label = f"shard {shard}: " if shard else ""
        print(f"{label}{count} features -> {out}")
        return out

    written: List[str] = []
    shard_sections = parse_shard_sections(ns.shard_sections)
    shard_intercepts = parse_shard_intercepts(ns.shard_intercepts)
    if shard_sections:
        for shard, sections in shard_sections.items():
            keys = avro_data.collect_feature_keys(paths, sections)
            written.append(
                emit(keys, shard_intercepts.get(shard, add_intercept_default), shard)
            )
    else:
        keys = avro_data.collect_feature_keys(paths)
        written.append(emit(keys, add_intercept_default, None))
    return written


if __name__ == "__main__":
    main()
