"""Feature indexing job: build partitioned name->index maps from data.

Reference spec: FeatureIndexingJob.scala:59-350 — scan the dataset for
distinct (name, term) keys per feature shard (+ intercept), hash-partition,
and write partitioned index stores the drivers later load via
--offheap-indexmap-dir. The PalDB-per-partition layout is replaced by the
IndexMap partitioned build (same hash-partition + global-offset semantics,
io/index_map.py) persisted as one JSON file per shard:

    <output>/feature-index.json              (single/global map)
    <output>/feature-index-<shard>.json      (per feature shard)
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from photon_ml_tpu.cli.game_params import parse_shard_intercepts, parse_shard_sections
from photon_ml_tpu.io import avro_data
from photon_ml_tpu.io.index_map import IndexMap


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu feature-indexing",
        description="Build feature index maps (FeatureIndexingJob parity)",
    )
    a = p.add_argument
    a("--data-input-dirs", required=True, help="comma-separated input dirs")
    a("--partition-num", type=int, default=1, help="hash partitions in the map")
    a("--output-dir", required=True)
    a("--feature-shard-id-to-feature-section-keys-map", dest="shard_sections", default=None)
    a("--feature-shard-id-to-intercept-map", dest="shard_intercepts", default=None)
    a("--add-intercept", default="true")
    return p


def main(argv: Optional[List[str]] = None) -> List[str]:
    ns = build_parser().parse_args(argv)
    paths = []
    for d in ns.data_input_dirs.split(","):
        if not d:
            continue
        if os.path.isfile(d):
            paths.append(d)
        else:
            paths.extend(
                os.path.join(d, f)
                for f in sorted(os.listdir(d))
                if not f.startswith((".", "_"))
            )
    os.makedirs(ns.output_dir, exist_ok=True)
    add_intercept_default = str(ns.add_intercept).strip().lower() in ("true", "1", "yes")

    written: List[str] = []
    shard_sections = parse_shard_sections(ns.shard_sections)
    shard_intercepts = parse_shard_intercepts(ns.shard_intercepts)
    if shard_sections:
        for shard, sections in shard_sections.items():
            keys = avro_data.collect_feature_keys(paths, sections)
            imap = IndexMap.build(
                keys,
                add_intercept=shard_intercepts.get(shard, add_intercept_default),
                num_partitions=max(ns.partition_num, 1),
            )
            out = os.path.join(ns.output_dir, f"feature-index-{shard}.json")
            imap.save(out)
            written.append(out)
            print(f"shard {shard}: {len(imap)} features -> {out}")
    else:
        keys = avro_data.collect_feature_keys(paths)
        imap = IndexMap.build(
            keys,
            add_intercept=add_intercept_default,
            num_partitions=max(ns.partition_num, 1),
        )
        out = os.path.join(ns.output_dir, "feature-index.json")
        imap.save(out)
        written.append(out)
        print(f"{len(imap)} features -> {out}")
    return written


if __name__ == "__main__":
    main()
