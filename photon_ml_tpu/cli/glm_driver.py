"""GLM training driver: the staged end-to-end pipeline.

Reference spec: Driver.scala:69-598 — stage progression INIT -> PREPROCESSED
-> TRAINED -> VALIDATED -> DIAGNOSED (DriverStage.scala; stage assertions
Driver.scala:513-527): preprocess (:228-254) loads + validates + summarizes
data, train (:256-290) runs the warm-started lambda grid, validate
(:363-372) computes metric maps and selects the best lambda, diagnose
(:484-511) builds the HTML model-diagnostic report (writer :577-597), and
models are written in text form (:160-163).

TPU-native: one host process owns ingest and orchestration; each solve is a
compiled XLA program on the batch (the Spark context / executors / kryo /
partition knobs have no analogue and are accepted-but-ignored for CLI
compatibility).
"""

from __future__ import annotations

import enum
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.cli.glm_params import (
    FieldNamesType,
    GLMParams,
    InputFormatType,
    parse_from_command_line,
)
from photon_ml_tpu.data.validators import sanity_check_data
from photon_ml_tpu.diagnostics import render_html
from photon_ml_tpu.diagnostics import (
    bootstrap_diagnostic,
    feature_importance,
    fitting,
    hosmer_lemeshow,
    independence,
)
from photon_ml_tpu.diagnostics.reports import (
    ModelDiagnosticReport,
    SystemReport,
    assemble_document,
)
from photon_ml_tpu.evaluation import metrics as metrics_mod
from photon_ml_tpu.io import avro_data
from photon_ml_tpu.io.index_map import INTERCEPT_KEY, DELIMITER, IndexMap
from photon_ml_tpu.io.libsvm import HostDataset, read_libsvm, to_batch
from photon_ml_tpu.model_selection import select_best_model
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.ops.stats import BasicStatisticalSummary, summarize
from photon_ml_tpu.optim.common import OptimizerConfig, summarize_result
from photon_ml_tpu.optim.constraints import BoxConstraints, parse_constraint_string
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.training import TrainedModelList, train_glm_grid
from photon_ml_tpu.types import (
    NormalizationType,
    RegularizationType,
    TaskType,
)
from photon_ml_tpu.utils.io_utils import (
    prepare_output_dir,
    write_basic_statistics,
    write_models_in_text,
)
from photon_ml_tpu.utils.logging import PhotonLogger
from photon_ml_tpu.utils.timer import Timer

# Above this dense width, batches stay in padded-sparse layout
DENSE_DIM_THRESHOLD = 4096
LEARNED_MODELS_TEXT = "output"  # Driver.LEARNED_MODELS_TEXT parity
REPORT_FILE = "model-diagnostic.html"


class DriverStage(enum.IntEnum):
    """Ordered driver stages (DriverStage.scala parity)."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3
    DIAGNOSED = 4


class Driver:
    """Staged GLM training pipeline. Construct with params, call run()."""

    def __init__(self, params: GLMParams, logger: Optional[PhotonLogger] = None):
        params.validate()
        self.params = params
        self.stage = DriverStage.INIT
        self.stage_history: List[DriverStage] = []
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(
            os.path.join(params.output_dir, "photon-ml-tpu.log")
        )
        self.timer = Timer(self.logger.info)

        self.index_map: Optional[IndexMap] = None
        self.train_ds: Optional[HostDataset] = None
        self.train_batch: Optional[GLMBatch] = None
        # out-of-core mode: chunk source replaces train_batch
        self.streaming_source = None
        self.validation_batch: Optional[GLMBatch] = None
        self.summary: Optional[BasicStatisticalSummary] = None
        self.norm: NormalizationContext = NormalizationContext.identity()
        self.trained: Optional[TrainedModelList] = None
        # raw-space (back-transformed) models keyed in training order
        self.models: List[Tuple[float, GeneralizedLinearModel]] = []
        self.best_reg_weight: Optional[float] = None
        self.best_model: Optional[GeneralizedLinearModel] = None
        self.validation_metrics: Dict[float, Dict[str, float]] = {}
        # lambda -> [metric map per completed iteration] (validate-per-iteration)
        self.per_iteration_metrics: Dict[float, List[Dict[str, float]]] = {}
        self.problem: Optional[GLMOptimizationProblem] = None

    # ------------------------------------------------------------------
    def _advance(self, stage: DriverStage) -> None:
        """Stage assertion (Driver.scala:513-527 parity)."""
        if stage <= self.stage:
            raise RuntimeError(f"cannot move back from {self.stage.name} to {stage.name}")
        self.stage_history.append(self.stage)
        self.stage = stage

    def _assert_stage(self, expected: DriverStage) -> None:
        if self.stage != expected:
            raise RuntimeError(
                f"stage {expected.name} required, currently {self.stage.name}"
            )

    # ------------------------------------------------------------------
    def run(self) -> None:
        p = self.params
        prepare_output_dir(p.output_dir, p.delete_output_dirs_if_exist)
        self.logger.info(f"job {p.job_name}: {p.task_type.value} via "
                         f"{p.optimizer_type.value}, lambdas={p.regularization_weights}")
        from photon_ml_tpu.compile import compile_stats

        compile_stats.install_xla_listeners()
        if p.persistent_cache_dir:
            from photon_ml_tpu import compat

            if compat.enable_persistent_cache(p.persistent_cache_dir):
                self.logger.info(
                    f"persistent XLA compilation cache: {p.persistent_cache_dir}"
                )
            else:
                self.logger.warn(
                    "--persistent-cache requested but this jax has no "
                    "compilation-cache API; compiling uncached"
                )
        try:
            with self.timer.measure("preprocess"):
                self.preprocess()
            with self.timer.measure("train"):
                self.train()
            if p.validating_data_dir:
                with self.timer.measure("validate"):
                    self.validate()
            if p.diagnostic_mode.runs_train or p.diagnostic_mode.runs_validate:
                with self.timer.measure("diagnose"):
                    self.diagnose()
            self.logger.info(self.timer.summary())
            self.logger.info(compile_stats.summary())
            if p.tensor_cache_dir:
                from photon_ml_tpu.io.tensor_cache import cache_stats

                self.logger.info(cache_stats.summary())
            if p.persistent_cache_dir and compile_stats.xla_cache_misses == 0:
                self.logger.info(
                    "persistent cache fully warm: zero new XLA compiles"
                )
        finally:
            if self._own_logger:
                self.logger.close()

    # ------------------------------------------------------------------
    # stage: preprocess
    # ------------------------------------------------------------------
    def _input_paths(self, directory: str) -> List[str]:
        if os.path.isfile(directory):
            return [directory]
        return [
            os.path.join(directory, f)
            for f in sorted(os.listdir(directory))
            if not f.startswith((".", "_"))
        ]

    def _selected_features(self) -> Optional[set]:
        """Whitelist of feature keys (GLMSuite.scala:141-180 parity: a file
        of name/term entries; text lines 'name<TAB>term' or 'name')."""
        path = self.params.selected_features_file
        if not path:
            return None
        keys = set()
        if path.endswith(".avro"):
            from photon_ml_tpu.io import avro as avro_io

            for rec in avro_io.read_container(path):
                keys.add(f"{rec['name']}{DELIMITER}{rec.get('term') or ''}")
        else:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    if DELIMITER in line:
                        keys.add(line)
                    elif "\t" in line:
                        name, term = line.split("\t", 1)
                        keys.add(f"{name}{DELIMITER}{term}")
                    else:
                        keys.add(f"{line}{DELIMITER}")
        return keys

    def _read_avro(self, directory: str) -> HostDataset:
        label_field = (
            "response"
            if self.params.field_names_type == FieldNamesType.RESPONSE_PREDICTION
            else "label"
        )
        return avro_data.read_training_examples(
            self._input_paths(directory),
            self.index_map,
            add_intercept=self.params.add_intercept,
            label_field=label_field,
        )

    def _build_index_map(self) -> IndexMap:
        p = self.params
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.io.offheap import load_index_map

            return load_index_map(p.offheap_indexmap_dir)
        keys = avro_data.collect_feature_keys(self._input_paths(p.training_data_dir))
        selected = self._selected_features()
        if selected is not None:
            keys = [k for k in keys if k in selected]
        return IndexMap.build(
            keys,
            add_intercept=p.add_intercept,
            num_partitions=max(p.offheap_indexmap_num_partitions, 1),
        )

    def _preprocess_streaming(self) -> None:
        """Out-of-core preprocess: decode input FILE BY FILE, spill dense
        row chunks to <output>/stream-chunks/, never materializing the full
        batch (the DISK_ONLY persistence analogue, StorageLevel.scala:22-24).
        Per-file sanity checks replace the whole-batch pass; the colStats
        summary accumulates over chunks (optim/streaming.py).

        Peak host memory is O(largest single input file + one chunk) — the
        decode granularity is the file, exactly like the reference's
        per-partition decode (DataProcessingUtils.scala:57-80); split huge
        inputs into more part files to bound it. Rows are re-chunked ACROSS
        file boundaries so all chunks but the final tail share one shape
        (one XLA executable for the whole stream)."""
        p = self.params
        from photon_ml_tpu.optim.streaming import (
            ChunkedGLMSource,
            streaming_summarize,
        )

        paths = self._input_paths(p.training_data_dir)
        if p.input_file_format == InputFormatType.LIBSVM:
            dim = p.feature_dimension if p.feature_dimension > 0 else None
            first = read_libsvm(paths[0], dim=dim, add_intercept=p.add_intercept)
            names = [str(i) for i in range(first.dim - int(p.add_intercept))]
            if p.add_intercept:
                names.append(INTERCEPT_KEY)
            self.index_map = IndexMap({k: i for i, k in enumerate(names)}, names)
            read_file = lambda path: read_libsvm(
                path, dim=first.dim - int(p.add_intercept),
                add_intercept=p.add_intercept,
            )
            file_ds = {paths[0]: first}
        else:
            self.index_map = self._build_index_map()
            label_field = (
                "response"
                if p.field_names_type == FieldNamesType.RESPONSE_PREDICTION
                else "label"
            )
            read_file = lambda path: avro_data.read_training_examples(
                [path], self.index_map,
                add_intercept=p.add_intercept, label_field=label_field,
            )
            file_ds = {}

        dim = len(self.index_map)
        if dim > DENSE_DIM_THRESHOLD:
            raise ValueError(
                f"--streaming-chunk-rows spills DENSE chunks; {dim} features "
                f"exceeds the dense threshold ({DENSE_DIM_THRESHOLD}). The "
                "wide-sparse regime streams through the in-memory sparse "
                "layout instead (sparse chunk spilling is not implemented)."
            )
        def _spill_chunks(chunk_dir: str) -> None:
            """Decode file by file and spill re-chunked rows into
            ``chunk_dir`` (rows carried across file boundaries so every
            chunk but the final tail shares one shape -> one executable)."""
            chunk_i = 0
            total_rows = 0
            buf: List[dict] = []
            buf_rows = 0

            def _flush(final=False):
                nonlocal chunk_i, buf, buf_rows
                while buf_rows >= p.streaming_chunk_rows or (final and buf_rows > 0):
                    take = min(buf_rows, p.streaming_chunk_rows)
                    parts: List[dict] = []
                    got = 0
                    while got < take:
                        head = buf[0]
                        n_h = len(head["y"])
                        if got + n_h <= take:
                            parts.append(buf.pop(0))
                            got += n_h
                        else:
                            split = take - got
                            parts.append({k: v[:split] for k, v in head.items()})
                            buf[0] = {k: v[split:] for k, v in head.items()}
                            got = take
                    payload = {
                        k: np.concatenate([q[k] for q in parts])
                        for k in parts[0]
                    }
                    from photon_ml_tpu.optim.streaming import write_chunk

                    write_chunk(chunk_dir, chunk_i, payload)
                    chunk_i += 1
                    buf_rows -= take

            for path in paths:
                ds = file_ds.pop(path, None) or read_file(path)
                batch = to_batch(ds, dense=True)
                sanity_check_data(batch, p.task_type, p.data_validation_type)
                # uniform keys across files (a file without offsets/weights
                # must still concatenate with one that has them)
                piece = {
                    "x": np.asarray(batch.features.matrix)[: ds.num_rows],
                    "y": np.asarray(ds.labels),
                    "offsets": (
                        np.asarray(ds.offsets)
                        if ds.offsets is not None
                        else np.zeros(ds.num_rows, np.float32)
                    ),
                    "weights": (
                        np.asarray(ds.weights)
                        if ds.weights is not None
                        else np.ones(ds.num_rows, np.float32)
                    ),
                }
                buf.append(piece)
                buf_rows += ds.num_rows
                total_rows += ds.num_rows
                _flush()
            _flush(final=True)
            self.logger.info(
                f"streaming mode: {total_rows} rows x {dim} features spilled "
                f"to {chunk_i} chunks of {p.streaming_chunk_rows} rows (+ tail)"
            )

        source_dir = None
        if p.tensor_cache_dir:
            # content-addressed chunk reuse: a warm run over unchanged
            # inputs + config mmaps the committed chunks, skipping decode +
            # sanity pass + spill entirely
            from photon_ml_tpu.io.tensor_cache import (
                TensorCache,
                index_map_digest,
            )
            from photon_ml_tpu.resilience import RetryError

            cache = TensorCache(p.tensor_cache_dir)
            cache_key = cache.key_for(
                paths,
                {"kind": "glm_stream_chunks",
                 "chunk_rows": p.streaming_chunk_rows,
                 "format": p.input_file_format,
                 "fields": p.field_names_type,
                 "intercept": p.add_intercept,
                 "index_map": index_map_digest(self.index_map)},
            )
            source_dir = cache.get_dir(cache_key)
            if source_dir is not None:
                self.logger.info(
                    f"tensor cache HIT {cache_key[:12]}: decode + spill skipped"
                )
            else:
                try:
                    source_dir = cache.build_dir(cache_key, _spill_chunks)
                    self.logger.info(f"tensor cache stored {cache_key[:12]}")
                except RetryError as e:
                    self.logger.info(f"tensor cache unusable (uncached): {e}")
                    source_dir = None
        if source_dir is None:
            source_dir = os.path.join(p.output_dir, "stream-chunks")
            # stale chunks from an aborted prior run must never be trained
            # on — and a FAILED purge must be loud, not a silent mixed model
            import shutil

            if os.path.exists(source_dir):
                shutil.rmtree(source_dir)  # raises loudly if the purge fails
            os.makedirs(source_dir)
            _spill_chunks(source_dir)
        self.streaming_source = ChunkedGLMSource.from_chunk_dir(source_dir)

        needs_summary = (
            p.normalization_type != NormalizationType.NONE
            or p.summarization_output_dir is not None
        )
        if needs_summary:
            self.summary = streaming_summarize(self.streaming_source)
            if p.summarization_output_dir:
                write_basic_statistics(
                    self.summary, p.summarization_output_dir, self.index_map
                )
        if p.normalization_type != NormalizationType.NONE:
            intercept = self.index_map.intercept_index
            self.norm = NormalizationContext.build(
                p.normalization_type,
                mean=self.summary.mean,
                std=self.summary.std,
                max_magnitude=self.summary.max_magnitude,
                intercept_id=intercept if intercept >= 0 else None,
            )

        if p.validating_data_dir:
            if p.input_file_format == InputFormatType.LIBSVM:
                vds = read_libsvm(
                    self._input_paths(p.validating_data_dir)[0],
                    dim=len(self.index_map) - int(p.add_intercept),
                    add_intercept=p.add_intercept,
                )
            else:
                vds = self._read_avro(p.validating_data_dir)
            self.validation_batch = to_batch(vds, dense=True)
            sanity_check_data(self.validation_batch, p.task_type, p.data_validation_type)
        self._advance(DriverStage.PREPROCESSED)

    def preprocess(self) -> None:
        self._assert_stage(DriverStage.INIT)
        p = self.params
        if p.streaming_chunk_rows > 0:
            self._preprocess_streaming()
            return

        if p.input_file_format == InputFormatType.LIBSVM:
            paths = self._input_paths(p.training_data_dir)
            dim = p.feature_dimension if p.feature_dimension > 0 else None
            ds = read_libsvm(paths[0], dim=dim, add_intercept=p.add_intercept)
            for extra in paths[1:]:
                more = read_libsvm(extra, dim=ds.dim - int(p.add_intercept),
                                   add_intercept=p.add_intercept)
                ds = _concat_datasets(ds, more)
            self.train_ds = ds
            names = [str(i) for i in range(ds.dim - int(p.add_intercept))]
            if p.add_intercept:
                names.append(INTERCEPT_KEY)
            self.index_map = IndexMap({k: i for i, k in enumerate(names)}, names)
        else:
            self.index_map = self._build_index_map()
            self.train_ds = self._read_avro(p.training_data_dir)

        dense = self.train_ds.dim <= DENSE_DIM_THRESHOLD
        self.train_batch = to_batch(self.train_ds, dense=dense)
        self.logger.info(
            f"training data: {self.train_ds.num_rows} rows x {self.train_ds.dim} "
            f"features ({'dense' if dense else 'sparse'} layout)"
        )

        sanity_check_data(self.train_batch, p.task_type, p.data_validation_type)

        needs_summary = (
            p.normalization_type != NormalizationType.NONE
            or p.summarization_output_dir is not None
            or p.diagnostic_mode.runs_train
            or p.diagnostic_mode.runs_validate
        )
        if needs_summary:
            self.summary = summarize(self.train_batch)
            if p.summarization_output_dir:
                write_basic_statistics(
                    self.summary, p.summarization_output_dir, self.index_map
                )

        if p.normalization_type != NormalizationType.NONE:
            intercept = self.index_map.intercept_index
            self.norm = NormalizationContext.build(
                p.normalization_type,
                mean=self.summary.mean,
                std=self.summary.std,
                max_magnitude=self.summary.max_magnitude,
                intercept_id=intercept if intercept >= 0 else None,
            )

        if p.validating_data_dir:
            if p.input_file_format == InputFormatType.LIBSVM:
                vds = read_libsvm(
                    self._input_paths(p.validating_data_dir)[0],
                    dim=self.train_ds.dim - int(p.add_intercept),
                    add_intercept=p.add_intercept,
                )
            else:
                vds = self._read_avro(p.validating_data_dir)
            self.validation_batch = to_batch(vds, dense=dense)
            sanity_check_data(self.validation_batch, p.task_type, p.data_validation_type)

        self._advance(DriverStage.PREPROCESSED)

    # ------------------------------------------------------------------
    # stage: train
    # ------------------------------------------------------------------
    def _regularization_context(self) -> RegularizationContext:
        p = self.params
        if p.regularization_type == RegularizationType.NONE:
            return RegularizationContext.none()
        if p.regularization_type == RegularizationType.L1:
            return RegularizationContext.l1(1.0)
        if p.regularization_type == RegularizationType.ELASTIC_NET:
            return RegularizationContext.elastic_net(
                1.0, p.elastic_net_alpha if p.elastic_net_alpha is not None else 0.5
            )
        return RegularizationContext.l2(1.0)

    def _constraints(self) -> Optional[BoxConstraints]:
        p = self.params
        if not p.coefficient_box_constraints:
            return None
        cmap = parse_constraint_string(
            p.coefficient_box_constraints, self.index_map.name_to_index
        )
        if not cmap:
            return None
        return BoxConstraints.from_map(len(self.index_map), cmap)

    def _to_raw_space(self, model: GeneralizedLinearModel) -> GeneralizedLinearModel:
        if self.norm.is_identity:
            return model
        w = self.norm.model_to_original_space(model.coefficients.means)
        variances = model.coefficients.variances
        if variances is not None and self.norm.factors is not None:
            variances = variances * jnp.square(self.norm.factors)
        return GeneralizedLinearModel(Coefficients(w, variances), model.task)

    def train(self) -> None:
        self._assert_stage(DriverStage.PREPROCESSED)
        p = self.params
        self.problem = GLMOptimizationProblem(
            task=p.task_type,
            optimizer=p.optimizer_type,
            optimizer_config=OptimizerConfig(
                max_iterations=p.max_num_iterations, tolerance=p.tolerance
            ),
            regularization=self._regularization_context(),
            compute_variance=p.compute_variance,
            constraints=self._constraints(),
            # per-iteration coefficient snapshots back the ModelTracker-style
            # validate-per-iteration pass (Driver.scala:292-361)
            track_coefficients=p.validate_per_iteration,
        )
        from photon_ml_tpu.utils.profiling import maybe_trace

        with maybe_trace("glm-train"):
            if self.streaming_source is not None:
                from photon_ml_tpu.compile import resolve_bucketer
                from photon_ml_tpu.training import train_glm_grid_streaming

                self.trained = train_glm_grid_streaming(
                    self.problem, self.streaming_source, self.norm,
                    p.regularization_weights,
                    bucketer=resolve_bucketer(p.shape_canonicalization),
                )
                # the spilled chunks are dead weight once training completes
                import shutil

                shutil.rmtree(
                    os.path.join(p.output_dir, "stream-chunks"),
                    ignore_errors=True,
                )
            else:
                self.trained = train_glm_grid(
                    self.problem, self.train_batch, self.norm,
                    p.regularization_weights,
                )
        self.models = [
            (lam, self._to_raw_space(m))
            for lam, m in zip(self.trained.weights, self.trained.models)
        ]
        for lam, res in zip(self.trained.weights, self.trained.results):
            self.logger.info(f"lambda={lam:g}: {summarize_result(res)}")
            if p.enable_optimization_state_tracker:
                hist = np.asarray(res.value_history)
                hist = hist[~np.isnan(hist)]
                self.logger.debug(
                    f"lambda={lam:g} value history: "
                    + " ".join(f"{v:.6g}" for v in hist)
                )

        write_models_in_text(
            self.models,
            os.path.join(p.output_dir, LEARNED_MODELS_TEXT),
            self.index_map,
        )
        self._advance(DriverStage.TRAINED)

    # ------------------------------------------------------------------
    # stage: validate
    # ------------------------------------------------------------------
    def validate(self) -> None:
        self._assert_stage(DriverStage.TRAINED)
        best_lam, best_model, all_metrics = select_best_model(
            self.models, self.validation_batch
        )
        self.best_reg_weight = best_lam
        self.best_model = best_model
        self.validation_metrics = all_metrics
        for lam in sorted(all_metrics):
            for name, value in sorted(all_metrics[lam].items()):
                self.logger.info(f"lambda={lam:g} {name}: {value:.6g}")
        if self.params.validate_per_iteration:
            self._validate_per_iteration()
        self.logger.info(f"best model: lambda={best_lam:g}")
        write_models_in_text(
            [(best_lam, best_model)],
            os.path.join(self.params.output_dir, "best"),
            self.index_map,
        )
        self._advance(DriverStage.VALIDATED)

    def _validate_per_iteration(self) -> None:
        """Validation metrics for EVERY iteration's model snapshot
        (Driver.scala:292-361: computeAndLogModelMetrics over the
        ModelTrackers). Snapshots live in the solve results'
        coefficient_history (row 0 = w0, row k = after iteration k);
        results land in ``self.per_iteration_metrics[lambda]`` as one
        metric map per completed iteration, and the per-task selection
        metric is logged per iteration."""
        from photon_ml_tpu.model_selection import selection_metric_for

        p = self.params
        sel_metric = selection_metric_for(p.task_type)
        self.per_iteration_metrics = {}
        for lam, res in zip(self.trained.weights, self.trained.results):
            hist = res.coefficient_history
            if hist is None:
                continue
            iters = int(res.iterations)
            per_iter = []
            for it in range(1, iters + 1):
                if it == iters and lam in self.validation_metrics:
                    # hist[iters] IS the final model — its metrics were
                    # already computed during model selection
                    m = self.validation_metrics[lam]
                else:
                    snap = GeneralizedLinearModel(
                        Coefficients(hist[it]), p.task_type
                    )
                    m = metrics_mod.evaluate(
                        self._to_raw_space(snap), self.validation_batch
                    )
                per_iter.append(m)
                self.logger.info(
                    f"lambda={lam:g} iteration {it}/{iters} "
                    f"{sel_metric}: {m[sel_metric]:.6g}"
                )
            self.per_iteration_metrics[lam] = per_iter

    # ------------------------------------------------------------------
    # stage: diagnose
    # ------------------------------------------------------------------
    def diagnose(self) -> None:
        p = self.params
        feature_names = [
            (self.index_map.get_feature_name(j) or str(j)).replace(DELIMITER, ":")
            for j in range(len(self.index_map))
        ]
        model_reports: List[ModelDiagnosticReport] = []

        import dataclasses as _dc

        # diagnostics never read coefficient histories — don't let a
        # --validate-per-iteration run carry (max_iter+1, D) tracking
        # buffers through every prefix/bootstrap solve
        diag_problem = _dc.replace(self.problem, track_coefficients=False)

        fitting_reports = {}
        if p.diagnostic_mode.runs_train:
            fitting_reports = fitting.diagnose(
                diag_problem,
                self.train_batch,
                self.norm,
                p.regularization_weights,
            )

        from photon_ml_tpu.diagnostics import avro_reports
        from photon_ml_tpu.types import ConvergenceReason

        results_by_lam = dict(zip(self.trained.weights, self.trained.results))
        eval_records = []

        for lam, model in self.models:
            sections = []
            if p.diagnostic_mode.runs_validate and self.validation_batch is not None:
                metrics = self.validation_metrics.get(lam)
                if metrics is None:
                    metrics = metrics_mod.evaluate(model, self.validation_batch)
                sections.append(
                    feature_importance.to_section(
                        feature_importance.diagnose(
                            model, self.summary, feature_names=feature_names
                        )
                    )
                )
                sections.append(
                    independence.to_section(
                        independence.diagnose(model, self.validation_batch)
                    )
                )
                if p.task_type == TaskType.LOGISTIC_REGRESSION:
                    sections.append(
                        hosmer_lemeshow.to_section(
                            hosmer_lemeshow.diagnose(model, self.validation_batch)
                        )
                    )
            else:
                metrics = metrics_mod.evaluate(model, self.train_batch)
            if p.diagnostic_mode.runs_train and lam in fitting_reports:
                sections.append(fitting.to_section({lam: fitting_reports[lam]}))
            model_reports.append(
                ModelDiagnosticReport(model, lam, metrics, sections)
            )

            # machine-readable EvaluationResultAvro per model (the schemas the
            # reference ships for offline consumers; VERDICT r2 missing #5).
            # The batch/path pair MUST match where `metrics` was computed
            # above (validation only when runs_validate chose it).
            res = results_by_lam.get(lam)
            reg = self._regularization_context().with_weight(lam)
            on_validation = (
                p.diagnostic_mode.runs_validate and self.validation_batch is not None
            )
            eval_batch = self.validation_batch if on_validation else self.train_batch
            data_path = (
                p.validating_data_dir if on_validation else p.training_data_dir
            )
            with_curves = p.task_type in (
                TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            )
            # score only when the curves will consume it
            scores = (
                np.asarray(model.compute_mean_functions(eval_batch))
                if with_curves
                else None
            )
            eval_records.append(
                avro_reports.evaluation_result(
                    model_id=f"{p.job_name}-lambda-{lam:g}",
                    model_path=os.path.join(p.output_dir, LEARNED_MODELS_TEXT),
                    data_path=data_path,
                    train_ctx=avro_reports.training_context(
                        p.task_type,
                        reg.l1_weight,
                        reg.l2_weight,
                        p.normalization_type != NormalizationType.NONE,
                        p.optimizer_type.value,
                        p.tolerance,
                        p.max_num_iterations,
                        ConvergenceReason(int(res.reason)) if res is not None else None,
                        p.training_data_dir,
                    ),
                    scalar_metrics=metrics,
                    scores=scores,
                    labels=np.asarray(eval_batch.labels),
                    weights=np.asarray(eval_batch.weights),
                    with_curves=with_curves,
                )
            )

        if p.diagnostic_mode.runs_train and self.validation_batch is not None:
            # dataset-level bootstrap at the best (or first) lambda
            lam0 = self.best_reg_weight if self.best_reg_weight is not None else self.models[0][0]
            boot_problem = _dc.replace(
                diag_problem,
                regularization=self.problem.regularization.with_weight(lam0),
            )
            boot = bootstrap_diagnostic.diagnose(
                boot_problem,
                self.train_batch,
                self.norm,
                self.validation_batch,
                feature_names=feature_names,
            )
            model_reports[0].sections.append(bootstrap_diagnostic.to_section(boot))

        doc = assemble_document(
            f"{p.job_name} model diagnostics",
            SystemReport(
                {
                    "task": p.task_type.value,
                    "optimizer": p.optimizer_type.value,
                    "regularization": p.regularization_type.value,
                    "lambdas": p.regularization_weights,
                    "normalization": p.normalization_type.value,
                    "training data": p.training_data_dir,
                    "validating data": p.validating_data_dir or "(none)",
                },
                self.summary,
                feature_names,
            ),
            model_reports,
        )
        with open(os.path.join(p.output_dir, REPORT_FILE), "w") as f:
            f.write(render_html(doc))
        self.logger.info(f"wrote {REPORT_FILE}")

        diag_dir = os.path.join(p.output_dir, "diagnostics")
        avro_reports.write_evaluation_results(diag_dir, eval_records)
        avro_reports.write_feature_summaries(
            diag_dir, avro_reports.feature_summaries(feature_names, self.summary)
        )
        self.logger.info(
            f"wrote {len(eval_records)} EvaluationResultAvro + feature summaries "
            f"to {diag_dir}"
        )
        if self.stage == DriverStage.TRAINED:
            self._advance(DriverStage.VALIDATED)  # keep ordering monotone
        self._advance(DriverStage.DIAGNOSED)


def _concat_datasets(a: HostDataset, b: HostDataset) -> HostDataset:
    if a.dim != b.dim:
        raise ValueError(f"feature dims differ: {a.dim} vs {b.dim}")

    def cat(x, y, fill):
        # fill must match to_batch's default for a missing column: offsets
        # default to 0, weights default to 1
        if x is None and y is None:
            return None
        x = x if x is not None else np.full(a.num_rows, fill, np.float32)
        y = y if y is not None else np.full(b.num_rows, fill, np.float32)
        return np.concatenate([x, y])

    return HostDataset(
        labels=np.concatenate([a.labels, b.labels]),
        indptr=np.concatenate([a.indptr, b.indptr[1:] + a.indptr[-1]]),
        indices=np.concatenate([a.indices, b.indices]),
        values=np.concatenate([a.values, b.values]),
        dim=a.dim,
        offsets=cat(a.offsets, b.offsets, 0.0),
        weights=cat(a.weights, b.weights, 1.0),
    )


def main(argv: Optional[List[str]] = None) -> Driver:
    import sys

    from photon_ml_tpu.resilience import preemption

    params = parse_from_command_line(argv)
    driver = Driver(params)
    # cooperative interruption: SIGTERM/SIGINT set the preemption flag; a
    # loop that polls (e.g. a compacted solve's chunk boundary) drains and
    # unwinds here, and the process exits with the distinct preemption code
    # so a supervisor (tools/run_supervised.py) can tell "rescheduled" from
    # "broken" and relaunch
    with preemption.signal_scope():
        try:
            driver.run()
        except preemption.Preempted as e:
            print(
                f"photon-ml-tpu glm: preempted ({e}); exiting "
                f"{preemption.PREEMPT_EXIT_CODE}",
                file=sys.stderr,
            )
            raise SystemExit(preemption.PREEMPT_EXIT_CODE) from e
    return driver


if __name__ == "__main__":
    main()
