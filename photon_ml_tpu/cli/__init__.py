"""Command-line drivers (reference L9 parity: Driver.scala, cli/game/)."""
