"""GAME scoring driver: load a saved GAME model, score data, save + evaluate.

Reference spec: cli/game/scoring/Driver.scala:50-241 — prepare feature maps,
load GAME data (response optional), load the model from its on-disk layout
(ModelProcessingUtils.loadGameModelFromHDFS), total score = sum of
coordinate scores + offset (GAMEModel.scala:92-94), save ScoringResultAvro
shards (:142-162), evaluate per requested evaluator (:222-236).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.cli.game_params import GameScoringParams, parse_scoring_params
from photon_ml_tpu.cli.game_training_driver import _input_files, resolve_date_range_dirs
from photon_ml_tpu.evaluation.evaluators import evaluator_for
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import avro_data, model_io, schemas
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.utils.io_utils import prepare_output_dir
from photon_ml_tpu.utils.logging import PhotonLogger

SCORES_DIR = "scores"


class GameScoringDriver:
    def __init__(self, params: GameScoringParams, logger: Optional[PhotonLogger] = None):
        params.validate()
        self.params = params
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(
            os.path.join(params.output_dir, "photon-ml-tpu-scoring.log")
        )
        self.shard_index_maps: Dict[str, IndexMap] = {}
        self.scores: Optional[np.ndarray] = None
        self.metrics: Dict[str, float] = {}
        # resolved once (date-range expansion walks the daily tree)
        self._input_paths: Optional[List[str]] = None

    def _resolved_input_paths(self) -> List[str]:
        if self._input_paths is None:
            p = self.params
            self._input_paths = _input_files(
                resolve_date_range_dirs(p.input_dirs, p.date_range, p.date_range_days_ago)
            )
        return self._input_paths

    # ------------------------------------------------------------------
    def _load_model_layout(self):
        """Discover coordinates + their shard/id bindings from the model dir."""
        layout = model_io.list_game_model(self.params.game_model_input_dir)
        fixed, random = [], []
        for name in layout[model_io.FIXED_EFFECT]:
            base = os.path.join(
                self.params.game_model_input_dir, model_io.FIXED_EFFECT, name
            )
            with open(os.path.join(base, model_io.ID_INFO)) as f:
                shard = f.read().strip()
            fixed.append((name, shard))
        for name in layout[model_io.RANDOM_EFFECT]:
            base = os.path.join(
                self.params.game_model_input_dir, model_io.RANDOM_EFFECT, name
            )
            with open(os.path.join(base, model_io.ID_INFO)) as f:
                lines = f.read().splitlines()
            re_id = lines[0] if lines else ""
            shard = lines[1] if len(lines) > 1 else ""
            random.append((name, re_id, shard))
        return fixed, random

    def _prepare_feature_maps(self, shards: List[str]) -> None:
        p = self.params
        paths = self._resolved_input_paths()
        for shard in shards:
            if p.offheap_indexmap_dir:
                from photon_ml_tpu.io.offheap import load_shard_index_map

                self.shard_index_maps[shard] = load_shard_index_map(
                    p.offheap_indexmap_dir, shard
                )
            else:
                sections = p.feature_shard_sections.get(shard) or ["features"]
                keys = avro_data.collect_feature_keys(paths, sections)
                add_intercept = p.feature_shard_intercepts.get(shard, True)
                self.shard_index_maps[shard] = IndexMap.build(keys, add_intercept)

    # ------------------------------------------------------------------
    def run(self) -> None:
        p = self.params
        prepare_output_dir(p.output_dir, p.delete_output_dir_if_exists)
        try:
            fixed, random = self._load_model_layout()
            shards = sorted(
                {s for _, s in fixed if s} | {s for _, _, s in random if s}
            )
            self._prepare_feature_maps(shards)
            id_types = sorted(
                set(p.random_effect_id_types) | {rid for _, rid, _ in random if rid}
            )
            data = avro_data.read_game_data(
                self._resolved_input_paths(),
                self.shard_index_maps,
                p.feature_shard_sections,
                id_types,
                shard_intercepts=p.feature_shard_intercepts or None,
                # evaluators need labels; pure inference reads tolerate nulls
                response_required=bool(p.evaluators),
            )
            self.logger.info(f"scoring {data.num_rows} rows")

            total = np.asarray(data.offset, np.float64).copy()
            for name, shard in fixed:
                means, _, _, _ = model_io.load_fixed_effect(
                    p.game_model_input_dir, name, self.shard_index_maps[shard]
                )
                feats = data.shards[shard]
                # CSR matvec on host (scoring path is IO-bound)
                contrib = np.zeros(data.num_rows)
                nnz_rows = np.repeat(np.arange(data.num_rows), np.diff(feats.indptr))
                np.add.at(contrib, nnz_rows, means[feats.indices] * feats.values)
                total += contrib
                self.logger.info(f"fixed effect {name!r} applied")

            for name, re_id, shard in random:
                entity_means, _, _, _ = model_io.load_random_effect(
                    p.game_model_input_dir, name, self.shard_index_maps[shard]
                )
                feats = data.shards[shard]
                vocab = data.id_vocabs[re_id]
                # entity-grouped scoring: one dense model row in memory at a
                # time (never a (num_entities x num_features) matrix)
                contrib = np.zeros(data.num_rows)
                nnz_rows = np.repeat(np.arange(data.num_rows), np.diff(feats.indptr))
                ent_of_nnz = data.ids[re_id][nnz_rows]
                order = np.argsort(ent_of_nnz, kind="stable")
                sorted_ent = ent_of_nnz[order]
                bounds = np.searchsorted(
                    sorted_ent, np.arange(len(vocab) + 1), side="left"
                )
                matched = 0
                for vi, raw in enumerate(vocab):
                    w_row = entity_means.get(raw)
                    if w_row is None:
                        continue  # rows of this entity score 0 (:129-158)
                    matched += 1
                    sel = order[bounds[vi]:bounds[vi + 1]]
                    np.add.at(
                        contrib, nnz_rows[sel], w_row[feats.indices[sel]] * feats.values[sel]
                    )
                total += contrib
                self.logger.info(
                    f"random effect {name!r}: {matched}/{len(vocab)} entities matched"
                )

            self.scores = total.astype(np.float32)
            self._save_scores(data)
            self._evaluate(data)
        finally:
            if self._own_logger:
                self.logger.close()

    # ------------------------------------------------------------------
    def _save_scores(self, data) -> None:
        p = self.params
        out = os.path.join(p.output_dir, SCORES_DIR)
        os.makedirs(out, exist_ok=True)
        n = data.num_rows
        shards = max(p.num_output_files_for_scores, 1)
        per = (n + shards - 1) // shards

        for i in range(shards):
            lo, hi = i * per, min((i + 1) * per, n)

            def records(lo=lo, hi=hi):
                for r in range(lo, hi):
                    label = float(data.response[r])
                    yield {
                        "uid": str(r),
                        "label": None if np.isnan(label) else label,
                        "modelId": p.game_model_id,
                        "predictionScore": float(self.scores[r]),
                        "weight": float(data.weight[r]),
                        "metadataMap": None,
                    }

            avro_io.write_container(
                os.path.join(out, f"part-{i:05d}.avro"),
                records(),
                schemas.SCORING_RESULT,
            )
        self.logger.info(f"wrote scores to {out}")

    def _evaluate(self, data) -> None:
        labels = jnp.asarray(data.response)
        weights = jnp.asarray(data.weight)
        scores = jnp.asarray(self.scores)
        for etype, k, id_name in self.params.evaluators:
            ev = evaluator_for(etype, k or 10)
            kwargs = {"labels": labels, "weights": weights}
            if id_name is not None:
                kwargs["group_ids"] = jnp.asarray(data.ids[id_name])
            key = etype.value if k is None else f"{etype.value}@{k}"
            self.metrics[key] = float(ev.evaluate(scores, **kwargs))
            self.logger.info(f"{key}: {self.metrics[key]:.6g}")


def main(argv: Optional[List[str]] = None) -> GameScoringDriver:
    params = parse_scoring_params(argv)
    driver = GameScoringDriver(params)
    driver.run()
    return driver


if __name__ == "__main__":
    main()
