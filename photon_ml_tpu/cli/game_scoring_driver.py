"""GAME scoring driver: load a saved GAME model, score data, save + evaluate.

Reference spec: cli/game/scoring/Driver.scala:50-241 — prepare feature maps,
load GAME data (response optional), load the model from its on-disk layout
(ModelProcessingUtils.loadGameModelFromHDFS), total score = sum of
coordinate scores + offset (GAMEModel.scala:92-94), save ScoringResultAvro
shards (:142-162), evaluate per requested evaluator (:222-236).

Scoring runs ON DEVICE (VERDICT r2 weak #4): fixed effects are one sparse
matvec; random effects stack the per-entity models into an (E, D) slab and
gather per-row coefficients by entity position — the same static-gather
design as algorithm/random_effect.py:111-122 (the reference's cogroup,
RandomEffectModel.scala:129-158, precomputed to indices). Set
``host_scoring=True`` (or --host-scoring) to force the reference-style
NumPy path — kept as the parity oracle for the device path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.cli.game_params import GameScoringParams, parse_scoring_params
from photon_ml_tpu.cli.game_training_driver import _input_files, resolve_date_range_dirs
from photon_ml_tpu.evaluation.evaluators import evaluator_for
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import avro_data, model_io, schemas
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.utils.io_utils import prepare_output_dir
from photon_ml_tpu.utils.logging import PhotonLogger

SCORES_DIR = "scores"


def _padded_sparse(feats):
    """HostFeatures CSR -> device SparseFeatures (padded (N, K) COO;
    pad index 0 with value 0 = gather-safe no-op)."""
    from photon_ml_tpu.data.game import padded_row_coo
    from photon_ml_tpu.ops.features import SparseFeatures

    cols, vals = padded_row_coo(feats, pad_col=0)
    return SparseFeatures(jnp.asarray(cols), jnp.asarray(vals), feats.dim)


def _re_gather_contrib_impl(slab, ent_pos, idx, vals):
    """score_n = sum_k vals_nk * slab[ent_pos_n, idx_nk]; ent_pos -1 -> 0."""
    safe_e = jnp.maximum(ent_pos, 0)
    gathered = slab[safe_e[:, None], idx]
    valid = ent_pos[:, None] >= 0
    return jnp.sum(jnp.where(valid, gathered * vals, 0.0), axis=-1)


def _factored_contrib_impl(latent, matrix, ent_pos, idx, vals):
    """Factored scoring straight from the LATENT structure: xp_n = sum_j
    val_nj * M[:, col_nj], score_n = xp_n . latent[ent_pos_n] — the (E, k)
    factors + (k, D) matrix never get flattened to (E, D)
    (FactoredRandomEffectCoordinate.score semantics over saved models)."""
    safe_e = jnp.maximum(ent_pos, 0)
    m_cols = matrix.T[idx]  # (N, K, k)
    xp = jnp.sum(m_cols * vals[:, :, None], axis=1)  # (N, k)
    contrib = jnp.sum(xp * latent[safe_e], axis=-1)
    return jnp.where(ent_pos >= 0, contrib, 0.0)


_re_gather_contrib = None  # jitted lazily (keeps module import off-device)
_factored_contrib = None


def _get_re_gather():
    global _re_gather_contrib
    if _re_gather_contrib is None:
        import jax

        _re_gather_contrib = jax.jit(_re_gather_contrib_impl)
    return _re_gather_contrib


def _get_factored_contrib():
    global _factored_contrib
    if _factored_contrib is None:
        import jax

        _factored_contrib = jax.jit(_factored_contrib_impl)
    return _factored_contrib


def _entity_positions(vocab, by_raw_id, ids, fallback_width):
    """Stack the per-entity vectors present in ``by_raw_id`` and map each
    data row's vocab id to its stack position (-1 = no model, scores 0 —
    RandomEffectModel.scala:129-158 semantics)."""
    pos = np.full(len(vocab), -1, np.int32)
    rows = []
    for vi, raw in enumerate(vocab):
        vec = by_raw_id.get(raw)
        if vec is not None:
            pos[vi] = len(rows)
            rows.append(vec)
    stacked = (
        np.stack(rows).astype(np.float32)
        if rows
        else np.zeros((1, fallback_width), np.float32)
    )
    ent_pos = np.where(ids >= 0, pos[np.maximum(ids, 0)], -1).astype(np.int32)
    return stacked, ent_pos, len(rows)


class GameScoringDriver:
    def __init__(self, params: GameScoringParams, logger: Optional[PhotonLogger] = None):
        params.validate()
        self.params = params
        self.host_scoring = getattr(params, "host_scoring", False)
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(
            os.path.join(params.output_dir, "photon-ml-tpu-scoring.log")
        )
        self.shard_index_maps: Dict[str, IndexMap] = {}
        self.scores: Optional[np.ndarray] = None
        self.metrics: Dict[str, float] = {}
        # resolved once (date-range expansion walks the daily tree)
        self._input_paths: Optional[List[str]] = None

    def _resolved_input_paths(self) -> List[str]:
        if self._input_paths is None:
            p = self.params
            self._input_paths = _input_files(
                resolve_date_range_dirs(p.input_dirs, p.date_range, p.date_range_days_ago)
            )
        return self._input_paths

    # ------------------------------------------------------------------
    def _load_model_layout(self):
        """Discover coordinates + their shard/id bindings from the model dir."""
        layout = model_io.list_game_model(self.params.game_model_input_dir)
        fixed, random = [], []
        for name in layout[model_io.FIXED_EFFECT]:
            base = os.path.join(
                self.params.game_model_input_dir, model_io.FIXED_EFFECT, name
            )
            with open(os.path.join(base, model_io.ID_INFO)) as f:
                shard = f.read().strip()
            fixed.append((name, shard))
        for name in layout[model_io.RANDOM_EFFECT]:
            base = os.path.join(
                self.params.game_model_input_dir, model_io.RANDOM_EFFECT, name
            )
            with open(os.path.join(base, model_io.ID_INFO)) as f:
                lines = f.read().splitlines()
            re_id = lines[0] if lines else ""
            shard = lines[1] if len(lines) > 1 else ""
            random.append((name, re_id, shard))
        return fixed, random

    def _prepare_feature_maps(self, shards: List[str]) -> None:
        p = self.params
        paths = self._resolved_input_paths()
        for shard in shards:
            if p.offheap_indexmap_dir:
                from photon_ml_tpu.io.offheap import load_shard_index_map

                self.shard_index_maps[shard] = load_shard_index_map(
                    p.offheap_indexmap_dir, shard
                )
            else:
                sections = p.feature_shard_sections.get(shard) or ["features"]
                keys = avro_data.collect_feature_keys(paths, sections)
                add_intercept = p.feature_shard_intercepts.get(shard, True)
                self.shard_index_maps[shard] = IndexMap.build(keys, add_intercept)

    # ------------------------------------------------------------------
    def run(self) -> None:
        import dataclasses

        from photon_ml_tpu import resilience

        p = self.params
        with resilience.resilience_scope(
            resilience.ResilienceConfig(
                on_corrupt=p.on_corrupt,
                corrupt_skip_budget=p.corrupt_skip_budget,
                # --io-retries overrides attempts; backoff shape keeps the
                # env-tunable defaults (PHOTON_IO_RETRY_* knobs)
                io_policy=dataclasses.replace(
                    resilience.RetryPolicy.io_default(),
                    max_attempts=p.io_retries,
                ),
            )
        ):
            self._run_guarded()

    def _run_guarded(self) -> None:
        p = self.params
        prepare_output_dir(p.output_dir, p.delete_output_dir_if_exists)
        try:
            fixed, random = self._load_model_layout()
            shards = sorted(
                {s for _, s in fixed if s} | {s for _, _, s in random if s}
            )
            self._prepare_feature_maps(shards)
            id_types = sorted(
                set(p.random_effect_id_types) | {rid for _, rid, _ in random if rid}
            )
            data = avro_data.read_game_data(
                self._resolved_input_paths(),
                self.shard_index_maps,
                p.feature_shard_sections,
                id_types,
                shard_intercepts=p.feature_shard_intercepts or None,
                # evaluators need labels; pure inference reads tolerate nulls
                response_required=bool(p.evaluators),
            )
            self.logger.info(f"scoring {data.num_rows} rows")

            if self.host_scoring:
                total = self._score_host(data, fixed, random)
            else:
                total = self._score_device(data, fixed, random)

            self.scores = np.asarray(total, np.float32)
            self._save_scores(data)
            self._evaluate(data)
        finally:
            if self._own_logger:
                self.logger.close()

    # ------------------------------------------------------------------
    def _score_device(self, data, fixed, random) -> np.ndarray:
        """Device-side scoring: sparse matvec for fixed effects; per-entity
        slab + static gathers for random effects."""
        import jax

        p = self.params
        n = data.num_rows
        total = jnp.asarray(data.offset, jnp.float32)

        fixed_matvec = jax.jit(lambda feats, w: feats.matvec(w))
        for name, shard in fixed:
            means, _, _, _ = model_io.load_fixed_effect(
                p.game_model_input_dir, name, self.shard_index_maps[shard]
            )
            feats = _padded_sparse(data.shards[shard])
            total = total + fixed_matvec(feats, jnp.asarray(means))
            self.logger.info(f"fixed effect {name!r} applied (device)")

        for name, re_id, shard in random:
            vocab = data.id_vocabs[re_id]
            feats = _padded_sparse(data.shards[shard])
            if model_io.is_factored_random_effect(p.game_model_input_dir, name):
                # latent-native scoring: (E, k) factors + (k, D) matrix — the
                # flattened (E, D) slab is never materialized. The matrix
                # columns are positional in the TRAINING feature space;
                # realign them by NAME to this run's index map (which may
                # have been rebuilt from the scoring inputs).
                factors, matrix, _, _ = model_io.load_factored_random_effect(
                    p.game_model_input_dir, name
                )
                matrix_aligned = model_io.aligned_latent_matrix(
                    p.game_model_input_dir, name,
                    self.shard_index_maps[shard], matrix,
                    warn=self.logger.warn,
                )
                latent, ent_pos, matched = _entity_positions(
                    vocab, factors, data.ids[re_id], matrix.shape[0]
                )
                total = total + _get_factored_contrib()(
                    jnp.asarray(latent), jnp.asarray(matrix_aligned),
                    jnp.asarray(ent_pos), feats.indices, feats.values,
                )
                self.logger.info(
                    f"factored random effect {name!r}: {matched}/{len(vocab)} "
                    "entities matched (device, latent-native)"
                )
                continue
            entity_means, _, _, _ = model_io.load_random_effect(
                p.game_model_input_dir, name, self.shard_index_maps[shard]
            )
            slab, ent_pos, matched = _entity_positions(
                vocab, entity_means, data.ids[re_id], feats.dim
            )
            total = total + _get_re_gather()(
                jnp.asarray(slab), jnp.asarray(ent_pos), feats.indices, feats.values
            )
            self.logger.info(
                f"random effect {name!r}: {matched}/{len(vocab)} entities "
                "matched (device)"
            )
        return np.asarray(jax.device_get(total))

    def _score_host(self, data, fixed, random) -> np.ndarray:
        """Reference-style host scoring (the parity oracle for the device
        path; never materializes an (entities x features) matrix)."""
        p = self.params
        total = np.asarray(data.offset, np.float64).copy()
        for name, shard in fixed:
            means, _, _, _ = model_io.load_fixed_effect(
                p.game_model_input_dir, name, self.shard_index_maps[shard]
            )
            feats = data.shards[shard]
            contrib = np.zeros(data.num_rows)
            nnz_rows = np.repeat(np.arange(data.num_rows), np.diff(feats.indptr))
            np.add.at(contrib, nnz_rows, means[feats.indices] * feats.values)
            total += contrib
            self.logger.info(f"fixed effect {name!r} applied")

        for name, re_id, shard in random:
            entity_means, _, _, _ = model_io.load_random_effect(
                p.game_model_input_dir, name, self.shard_index_maps[shard]
            )
            feats = data.shards[shard]
            vocab = data.id_vocabs[re_id]
            contrib = np.zeros(data.num_rows)
            nnz_rows = np.repeat(np.arange(data.num_rows), np.diff(feats.indptr))
            ent_of_nnz = data.ids[re_id][nnz_rows]
            order = np.argsort(ent_of_nnz, kind="stable")
            sorted_ent = ent_of_nnz[order]
            bounds = np.searchsorted(
                sorted_ent, np.arange(len(vocab) + 1), side="left"
            )
            matched = 0
            for vi, raw in enumerate(vocab):
                w_row = entity_means.get(raw)
                if w_row is None:
                    continue  # rows of this entity score 0 (:129-158)
                matched += 1
                sel = order[bounds[vi]:bounds[vi + 1]]
                np.add.at(
                    contrib, nnz_rows[sel], w_row[feats.indices[sel]] * feats.values[sel]
                )
            total += contrib
            self.logger.info(
                f"random effect {name!r}: {matched}/{len(vocab)} entities matched"
            )
        return total

    # ------------------------------------------------------------------
    def _save_scores(self, data) -> None:
        p = self.params
        out = os.path.join(p.output_dir, SCORES_DIR)
        os.makedirs(out, exist_ok=True)
        n = data.num_rows
        shards = max(p.num_output_files_for_scores, 1)
        per = (n + shards - 1) // shards

        for i in range(shards):
            lo, hi = i * per, min((i + 1) * per, n)

            def records(lo=lo, hi=hi):
                for r in range(lo, hi):
                    label = float(data.response[r])
                    yield {
                        "uid": str(r),
                        "label": None if np.isnan(label) else label,
                        "modelId": p.game_model_id,
                        "predictionScore": float(self.scores[r]),
                        "weight": float(data.weight[r]),
                        "metadataMap": None,
                    }

            avro_io.write_container(
                os.path.join(out, f"part-{i:05d}.avro"),
                records(),
                schemas.SCORING_RESULT,
            )
        self.logger.info(f"wrote scores to {out}")

    def _evaluate(self, data) -> None:
        labels = jnp.asarray(data.response)
        weights = jnp.asarray(data.weight)
        scores = jnp.asarray(self.scores)
        for etype, k, id_name in self.params.evaluators:
            ev = evaluator_for(etype, k or 10)
            kwargs = {"labels": labels, "weights": weights}
            if id_name is not None:
                kwargs["group_ids"] = jnp.asarray(data.ids[id_name])
            key = etype.value if k is None else f"{etype.value}@{k}"
            self.metrics[key] = float(ev.evaluate(scores, **kwargs))
            self.logger.info(f"{key}: {self.metrics[key]:.6g}")


def main(argv: Optional[List[str]] = None) -> GameScoringDriver:
    import sys

    from photon_ml_tpu.resilience import preemption

    params = parse_scoring_params(argv)
    driver = GameScoringDriver(params)
    # scoring is restartable from scratch (no descent state): cooperative
    # preemption here just means a clean distinct exit for the supervisor
    with preemption.signal_scope():
        try:
            driver.run()
        except preemption.Preempted as e:
            print(
                f"photon-ml-tpu game-scoring: preempted ({e}); exiting "
                f"{preemption.PREEMPT_EXIT_CODE}",
                file=sys.stderr,
            )
            raise SystemExit(preemption.PREEMPT_EXIT_CODE) from e
    return driver


if __name__ == "__main__":
    main()
