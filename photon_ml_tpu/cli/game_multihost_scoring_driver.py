"""Multi-host SPMD GAME scoring driver: score datasets against models that
NO single host ever holds.

Every host runs the same program under ``jax.distributed``: it loads only
its share of the random-effect model's part files
(ModelProcessingUtils.scala:205-219 layout — the same per-partition model
files the multihost TRAINING driver writes), routes each model record to
its entity's owner device with the stable-hash shuffle, decodes only its
slice of the input rows, routes them to the owners for scoring
(parallel.perhost_ingest.score_routed_rows), and writes its own scores
part file. The fixed-effect model is small and replicated (the broadcast
analogue). This is how a "hundreds of billions of coefficients" model
(reference README.md:73) is SCORED: coefficients stay sharded end to end
— loaded sharded, stored sharded, applied sharded.

Factored/MF models score latent-native: the shared (k, D) matrix is
replicated (it is tiny), each host loads its share of the latent-factor
part files, rows are projected into the k-dim latent space host-side and
routed exactly like a plain random effect in a k-dim feature space.

Scope (v1): AVRO inputs, prebuilt feature maps (--offheap-indexmap-dir).

Run (one process per host):

    python -m photon_ml_tpu.cli.game_multihost_scoring_driver \\
        --multihost-coordinator HOST:PORT --multihost-num-processes N \\
        --multihost-process-id I  <game scoring flags...>
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.cli.game_multihost_driver import _add_multihost_flags
from photon_ml_tpu.cli.game_params import parse_scoring_params
from photon_ml_tpu.cli.game_scoring_driver import SCORES_DIR
from photon_ml_tpu.cli.game_training_driver import (
    _input_files,
    resolve_date_range_dirs,
)
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import model_io, schemas
from photon_ml_tpu.io.avro_data import read_game_data
from photon_ml_tpu.parallel import multihost
from photon_ml_tpu.parallel.perhost_ingest import (
    concat_host_rows,
    csr_to_padded,
    global_row_layout,
    host_file_share,
    HostRows,
    merge_row_vectors,
    per_host_model_slabs,
    score_routed_rows,
)
from photon_ml_tpu.parallel.shuffle import collective_sum
from photon_ml_tpu.utils.io_utils import prepare_output_dir
from photon_ml_tpu.utils.logging import PhotonLogger


def _load_re_model_rows(base: str, part_files: List[str], index_map):
    """Decode THIS host's share of one RE model's part files into sparse
    per-entity coefficient rows (global indices)."""
    ids: List[str] = []
    idx_rows: List[np.ndarray] = []
    val_rows: List[np.ndarray] = []
    for f in part_files:
        for rec in avro_io.read_container(os.path.join(base, f)):
            cols, vals = [], []
            for ntv in rec["means"]:
                j = model_io.ntv_index(ntv, index_map)
                if j >= 0:
                    cols.append(j)
                    vals.append(ntv["value"])
            ids.append(rec["modelId"])
            idx_rows.append(np.asarray(cols, np.int32))
            val_rows.append(np.asarray(vals, np.float32))
    k = max((len(c) for c in idx_rows), default=1)
    k = max(k, 1)
    fi = np.full((len(ids), k), -1, np.int32)
    fv = np.zeros((len(ids), k), np.float32)
    for i, (c, v) in enumerate(zip(idx_rows, val_rows)):
        fi[i, : len(c)] = c
        fv[i, : len(c)] = v
    return ids, fi, fv


def main(argv: Optional[List[str]] = None) -> dict:
    import sys

    mh_args, rest = _add_multihost_flags(
        list(argv) if argv is not None else sys.argv[1:]
    )
    p = parse_scoring_params(rest)
    mh = multihost.initialize(
        coordinator_address=mh_args["coordinator"],
        num_processes=mh_args["num_processes"],
        process_id=mh_args["process_id"],
    )
    ctx = mh.mesh_context()
    if mh.coordinator_only_io():
        prepare_output_dir(p.output_dir, p.delete_output_dir_if_exists)
    mh.barrier("output-dir")
    logger = PhotonLogger(
        os.path.join(p.output_dir, f"photon-ml-tpu-mh-scoring-{mh.process_id}.log")
    )
    if not p.offheap_indexmap_dir:
        raise ValueError(
            "multihost scoring needs prebuilt feature maps: pass "
            "--offheap-indexmap-dir (a full-data vocabulary scan per host "
            "defeats per-host ingest)"
        )

    # ---- model layout -----------------------------------------------------
    layout = model_io.list_game_model(p.game_model_input_dir)
    fixed, random = [], []
    for name in layout[model_io.FIXED_EFFECT]:
        base = os.path.join(p.game_model_input_dir, model_io.FIXED_EFFECT, name)
        with open(os.path.join(base, model_io.ID_INFO)) as f:
            fixed.append((name, f.read().strip()))
    for name in layout[model_io.RANDOM_EFFECT]:
        base = os.path.join(p.game_model_input_dir, model_io.RANDOM_EFFECT, name)
        with open(os.path.join(base, model_io.ID_INFO)) as f:
            lines = f.read().splitlines()
        random.append((
            name, lines[0], lines[1] if len(lines) > 1 else "",
            model_io.is_factored_random_effect(p.game_model_input_dir, name),
        ))

    from photon_ml_tpu.io.offheap import load_shard_index_map

    shards = sorted(
        {s for _, s in fixed if s} | {s for _, _, s, _ in random if s}
    )
    shard_maps = {s: load_shard_index_map(p.offheap_indexmap_dir, s) for s in shards}
    grouped_ids = sorted({idn for _, _, idn in (p.evaluators or []) if idn})
    id_types = sorted(
        set(p.random_effect_id_types)
        | {rid for _, rid, _, _ in random if rid}
        | set(grouped_ids)
    )

    # ---- per-host input decode -------------------------------------------
    # _input_files is deterministic (per-dir sorted, dirs in argument
    # order) and identical on every host — NO global re-sort, so uid/row
    # order matches the single-process scoring driver exactly
    all_files = _input_files(
        resolve_date_range_dirs(p.input_dirs, p.date_range, p.date_range_days_ago)
    )
    host_files = host_file_share(all_files, mh.num_processes, mh.process_id)
    gds = []
    for f, ordinal in host_files:
        gd = read_game_data(
            [f], shard_maps, p.feature_shard_sections, id_types,
            shard_intercepts=p.feature_shard_intercepts or None,
            # evaluators need labels; pure inference tolerates nulls (the
            # single-process driver's rule)
            response_required=bool(p.evaluators),
        )
        gds.append((ordinal, gd))
    file_base, n_global = global_row_layout(
        len(all_files), gds, ctx, mh.num_processes
    )
    logger.info(
        f"host {mh.process_id}: scoring {sum(gd.num_rows for _, gd in gds)}"
        f"/{n_global} rows ({len(host_files)}/{len(all_files)} files)"
    )

    def merge(vec_per_gd):
        return merge_row_vectors(
            gds, file_base, n_global, ctx, mh.num_processes, vec_per_gd
        )

    scores = merge(lambda gd: gd.offset.astype(np.float32)).astype(np.float64)

    # ---- fixed effects: replicated model, local margins -------------------
    for name, shard in fixed:
        means, _, _, _ = model_io.load_fixed_effect(
            p.game_model_input_dir, name, shard_maps[shard]
        )
        local = np.zeros(n_global, np.float32)
        for ordinal, gd in gds:
            f = gd.shards[shard]
            fi, fv = csr_to_padded(f, gd.num_rows)
            sel = np.where(fi >= 0, means[np.maximum(fi, 0)], 0.0)
            local[file_base[ordinal] + np.arange(gd.num_rows)] = np.sum(
                sel * fv, axis=1
            )
        scores += collective_sum(local, ctx, mh.num_processes)

    # ---- random effects: per-host model parts -> owner slabs -> routing ---
    for name, re_id, shard, factored in random:
        if factored:
            # latent-native: v_e (k,) per entity + shared (k, D) matrix.
            # Each host loads its share of the latent-factor part files;
            # the tiny matrix is replicated and rows are PROJECTED into the
            # k-dim latent space host-side before routing — after that the
            # scoring math is identical to a plain RE in a k-dim space.
            fbase = os.path.join(
                p.game_model_input_dir, model_io.RANDOM_EFFECT, name,
            )
            # ONLY the tiny matrix is loaded whole; the per-entity latent
            # factors are read per host below (sharded end to end)
            matrix = model_io.load_latent_matrix(p.game_model_input_dir, name)
            matrix_aligned = model_io.aligned_latent_matrix(
                p.game_model_input_dir, name, shard_maps[shard],
                matrix, warn=logger.warn,
            )
            lat_dir = os.path.join(fbase, model_io.LATENT_FACTORS)
            parts = sorted(f for f in os.listdir(lat_dir) if f.endswith(".avro"))
            my_parts = [f for f, _ in host_file_share(
                parts, mh.num_processes, mh.process_id
            )]
            ids, vecs = [], []
            for f in my_parts:
                for rec in avro_io.read_container(os.path.join(lat_dir, f)):
                    ids.append(rec["effectId"])
                    vecs.append(np.asarray(rec["latentFactor"], np.float32))
            k_lat = matrix.shape[0]
            fv_m = (np.stack(vecs) if vecs
                    else np.zeros((0, k_lat), np.float32))
            fi_m = np.tile(np.arange(k_lat, dtype=np.int32), (len(ids), 1))
            logger.info(
                f"factored effect {name!r}: host {mh.process_id} loaded "
                f"{len(ids)} latent factors "
                f"({len(my_parts)}/{len(parts)} part files)"
            )
            sd, w = per_host_model_slabs(
                ids, fi_m, fv_m, k_lat, ctx, mh.num_processes, mh.process_id,
            )
            vparts = []
            for ordinal, gd in gds:
                f = gd.shards[shard]
                fi, fv = csr_to_padded(f, gd.num_rows)
                # xp = x @ M^T via the padded sparse encoding, accumulated
                # one padded column at a time: O(n*k) memory (a (k, n, K)
                # gather would be k*n*K floats — the memory-scaling the
                # driver exists to avoid). csr_to_padded zero-fills padding
                # values, so masked-column contributions are exact 0s.
                xp = np.zeros((gd.num_rows, matrix_aligned.shape[0]), np.float32)
                for j in range(fi.shape[1]):
                    xp += fv[:, j, None] * matrix_aligned[:, np.maximum(fi[:, j], 0)].T
                vocab = gd.id_vocabs[re_id]
                vparts.append(HostRows(
                    entity_raw_ids=[vocab[i] for i in gd.ids[re_id]],
                    row_index=file_base[ordinal]
                    + np.arange(gd.num_rows, dtype=np.int64),
                    labels=np.nan_to_num(gd.response).astype(np.float32),
                    weights=gd.weight.astype(np.float32),
                    offsets=gd.offset.astype(np.float32),
                    feat_idx=np.tile(
                        np.arange(k_lat, dtype=np.int32), (gd.num_rows, 1)
                    ),
                    feat_val=xp.astype(np.float32),
                    global_dim=k_lat,
                ))
            vrows = concat_host_rows(vparts, k_lat)
            scores += score_routed_rows(
                sd, w, vrows, n_global, ctx, mh.num_processes, mh.process_id
            )
            continue
        base = os.path.join(
            p.game_model_input_dir, model_io.RANDOM_EFFECT, name,
            model_io.COEFFICIENTS,
        )
        parts = sorted(f for f in os.listdir(base) if f.endswith(".avro"))
        my_parts = [f for f, _ in host_file_share(
            parts, mh.num_processes, mh.process_id
        )]
        ids, fi_m, fv_m = _load_re_model_rows(base, my_parts, shard_maps[shard])
        logger.info(
            f"random effect {name!r}: host {mh.process_id} loaded "
            f"{len(ids)} of the model's entities "
            f"({len(my_parts)}/{len(parts)} part files)"
        )
        sd, w = per_host_model_slabs(
            ids, fi_m, fv_m, len(shard_maps[shard]), ctx,
            mh.num_processes, mh.process_id,
        )
        row_parts = []
        for ordinal, gd in gds:
            f = gd.shards[shard]
            fi, fv = csr_to_padded(f, gd.num_rows)
            vocab = gd.id_vocabs[re_id]
            row_parts.append(HostRows(
                entity_raw_ids=[vocab[i] for i in gd.ids[re_id]],
                row_index=file_base[ordinal] + np.arange(gd.num_rows, dtype=np.int64),
                labels=np.nan_to_num(gd.response).astype(np.float32),
                weights=gd.weight.astype(np.float32),
                offsets=gd.offset.astype(np.float32),
                feat_idx=fi, feat_val=fv,
                global_dim=f.dim,
            ))
        vrows = concat_host_rows(row_parts, len(shard_maps[shard]))
        scores += score_routed_rows(
            sd, w, vrows, n_global, ctx, mh.num_processes, mh.process_id
        )

    scores = scores.astype(np.float32)

    # ---- save: each host writes its own scores part files -----------------
    out = os.path.join(p.output_dir, SCORES_DIR)
    if mh.coordinator_only_io():
        os.makedirs(out, exist_ok=True)
    mh.barrier("scores-dir")
    for ordinal, gd in gds:
        base_id = int(file_base[ordinal])

        def records():
            for r in range(gd.num_rows):
                label = float(gd.response[r])
                yield {
                    "uid": str(base_id + r),
                    "label": None if np.isnan(label) else label,
                    "modelId": p.game_model_id,
                    "predictionScore": float(scores[base_id + r]),
                    "weight": float(gd.weight[r]),
                    "metadataMap": None,
                }

        avro_io.write_container(
            os.path.join(out, f"part-{ordinal:05d}.avro"),
            records(),
            schemas.SCORING_RESULT,
        )
    mh.barrier("scores-written")

    # ---- optional evaluators (replicated labels/weights) ------------------
    metrics: Dict[str, float] = {}
    if p.evaluators:
        from photon_ml_tpu.evaluation.evaluators import evaluator_for
        from photon_ml_tpu.parallel.perhost_ingest import merge_group_ids

        labels = merge(lambda gd: gd.response.astype(np.float32))
        weights = merge(lambda gd: gd.weight.astype(np.float32))
        group_cols = {
            idn: jnp.asarray(merge_group_ids(
                gds, file_base, n_global, idn, ctx, mh.num_processes
            ))
            for idn in grouped_ids
        }
        for etype, k, id_name in p.evaluators:
            ev = evaluator_for(etype, k or 10)
            kwargs = {"labels": jnp.asarray(labels),
                      "weights": jnp.asarray(weights)}
            if id_name is not None:
                kwargs["group_ids"] = group_cols[id_name]
            key = etype.value if k is None else f"{etype.value}@{k}"
            metrics[key] = float(ev.evaluate(jnp.asarray(scores), **kwargs))
        if mh.coordinator_only_io():
            logger.info(
                "metrics: " + " ".join(f"{k}={v:.6g}" for k, v in metrics.items())
            )
    logger.info(f"wrote scores to {out}")
    logger.close()
    return {
        "num_rows": n_global,
        "metrics": metrics,
        "process_id": mh.process_id,
        "scores_dir": out,
    }


if __name__ == "__main__":
    main()
