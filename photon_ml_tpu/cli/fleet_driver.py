"""Sharded serving fleet driver (photon_ml_tpu.serve.fleet).

One CLI, three modes (the deployment wires them together — typically N
replica processes plus one router process per serving cell):

**build** (``--build-fleet-stores true``): shard-export a saved GAME model
into ``--fleet-dir`` (one ``replica-<r>/`` store per replica, owned
random-effect slab rows only, replicated fixed effects + feature maps,
``fleet.json`` plan), then exit.

**replica** (``--replica-id R``): open ``replica-R``'s shard store, warm
the ladder (PR 6 startup — persistent cache + warmup + compile summary),
start heartbeats, and serve the fleet protocol over TCP until a
``shutdown`` message. Prints ``READY <host:port>`` on stdout so a
supervisor (or the test harness) can discover an ephemeral port.

**router** (default): connect to ``--replica-addresses``, serve JSON-lines
scoring requests on stdin/stdout through the consistent-hash
scatter/gather path — the SAME wire format as ``serve_driver``, swap
command included (``{"cmd": "swap", "store_dir": <new fleet dir>}`` rolls
the whole fleet atomically).

Usage (2-replica cell)::

    python -m photon_ml_tpu.cli.fleet_driver --fleet-dir /models/fleet \
        --game-model-input-dir /models/best --num-fleet-replicas 2 \
        --build-fleet-stores true
    python -m photon_ml_tpu.cli.fleet_driver --fleet-dir /models/fleet \
        --replica-id 0 --num-fleet-replicas 2 --port 7001 \
        --heartbeat-dir /models/fleet/hb &
    python -m photon_ml_tpu.cli.fleet_driver --fleet-dir /models/fleet \
        --replica-id 1 --num-fleet-replicas 2 --port 7002 \
        --heartbeat-dir /models/fleet/hb &
    python -m photon_ml_tpu.cli.fleet_driver --fleet-dir /models/fleet \
        --num-fleet-replicas 2 \
        --replica-addresses 127.0.0.1:7001,127.0.0.1:7002 \
        --heartbeat-dir /models/fleet/hb < requests.jsonl
"""

from __future__ import annotations

import sys
from typing import List, Optional

from photon_ml_tpu.cli.game_params import GameFleetParams, parse_fleet_params
from photon_ml_tpu.utils.logging import PhotonLogger


class GameFleetDriver:
    """Dispatches one of the three fleet modes."""

    def __init__(
        self, params: GameFleetParams, logger: Optional[PhotonLogger] = None
    ):
        params.validate()
        self.params = params
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(params.log_path)
        self.fleet_meta: Optional[dict] = None
        self.router = None
        self.engine = None
        self.handled = 0

    # -- build mode ----------------------------------------------------------
    def build_stores(self) -> dict:
        from photon_ml_tpu.compile import resolve_bucketer
        from photon_ml_tpu.serve.fleet import build_fleet_stores

        p = self.params
        self.logger.info(
            f"shard-exporting {p.game_model_input_dir} -> "
            f"{p.num_fleet_replicas}-replica fleet {p.fleet_dir}"
        )
        self.fleet_meta = build_fleet_stores(
            p.game_model_input_dir,
            p.fleet_dir,
            num_replicas=p.num_fleet_replicas,
            num_buckets=p.num_buckets,
            bucketer=resolve_bucketer(p.shape_canonicalization),
            store_dtype=p.store_dtype,
        )
        for rep in self.fleet_meta["replicas"]:
            self.logger.info(
                f"replica {rep['replica']}: entities {rep['entities']}"
            )
        return self.fleet_meta

    # -- replica mode --------------------------------------------------------
    def run_replica(self, out_stream=None) -> None:
        from photon_ml_tpu import compat
        from photon_ml_tpu.compile import compile_stats
        from photon_ml_tpu.serve import ModelStore
        from photon_ml_tpu.serve.fleet import (
            ReplicaEngine,
            ReplicaServer,
            replica_store_dir,
        )

        p = self.params
        out = out_stream if out_stream is not None else sys.stdout
        if p.persistent_cache_dir:
            if compat.enable_persistent_cache(p.persistent_cache_dir):
                self.logger.info(
                    f"persistent XLA cache: {p.persistent_cache_dir}"
                )
        compile_stats.install_xla_listeners()
        from photon_ml_tpu.serve.fleet import load_fleet_meta

        # fleet.json BEFORE the store open: load_fleet_meta raises on a
        # mixed-dtype fleet, and an already-open store would leak its
        # mmaps on that raise
        fleet_dtype = load_fleet_meta(p.fleet_dir).get("store_dtype") or "f32"
        store = ModelStore(replica_store_dir(p.fleet_dir, p.replica_id))
        if store.store_dtype != fleet_dtype:
            # the replica-side half of the mixed-dtype refusal, for the
            # stores load_fleet_meta could not read from the router's
            # host (its meta path recorded remote/unreadable): this store
            # was (re-)exported out of band at a different dtype than the
            # fleet plan it would serve under
            store.close()
            raise RuntimeError(
                f"replica {p.replica_id}'s store is {store.store_dtype} "
                f"but fleet.json pins store_dtype {fleet_dtype}; refusing "
                "to serve a mixed-dtype fleet — re-export the whole fleet"
            )
        fp = store.footprint()
        self.logger.info(
            f"replica store footprint: dtype {fp['store_dtype']}, "
            f"{fp['slab_bytes_disk']} slab bytes on disk, "
            f"{fp['mapped_bytes']} bytes mapped"
        )
        self.engine = ReplicaEngine(
            store,
            replica_id=p.replica_id,
            num_replicas=p.num_fleet_replicas,
            heartbeat_dir=p.heartbeat_dir,
            shard_sections=p.feature_shard_sections,
            bucketer=p.shape_canonicalization,
            max_batch_rows=p.max_batch_rows,
            max_wait_ms=p.max_wait_ms,
        )
        self.logger.info(self.engine.describe())
        if p.warmup:
            report = self.engine.warmup(warm_nnz=p.warm_nnz)
            self.logger.info(
                f"replica warmup: {report['warm_batches']} batches, "
                f"{report['new_traces']} traces, "
                f"{report['new_xla_misses']} new XLA compiles"
            )
        self.logger.info(compile_stats.summary())
        server = ReplicaServer(self.engine, host=p.host, port=p.port)
        out.write(f"READY {server.address}\n")
        out.flush()
        self.logger.info(f"replica {p.replica_id} serving on {server.address}")
        try:
            server.serve_until_shutdown()
        finally:
            self.logger.info(self.engine.stats.summary())
            self.engine.close()

    # -- router mode ---------------------------------------------------------
    def run_router(self, in_stream=None, out_stream=None) -> None:
        from photon_ml_tpu.serve import serve_json_lines
        from photon_ml_tpu.serve.fleet import (
            FleetRouter,
            FleetSwapper,
            TcpReplicaClient,
            load_fleet_meta,
        )
        from photon_ml_tpu.serve.stats import FleetStats

        p = self.params
        self.fleet_meta = load_fleet_meta(p.fleet_dir)
        clients = [TcpReplicaClient(addr) for addr in p.replica_addresses]
        self.router = FleetRouter(
            self.fleet_meta,
            clients,
            heartbeat_dir=p.heartbeat_dir,
            heartbeat_deadline_s=p.heartbeat_deadline_s,
            request_timeout_s=p.request_timeout_s,
            hedge_ms=p.hedge_ms,
            stats=FleetStats(),
        )
        swapper = FleetSwapper(self.router)
        self.router.sync_generation()
        self.logger.info(
            f"fleet router up: {self.router.num_replicas} replicas, "
            f"generation {self.router.generation}, live "
            f"{sorted(self.router.live_replicas())}, store dtype "
            f"{self.fleet_meta.get('store_dtype') or 'f32'}"
        )
        try:
            self.handled = serve_json_lines(
                self.router,
                in_stream if in_stream is not None else sys.stdin,
                out_stream if out_stream is not None else sys.stdout,
                swapper=swapper,
            )
        finally:
            self.logger.info(self.router.stats.summary())
            self.router.close()

    # ------------------------------------------------------------------
    def run(self, in_stream=None, out_stream=None) -> None:
        try:
            mode = self.params.mode()
            if mode == "build":
                self.build_stores()
            elif mode == "replica":
                self.run_replica(out_stream=out_stream)
            else:
                self.run_router(in_stream=in_stream, out_stream=out_stream)
        finally:
            if self._own_logger:
                self.logger.close()


def main(argv: Optional[List[str]] = None) -> GameFleetDriver:
    driver = GameFleetDriver(parse_fleet_params(argv))
    driver.run()
    return driver


if __name__ == "__main__":
    main()
