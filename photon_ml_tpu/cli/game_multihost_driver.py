"""Multi-host SPMD GAME training driver.

Every host runs this SAME program under ``jax.distributed``: it decodes
ONLY its slice of the input part files (per-partition decode with the
shared mmap'd feature index, DataProcessingUtils.scala:57-80 semantics),
ingests per host — the collective shuffle regroups random-effect rows by
entity owner (parallel/shuffle.py), fixed-effect rows stay host-local as
uniform row blocks — trains the coordinate descent over multihost-sharded
coordinates, and each host writes its OWN part file of the random-effect
model (the coefficient slab is never gathered); the coordinator writes the
fixed-effect model and metadata.

This is the driver-contract completion of the reference's cluster driver
(cli/game/training/Driver.scala:537 on Spark executors): same flag
grammar, SPMD instead of driver/executor. Scope (v2): the full coordinate
grid (combo sweep with best-combo selection by the primary validation
evaluator, Driver.scala:330-402 semantics; ``--grid-warm-start true``
additionally seeds each combo from the previous combo's coefficients, the
ModelTraining.scala:158-191 warm-start idea lifted to the combo axis —
off by default so the sweep matches the single-process driver and the
reference exactly), plain + bucketed + factored random-effect
coordinates, all three projector types (INDEX_MAP / RANDOM / IDENTITY,
projector/ProjectorType.scala:22-30), and prebuilt feature index maps
(``--offheap-indexmap-dir`` or a name-and-term path) — index vocabularies
must not require a full-data scan on every host. Datasets are ingested
ONCE (they are combo-invariant); each combo binds fresh optimization
problems to the shared slabs.

Run (one process per host):

    python -m photon_ml_tpu.cli.game_multihost_driver \\
        --multihost-coordinator HOST:PORT --multihost-num-processes N \\
        --multihost-process-id I  <game training flags...>
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from photon_ml_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.cli.game_params import (
    CoordinateOptConfig,
    parse_training_params,
)
from photon_ml_tpu.io import model_io
from photon_ml_tpu.io.avro_data import read_game_data
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optim.common import OptResult
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.parallel import multihost
from photon_ml_tpu.parallel.distributed import DistributedFixedEffectSolver
from photon_ml_tpu.parallel.mesh import MeshContext
from photon_ml_tpu.parallel.perhost_ingest import (
    HostRows,
    PerHostRandomEffectSolver,
    _unpack_u64,
    concat_host_rows,
    csr_to_padded,
    global_row_layout,
    host_file_share,
    local_shards,
    merge_group_ids,
    merge_row_vectors,
    per_host_re_dataset,
)
from photon_ml_tpu.parallel.shuffle import collective_sum
from photon_ml_tpu.types import real_dtype
from photon_ml_tpu.utils.logging import PhotonLogger

Array = jax.Array


class MultihostFixedEffectCoordinate:
    """Fixed-effect coordinate over per-host row blocks (drop-in for
    CoordinateDescent): rows stay where they were decoded; the solve is the
    psum-in-kernel data-parallel GLM; scoring scatters this host's margins
    into the global (N,) vector and one psum merges (owner-computes, like
    the random-effect side — the broadcast model IS the replicated w)."""

    cd_jit = False  # arrays span hosts: CoordinateDescent must not re-jit

    def __init__(self, x, labels, offsets, weights, row_ids, num_rows: int,
                 problem: GLMOptimizationProblem, ctx: MeshContext,
                 mh: "multihost.MultihostContext"):
        self.ctx = ctx
        self.num_rows = num_rows
        self.problem = problem
        self.norm = NormalizationContext.identity()
        self.solver = DistributedFixedEffectSolver(problem, ctx)
        self._score_fn = None
        self._fold_fn = jax.jit(
            lambda base, ids, resid: base
            + jnp.where(ids >= 0, resid[jnp.maximum(ids, 0)], 0.0)
        )
        local = max(ctx.num_devices // mh.num_processes, 1)
        n_loc = x.shape[0]
        from photon_ml_tpu.parallel.shuffle import collective_max

        r_max = int(collective_max(np.asarray([n_loc], np.int64), ctx,
                                   mh.num_processes)[0])
        r_max = -(-r_max // local) * local  # device multiple

        def pad(a, fill=0.0):
            if a.shape[0] == r_max:
                return a
            p = np.full((r_max - a.shape[0],) + a.shape[1:], fill, a.dtype)
            return np.concatenate([a, p])

        sharding = NamedSharding(ctx.mesh, P(ctx.axis))
        self.x = jax.make_array_from_process_local_data(
            sharding, pad(x.astype(np.float32))
        )
        self.labels = jax.make_array_from_process_local_data(
            sharding, pad(labels.astype(np.float32))
        )
        self.base_offsets = jax.make_array_from_process_local_data(
            sharding, pad(offsets.astype(np.float32))
        )
        self.weights = jax.make_array_from_process_local_data(
            sharding, pad(weights.astype(np.float32), 0.0)  # pad weight 0
        )
        self.row_ids = jax.make_array_from_process_local_data(
            sharding, pad(row_ids.astype(np.int32), -1)
        )

    @property
    def dim(self) -> int:
        return self.x.shape[1]

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.dim,), real_dtype())

    def update(self, residual_offsets: Array,
               init_coefficients: Array) -> Tuple[Array, OptResult]:
        # residuals arrive in GLOBAL row order; gather this shard's rows
        offs = self._fold_fn(self.base_offsets, self.row_ids, residual_offsets)
        batch = GLMBatch(DenseFeatures(self.x), self.labels, offs, self.weights)
        model, result = self.solver.run(batch, self.norm, init_coefficients)
        return model.coefficients.means, result

    def score(self, coefficients: Array) -> Array:
        if self._score_fn is None:
            axis = self.ctx.axis
            n = self.num_rows

            def score_shard(w, x, ids):
                s = x @ w  # (R_loc,)
                out = jnp.zeros((n,), s.dtype).at[jnp.maximum(ids, 0)].add(
                    jnp.where(ids >= 0, s, 0.0)
                )
                return jax.lax.psum(out, axis)

            self._score_fn = jax.jit(
                shard_map(
                    score_shard, mesh=self.ctx.mesh,
                    in_specs=(P(), P(self.ctx.axis), P(self.ctx.axis)),
                    out_specs=P(),
                )
            )
        return self._score_fn(coefficients, self.x, self.row_ids)

    def regularization_term(self, coefficients: Array) -> Array:
        return self.problem.regularization_term_value(coefficients)

    def rebind(self, problem: GLMOptimizationProblem
               ) -> "MultihostFixedEffectCoordinate":
        """Shallow copy sharing the device-resident data arrays (and the
        jitted score fn) but solving a DIFFERENT optimization problem —
        what the combo grid needs: the design matrix uploads once, only
        the per-combo problem binding changes."""
        import copy

        c = copy.copy(self)
        c.problem = problem
        c.solver = DistributedFixedEffectSolver(problem, self.ctx)
        return c


def _add_multihost_flags(argv: List[str]) -> Tuple[dict, List[str]]:
    """Strip the --multihost-* / --grid-warm-start flags; the rest is the
    normal game grammar."""
    mh_args = {"coordinator": None, "num_processes": None, "process_id": None,
               "grid_warm_start": False}
    rest: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("--multihost-coordinator", "--multihost-num-processes",
                 "--multihost-process-id", "--grid-warm-start"):
            if i + 1 >= len(argv):
                raise ValueError(f"{a} requires a value")
            value = argv[i + 1]
            if a == "--multihost-coordinator":
                mh_args["coordinator"] = value
            elif a == "--multihost-num-processes":
                mh_args["num_processes"] = int(value)
            elif a == "--grid-warm-start":
                mh_args["grid_warm_start"] = value.strip().lower() in (
                    "true", "1", "yes"
                )
            else:
                mh_args["process_id"] = int(value)
            i += 2
        else:
            rest.append(a); i += 1
    return mh_args, rest


def main(argv: Optional[List[str]] = None) -> dict:
    import sys

    from photon_ml_tpu.resilience import preemption

    mh_args, rest = _add_multihost_flags(
        list(argv) if argv is not None else sys.argv[1:]
    )
    p = parse_training_params(rest)

    # SPMD preemption: every host observes the same request (the pod
    # scheduler SIGTERMs all workers; PHOTON_PREEMPT_AT counts polls
    # identically on every host) and drains at the same boundary, so the
    # emergency-checkpoint collectives stay aligned. A relaunch re-ingests
    # (the slabs are process state) and resumes descent from the
    # collective-min checkpoint step.
    with preemption.signal_scope():
        try:
            return preemption.run_with_restarts(
                lambda attempt: _main_once(mh_args, p, restart=attempt > 0),
                p.max_restarts,
            )
        except preemption.Preempted as e:
            print(
                f"photon-ml-tpu multihost: preempted ({e}); exiting "
                f"{preemption.PREEMPT_EXIT_CODE}",
                file=sys.stderr,
            )
            raise SystemExit(preemption.PREEMPT_EXIT_CODE) from e


def _check_multihost_support(p) -> None:
    """Loud scope checks for this driver (unit-testable without launching
    processes): flags it does not implement are rejected, never silently
    ignored."""
    unsupported = [
        flag for flag, on in (
            ("--compute-variance", p.compute_variance),
            ("--fused-cycle", p.fused_cycle),
            ("--vmapped-grid", p.vmapped_grid != "false"),
        ) if on
    ]
    if unsupported:
        raise ValueError(
            f"multihost driver does not implement {unsupported} — "
            "rejecting rather than silently ignoring (the sharded slabs "
            "are non-addressable, so an outer jit over the whole cycle "
            "cannot close over them)"
        )
    from photon_ml_tpu.optim.scheduler import resolve_schedule

    if (resolve_schedule(p.solve_compaction) is not None
            and not p.streaming_random_effects):
        raise ValueError(
            "multihost driver composes --solve-compaction with "
            "--streaming-random-effects (each host compacts its owned "
            "blocks through the shared chunk kernels; updates are "
            "owner-computes, no collective) — the in-memory shard_map "
            "random-effect solver cannot pause at chunk boundaries; add "
            "--streaming-random-effects or drop --solve-compaction"
        )


def _attempt_relaunch_adoption(p, mh, ctx, logger) -> Dict[str, object]:
    """Relaunch-time re-plan (parallel/elastic.py:relaunch_replan) for
    every streaming random-effect coordinate: restore the prior cohort's
    plan-versioned sidecars, re-plan against THIS cohort's membership, and
    delta-transfer only the moved block/state files — a supervised relaunch
    onto a smaller or larger fleet resumes instead of re-ingesting.

    Returns ``{coordinate: RelaunchReplanResult}`` only when EVERY host
    succeeded for EVERY coordinate (one collective vote); any failure — or
    a same-cohort restart, which needs no re-plan — returns ``{}`` and the
    caller takes the ordinary full-ingest path on all hosts together."""
    import re as _re

    from photon_ml_tpu.parallel.elastic import ElasticError, relaunch_replan
    from photon_ml_tpu.parallel.perhost_streaming import load_plan_sidecars
    from photon_ml_tpu.parallel.shuffle import collective_max

    names = [
        n for n in p.updating_sequence
        if n in p.random_effect_data_configs and n not in p.factored_configs
    ]
    state_base = os.path.join(p.output_dir, "streaming-re-state")
    adopted: Dict[str, object] = {}
    code, why = 1, ""  # 0 = failed, 1 = adopted, 2 = same cohort
    try:
        prior_cohort = None
        first_root = (
            os.path.join(p.output_dir, "streaming-re", names[0])
            if names else None
        )
        if first_root and os.path.isdir(first_root):
            for d in sorted(os.listdir(first_root)):
                mdir = os.path.join(first_root, d)
                if d.startswith("process-") and os.path.isfile(
                        os.path.join(mdir, "manifest.json")):
                    meta, _, _ = load_plan_sidecars(mdir)
                    if meta is not None:
                        prior_cohort = sorted(
                            {int(q) for q in meta["binding"].values()}
                        )
                    break
        if prior_cohort is None:
            code, why = 0, "no committed plan-versioned prior layout"
        elif prior_cohort == list(range(mh.num_processes)):
            code = 2
        else:
            for name in names:
                coord_root = os.path.join(p.output_dir, "streaming-re", name)
                # prior spill roots by OLD physical pid, grouped per
                # coordinate-state instance (the -<seq> suffix), each paired
                # with MY destination root of the same instance
                pairs = []
                if os.path.isdir(state_base):
                    pat = _re.compile(_re.escape(name) + r"-host(\d+)-(\d+)$")
                    by_seq: Dict[int, Dict[int, str]] = {}
                    for d in os.listdir(state_base):
                        m = pat.match(d)
                        if m:
                            by_seq.setdefault(int(m.group(2)), {})[
                                int(m.group(1))
                            ] = os.path.join(state_base, d)
                    pairs = [
                        (srcs, os.path.join(
                            state_base, f"{name}-host{mh.process_id}-{seq}"
                        ))
                        for seq, srcs in sorted(by_seq.items())
                    ]
                adopted[name] = relaunch_replan(
                    coord_root, mh.process_id, mh.num_processes,
                    state_root_pairs=pairs,
                )
    except (ElasticError, OSError, ValueError, KeyError) as e:
        code, why = 0, f"{type(e).__name__}: {e}"
        adopted = {}
    # EVERY host votes, failed or not — the verdict must be unanimous or
    # everyone falls back to the full re-ingest TOGETHER (a mixed resume
    # would strand the routing collectives)
    v = np.asarray([code], np.int64)
    vmax = int(collective_max(v, ctx, mh.num_processes)[0])
    vmin = -int(collective_max(-v, ctx, mh.num_processes)[0])
    if vmax != vmin or vmin != 1:
        if vmax == vmin == 2:
            logger.info(
                "relaunch: same cohort as the prior run — plain resume "
                "from the plan-versioned checkpoints, no re-plan needed"
            )
        else:
            logger.warn(
                "relaunch re-plan unavailable on at least one host"
                + (f" (here: {why})" if code != 1 else "")
                + " — full re-ingest on the new cohort (recorded decision)"
            )
        return {}
    return adopted


def _fe_chunk_share(all_files, adopted, mh, logger):
    """This host's input-file share. An adopted re-plan carries the prior
    run's fixed-effect chunk ownership re-based onto the new cohort (chunk
    c IS input file c, versioned with the entity-shard plan); otherwise the
    split is the deterministic positional share."""
    if adopted:
        result = next(iter(adopted.values()))
        shard_plan = result.plan
        own = getattr(shard_plan, "fe_chunk_owners", None)
        if own is not None and len(own) == len(all_files):
            chunks = shard_plan.owned_fe_chunks(
                mh.process_id, membership=result.membership
            )
            logger.info(
                f"host {mh.process_id}: FE chunk ownership from re-based "
                f"plan v{shard_plan.version} "
                f"({len(chunks)}/{len(all_files)} chunks)"
            )
            return [(all_files[int(c)], int(c)) for c in chunks]
        logger.info(
            "adopted plan has no usable FE chunk ownership — positional "
            "file share (chunk merge is exact either way; ownership only "
            "balances the streaming fixed-effect load)"
        )
    return host_file_share(all_files, mh.num_processes, mh.process_id)


def _attach_fe_ownership(mh, all_files, g_file_counts, streaming_manifests,
                         logger) -> None:
    """Fresh ingest: fold the ACTUAL per-host file split into every
    streaming coordinate's committed plan sidecars, so a later relaunch
    re-plan re-bases fixed-effect chunks exactly like entity blocks."""
    from photon_ml_tpu.parallel.perhost_streaming import (
        attach_fe_chunks_to_sidecars,
    )

    owners = np.zeros(len(all_files), np.int32)
    for pid in range(mh.num_processes):
        for _, ordinal in host_file_share(all_files, mh.num_processes, pid):
            owners[ordinal] = pid
    for name, sm in streaming_manifests.items():
        try:
            attach_fe_chunks_to_sidecars(sm.dir, owners, g_file_counts)
        except (OSError, ValueError) as e:
            logger.warn(
                f"streaming RE {name}: could not record FE chunk ownership "
                f"in the plan sidecars ({e}) — a relaunch re-plan falls "
                "back to the positional file share"
            )


def _mh_ingest_inputs(p, plan) -> Dict[str, object]:
    """The pre-feature-map ingest identity (the single-process driver's
    ``_ingest_inputs`` shape) — what the delta planner compares."""
    bk = plan.bucketer
    return {
        "sections": {k: list(v) for k, v in sorted(
            (p.feature_shard_sections or {}).items())},
        "intercepts": {k: bool(v) for k, v in sorted(
            (p.feature_shard_intercepts or {}).items())},
        "id_types": sorted({c.random_effect_id
                            for c in p.random_effect_data_configs.values()}),
        "ladder": (
            f"{bk.base}:{bk.growth:g}" if bk is not None else None
        ),
        "offheap_indexmap_dir": p.offheap_indexmap_dir,
        "name_and_term": p.feature_name_and_term_set_path,
    }


def _mh_eval_identity(p) -> Dict[str, object]:
    """Validation-side identity (file stats + evaluator specs): a changed
    validation set must re-score even when training has nothing to do."""
    from photon_ml_tpu.cli.game_training_driver import (
        _input_files,
        resolve_date_range_dirs,
    )
    from photon_ml_tpu.io.tensor_cache import file_stat_token

    val_files = []
    if p.validate_input_dirs:
        val_files = _input_files(resolve_date_range_dirs(
            p.validate_input_dirs, p.validate_date_range,
            p.validate_date_range_days_ago,
        ))
    return {
        "validate_files": file_stat_token(val_files),
        "evaluators": [
            [etype.value, k, id_name]
            for etype, k, id_name in (p.evaluators or [])
        ],
    }


def _mh_ingest_digest(p, plan, shard_maps) -> str:
    """SHA-256 of the full ingest identity incl. per-shard feature-map
    digests (the feature-space identity warm reuse requires)."""
    import hashlib
    import json as _json

    from photon_ml_tpu.io.tensor_cache import index_map_digest

    cfg = dict(
        _mh_ingest_inputs(p, plan),
        index_maps={
            shard: index_map_digest(imap)
            for shard, imap in sorted(shard_maps.items())
        },
    )
    return hashlib.sha256(
        _json.dumps(cfg, sort_keys=True, default=str).encode()
    ).hexdigest()


def _blocking_unchanged(prior, name, manifest) -> bool:
    """Freezing a streaming coordinate additionally requires the prior
    run's entity blocking to BE this run's blocking — ``block_of`` is a
    pure function of the agreed entity counts, so the guard is
    membership-invariant (it holds across topology changes) and fails
    closed for a prior without plan sidecars (e.g. a single-process
    run's manifest)."""
    rec = prior.coordinates.get(name)
    if rec is None or not rec.streaming_manifest_dir:
        return False
    from photon_ml_tpu.parallel.perhost_streaming import _PLAN_BLOCK_OF

    try:
        prior_bo = np.load(
            os.path.join(rec.streaming_manifest_dir, _PLAN_BLOCK_OF)
        )
        cur_bo, _ = manifest.plan_arrays()
    except OSError:
        return False
    return bool(np.array_equal(np.asarray(prior_bo), np.asarray(cur_bo)))


def _prepare_multihost_warm(p, mh, ctx, logger, plan, shard_maps, all_files,
                            streaming_manifests, combos):
    """--warm-start-from for the multihost driver: every host plans its
    own delta against the prior ``retrain.json``, builds its warm seeds,
    and ONE collective agreement compares a digest of the outcome
    (classification + warm + frozen sets) across the cohort. Any
    disagreement — or any host's unusable prior, including an injected
    ``retrain.multihost_delta_agree`` fault — degrades EVERY host to a
    RECORDED cold run; a split-brain warm resume is impossible by
    construction.

    Returns ``(initial_params or None, frozen_blocks_by_name,
    frozen_coordinate_names)``."""
    if not p.warm_start_from:
        return None, {}, set()
    import hashlib
    import json as _json

    from photon_ml_tpu import retrain
    from photon_ml_tpu.parallel.shuffle import collective_max
    from photon_ml_tpu.resilience import faults
    from photon_ml_tpu.retrain.delta import NEW

    prior = delta = None
    warm: Dict[str, object] = {}
    frozen_blocks: Dict[str, frozenset] = {}
    frozen: set = set()
    digest, why = -1, ""
    try:
        # the chaos seam fires FIRST and the collectives run AFTER, no
        # matter what: a one-sided failure poisons THIS host's digest
        # (-1) but the host still votes below — it must never strand its
        # peers in a collective
        faults.inject(
            "retrain.multihost_delta_agree", process=int(mh.process_id)
        )
        prior = retrain.load_prior_manifest(p.warm_start_from)
        combo_configs = None
        if len(combos) == 1:
            combo_configs = {
                name: str(combos[0].get(name, CoordinateOptConfig()))
                for name in p.updating_sequence
            }
        delta = retrain.plan_delta(
            prior, all_files,
            task=p.task_type.value,
            updating_sequence=p.updating_sequence,
            ingest_inputs=_mh_ingest_inputs(p, plan),
            combo_configs=combo_configs,
            eval_identity=_mh_eval_identity(p),
        )
        freezable = (
            delta.frozen_coordinates() if len(combos) == 1 else set()
        )
        for name in p.updating_sequence:
            cdelta = delta.coordinates.get(name)
            if cdelta is None or cdelta.status == NEW:
                continue
            if name in p.fixed_effect_data_configs:
                spec = p.fixed_effect_data_configs[name]
                w0 = retrain.fixed_effect_init(
                    prior.model_dir, name,
                    shard_maps[spec.feature_shard_id],
                )
                if w0 is None:
                    logger.info(f"warm start {name}: prior fixed-effect "
                                "model missing — cold")
                    continue
                warm[name] = jnp.asarray(w0)
                if name in freezable:
                    frozen.add(name)
            elif name in streaming_manifests:
                dc = p.random_effect_data_configs[name]
                means = retrain.random_effect_entity_means(
                    prior.model_dir, name, shard_maps[dc.feature_shard_id]
                )
                if means is None:
                    logger.info(f"warm start {name}: prior random-effect "
                                "model missing or factored — cold")
                    continue
                warm[name] = retrain.seed_perhost_spilled_state(
                    streaming_manifests[name], means,
                    os.path.join(p.output_dir, "retrain-warm",
                                 f"{name}-host{mh.process_id}"),
                )
                if name in freezable and _blocking_unchanged(
                        prior, name, streaming_manifests[name]):
                    frozen.add(name)
                    # every LOCAL owned block skips its solve bitwise —
                    # per-host, the fleet-wide freeze the agreement
                    # guarantees is consistent
                    frozen_blocks[name] = frozenset(
                        range(len(streaming_manifests[name].blocks))
                    )
            else:
                # in-memory multihost RE solvers hold device-sharded slabs
                # with no host-side seeding path — a recorded cold solve,
                # the same rule as factored coordinates
                logger.info(f"warm start {name}: no multihost warm path "
                            "for this coordinate kind — cold")
        canon = _json.dumps(
            {
                "status": {n: c.status for n, c in
                           delta.coordinates.items()},
                "warm": sorted(warm),
                "frozen": sorted(frozen),
            },
            sort_keys=True,
        )
        # non-negative int63 (-1 stays a distinguishable poison value)
        digest = int.from_bytes(
            hashlib.sha256(canon.encode()).digest()[:8], "big"
        ) >> 1
    except Exception as e:  # noqa: BLE001 — ANY unusable prior (bad JSON, vanished model, unwritable seed dir, injected fault) must degrade to a cold run, never a wrong warm result or a stranded collective
        warm, frozen_blocks, frozen = {}, {}, set()
        why = f"{type(e).__name__}: {e}"
    d = np.asarray([digest], np.int64)
    dmax = int(collective_max(d, ctx, mh.num_processes)[0])
    dmin = -int(collective_max(-d, ctx, mh.num_processes)[0])
    if dmax != dmin or dmin < 0:
        logger.warn(
            "--warm-start-from: delta plan "
            + ("disagrees across hosts" if dmax != dmin
               else "failed on at least one host")
            + (f" (here: {why})" if why else "")
            + " — retraining cold everywhere (recorded decision)"
        )
        return None, {}, set()
    logger.info(
        f"delta retrain plan (agreed across {mh.num_processes} hosts): "
        f"files {delta.files.describe()}; "
        + " ".join(f"{n}={c.status}" for n, c in delta.coordinates.items())
    )
    for line in delta.describe_decisions():
        logger.info(f"delta retrain: {line}")
    if warm:
        logger.info(
            f"warm start: {sorted(warm)} seeded from {prior.model_dir}"
            + (f"; frozen {sorted(frozen)}" if frozen else "")
        )
    return (warm or None), frozen_blocks, frozen


def _write_mh_retrain_manifest(p, plan, best_dir, shard_maps, combos,
                               best_index, streaming_manifests,
                               coord_cache_keys, train_file_stats,
                               logger, coord_objs=None) -> None:
    """The coordinator's ``retrain.json`` (the single-process driver's
    record, multihost leg): next run's planner diffs against it, and the
    fleet rollout's provenance check traces its ``model_dir``."""
    from photon_ml_tpu.retrain import RetrainManifest
    from photon_ml_tpu.retrain.manifest import CoordinateRecord

    sel = combos[best_index]
    coords: Dict[str, CoordinateRecord] = {}
    for name in p.updating_sequence:
        if name in p.fixed_effect_data_configs:
            kind = "fixed"
        elif name in p.factored_configs:
            kind = "factored"
        elif name in streaming_manifests:
            kind = "streaming_random"
        elif p.bucketed_random_effects:
            kind = "bucketed"
        else:
            kind = "random"
        sm = streaming_manifests.get(name)
        # the coordinator's convergence ledger (its OWN blocks, keyed by
        # global block id) rides along; the other hosts' entries live in
        # their per-host manifest-dir sidecars, re-based by elastic commits
        ledger = None
        export = getattr((coord_objs or {}).get(name), "ledger_export", None)
        if callable(export):
            ledger = export() or None
        coords[name] = CoordinateRecord(
            kind=kind,
            opt_config=str(sel.get(name, CoordinateOptConfig())),
            cache_key=coord_cache_keys.get(name),
            streaming_manifest_dir=(
                os.path.abspath(sm.dir) if sm is not None else None
            ),
            shard_plan_version=int(
                getattr(sm, "plan_version", 1) if sm is not None else 1
            ),
            convergence_ledger=ledger,
        )
    manifest = RetrainManifest(
        output_dir=os.path.abspath(p.output_dir),
        model_dir=os.path.abspath(best_dir),
        task=p.task_type.value,
        file_stats=train_file_stats,
        ingest_inputs=_mh_ingest_inputs(p, plan),
        ingest_digest=_mh_ingest_digest(p, plan, shard_maps),
        updating_sequence=list(p.updating_sequence),
        coordinates=coords,
        data_cache_key=None,
        eval_identity=_mh_eval_identity(p),
    )
    path = manifest.save(p.output_dir)
    logger.info(f"retrain manifest written: {path}")


def _main_once(mh_args: dict, p, restart: bool = False) -> dict:
    mh = multihost.initialize(
        coordinator_address=mh_args["coordinator"],
        num_processes=mh_args["num_processes"],
        process_id=mh_args["process_id"],
    )
    ctx = mh.mesh_context()
    # the coordinator owns the output dir lifecycle (incl. purge — stale
    # per-host RE part files from a previous topology must never be merged
    # into a reloaded model); everyone else waits. A supervised relaunch
    # keeps the dir — the checkpoints under it are what it resumes from.
    if mh.coordinator_only_io():
        from photon_ml_tpu.utils.io_utils import prepare_output_dir

        if restart:
            os.makedirs(p.output_dir, exist_ok=True)
        else:
            prepare_output_dir(p.output_dir, p.delete_output_dir_if_exists)
    mh.barrier("output-dir")
    logger = PhotonLogger(
        os.path.join(p.output_dir, f"photon-ml-tpu-mh-{mh.process_id}.log")
    )
    from photon_ml_tpu.compile import compile_stats

    compile_stats.install_xla_listeners()
    if p.persistent_cache_dir:
        # per-process subdir: hosts compile the same programs but must not
        # race each other's cache files on a shared filesystem
        from photon_ml_tpu import compat

        cache_dir = os.path.join(
            p.persistent_cache_dir, f"process-{mh.process_id}"
        )
        if compat.enable_persistent_cache(cache_dir):
            logger.info(f"persistent XLA compilation cache: {cache_dir}")
        else:
            logger.warn(
                "--persistent-cache requested but this jax has no "
                "compilation-cache API; compiling uncached"
            )

    _check_multihost_support(p)
    # the execution plan (photon_ml_tpu.compile.plan) threads the shape
    # ladder + solve schedule + sparse selection through the per-host
    # streaming coordinates — the PR 4 compaction scheduler and the PR 7
    # sparse races now run ON the billion-coefficient path, per host, with
    # no collective in the update (owner-computes)
    from photon_ml_tpu.compile.plan import ExecutionPlan

    plan = ExecutionPlan.resolve(
        shape_canonicalization=p.shape_canonicalization,
        solve_compaction=p.solve_compaction,
        adaptive_schedule=p.adaptive_schedule,
        distributed=True,
        streaming=p.streaming_random_effects,
        bucketed=p.bucketed_random_effects,
        fused_cycle=p.fused_cycle,
        num_processes=mh.num_processes,
    )
    logger.info(plan.describe())
    for line in plan.describe_decisions():
        logger.info(f"execution plan: {line}")
    for cname, dc in p.random_effect_data_configs.items():
        proj = dc.projector.upper()
        if proj not in ("INDEX_MAP", "IDENTITY", "RANDOM"):
            raise ValueError(
                f"coordinate {cname!r} requests unknown projector "
                f"{dc.projector!r}"
            )
        if proj == "RANDOM" and dc.random_projection_dim is None:
            raise ValueError(
                f"coordinate {cname!r}: RANDOM projector needs "
                "random_projection_dim in its data configuration"
            )
    combos = p.config_grid()

    # ---- feature maps: prebuilt, shared, mmap'd ---------------------------
    shard_maps = {}
    needed_shards = {c.feature_shard_id for c in p.fixed_effect_data_configs.values()}
    needed_shards |= {c.feature_shard_id for c in p.random_effect_data_configs.values()}
    for shard in needed_shards:
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.io.offheap import load_shard_index_map

            shard_maps[shard] = load_shard_index_map(p.offheap_indexmap_dir, shard)
        elif p.feature_name_and_term_set_path:
            from photon_ml_tpu.io.name_and_term import NameAndTermFeatureSetContainer

            all_sections = sorted(
                {s for secs in p.feature_shard_sections.values() for s in secs}
            )
            nt = NameAndTermFeatureSetContainer.read_from_text(
                p.feature_name_and_term_set_path, all_sections
            )
            shard_maps[shard] = nt.index_map(
                p.feature_shard_sections.get(shard) or ["features"],
                p.feature_shard_intercepts.get(shard, True),
            )
        else:
            raise ValueError(
                "multihost ingest needs prebuilt feature maps: pass "
                "--offheap-indexmap-dir (FeatureIndexingJob output) or "
                "--feature-name-and-term-set-path"
            )

    # ---- per-host decode --------------------------------------------------
    from photon_ml_tpu.cli.game_training_driver import (
        _input_files,
        resolve_date_range_dirs,
    )

    # _input_files is deterministic (per-dir sorted, dirs in argument
    # order) and identical on every host — no global re-sort, matching the
    # single-process driver's row order
    all_files = _input_files(resolve_date_range_dirs(
        p.train_input_dirs, p.train_date_range, p.train_date_range_days_ago
    ))
    # pre-ingest stat tokens for the retrain manifest (a file overwritten
    # mid-run must be recorded with its pre-overwrite identity, same rule
    # as the single-process driver)
    from photon_ml_tpu.io.tensor_cache import file_stat_token

    train_file_stats = file_stat_token(all_files)
    # relaunch-time re-plan (the elasticity x supervised-relaunch seam): a
    # restart onto a DIFFERENT cohort adopts the prior cohort's durable
    # streaming layout — plan-versioned sidecars restored, replan() against
    # the new membership, only MOVED block/state files copied — instead of
    # re-ingesting everything. ANY host failing degrades EVERY host to a
    # recorded full re-ingest (collectively agreed: never a mixed resume).
    adopted: Dict[str, object] = {}
    if restart and p.streaming_random_effects:
        adopted = _attempt_relaunch_adoption(p, mh, ctx, logger)
    host_files = _fe_chunk_share(all_files, adopted, mh, logger)
    id_types = sorted({c.random_effect_id
                       for c in p.random_effect_data_configs.values()})
    gds = []
    for f, ordinal in host_files:
        gd = read_game_data(
            [f], shard_maps,
            {s: p.feature_shard_sections.get(s) or ["features"]
             for s in needed_shards},
            id_types,
            shard_intercepts={
                s: p.feature_shard_intercepts.get(s, True) for s in needed_shards
            },
        )
        gds.append((ordinal, gd))
    file_base, n_global = global_row_layout(
        len(all_files), gds, ctx, mh.num_processes
    )
    logger.info(
        f"host {mh.process_id}: {len(host_files)}/{len(all_files)} files, "
        f"{sum(gd.num_rows for _, gd in gds)}/{n_global} rows"
    )

    # replicated (N,) label/weight vectors for the training objective:
    # scatter own rows, one psum merges (these are O(N) scalars — the same
    # footprint as the score vectors the descent already carries)
    def assemble_global(vec_per_gd):
        merged = merge_row_vectors(
            gds, file_base, n_global, ctx, mh.num_processes, vec_per_gd
        )
        return jax.device_put(merged, NamedSharding(ctx.mesh, P()))

    labels_g = assemble_global(lambda gd: gd.response.astype(np.float32))
    weights_g = assemble_global(lambda gd: gd.weight.astype(np.float32))

    # ---- build DATASETS once (combo-invariant) ----------------------------
    fe_tensors: Dict[str, tuple] = {}
    fe_chunks: Dict[str, tuple] = {}  # streaming: (chunk_sizes, owned, dim)
    re_datasets: Dict[str, object] = {}
    streaming_manifests: Dict[str, object] = {}
    coord_cache_keys: Dict[str, Optional[str]] = {}
    # per-file row counts (identical on every host): the global chunk grid
    # of the streaming fixed effect — chunk c IS input file c, so chunk
    # ownership falls out of the per-host file share with no routing
    g_file_counts = np.diff(np.append(file_base, n_global)).astype(np.int64)
    for name in p.updating_sequence:
        if name in p.fixed_effect_data_configs:
            spec = p.fixed_effect_data_configs[name]
            feats_parts, y_parts, o_parts, w_parts, id_parts = [], [], [], [], []
            dim = len(shard_maps[spec.feature_shard_id])
            owned_loaders: Dict[int, object] = {}
            for ordinal, gd in gds:
                f = gd.shards[spec.feature_shard_id]
                if p.streaming_random_effects:
                    # one chunk per input file, densified INSIDE the loader:
                    # the streaming contract is one dense chunk resident at
                    # a time — only the (much smaller) CSR shards persist
                    def load(f=f, gd=gd, dim=dim):
                        dense = np.zeros((gd.num_rows, dim), np.float32)
                        rr = np.repeat(np.arange(gd.num_rows), np.diff(f.indptr))
                        dense[rr, f.indices] = f.values
                        return {
                            "x": dense,
                            "y": gd.response.astype(np.float32),
                            "offsets": gd.offset.astype(np.float32),
                            "weights": gd.weight.astype(np.float32),
                        }

                    owned_loaders[ordinal] = load
                    continue
                dense = np.zeros((gd.num_rows, dim), np.float32)
                nnz = np.diff(f.indptr)
                rows_rep = np.repeat(np.arange(gd.num_rows), nnz)
                dense[rows_rep, f.indices] = f.values
                feats_parts.append(dense)
                y_parts.append(gd.response)
                o_parts.append(gd.offset)
                w_parts.append(gd.weight)
                id_parts.append(file_base[ordinal] + np.arange(gd.num_rows))
            if p.streaming_random_effects:
                fe_chunks[name] = (
                    [int(c) for c in g_file_counts], owned_loaders, dim
                )
                continue
            # upload ONCE: the device-resident coordinate is combo-invariant;
            # each combo rebinds only its optimization problem (rebind())
            fe_tensors[name] = MultihostFixedEffectCoordinate(
                np.concatenate(feats_parts) if feats_parts else np.zeros((0, dim), np.float32),
                np.concatenate(y_parts) if y_parts else np.zeros(0),
                np.concatenate(o_parts) if o_parts else np.zeros(0),
                np.concatenate(w_parts) if w_parts else np.zeros(0),
                np.concatenate(id_parts) if id_parts else np.zeros(0, np.int64),
                n_global,
                GLMOptimizationProblem(
                    p.task_type, CoordinateOptConfig().optimizer,
                    CoordinateOptConfig().optimizer_config(),
                    CoordinateOptConfig().regularization_context(),
                ),
                ctx, mh,
            )
        else:
            dc = p.random_effect_data_configs[name]
            if name in p.factored_configs and dc.projector.upper() != "IDENTITY":
                raise ValueError(
                    f"factored coordinate {name!r} requires an IDENTITY "
                    f"projector in its data config (got {dc.projector!r}) — "
                    "the latent matrix projects the global shard space"
                )
            if name in adopted:
                # relaunch adoption (agreed above, so every host skips the
                # routing collectives together): the re-based manifest IS
                # this run's ingest output — resume without re-reading a row
                streaming_manifests[name] = adopted[name].manifest
                logger.info(
                    f"streaming RE {name}: adopted relaunch re-plan "
                    f"v{adopted[name].plan.version} — host {mh.process_id} "
                    f"owns {len(streaming_manifests[name].blocks)}/"
                    f"{streaming_manifests[name].num_blocks_total} blocks, "
                    "no re-ingest"
                )
                continue
            parts = []
            for ordinal, gd in gds:
                f = gd.shards[dc.feature_shard_id]
                fi, fv = csr_to_padded(f, gd.num_rows)
                vocab = gd.id_vocabs[dc.random_effect_id]
                parts.append(HostRows(
                    entity_raw_ids=[vocab[i] for i in gd.ids[dc.random_effect_id]],
                    row_index=file_base[ordinal] + np.arange(gd.num_rows, dtype=np.int64),
                    labels=gd.response.astype(np.float32),
                    weights=gd.weight.astype(np.float32),
                    offsets=gd.offset.astype(np.float32),
                    feat_idx=fi, feat_val=fv,
                    global_dim=f.dim,
                ))
            rows = concat_host_rows(
                parts, len(shard_maps[dc.feature_shard_id])
            )
            if p.streaming_random_effects and name not in p.factored_configs:
                # entity-sharded streaming: agree counts -> agreed global
                # blocking -> route rows to block owners (one all_to_all) ->
                # build ONLY the owned blocks under the per-host manifest
                # layout (each host a private subdir — or a shard-scoped
                # tensor-cache entry that can never cross-read a peer's)
                from photon_ml_tpu.parallel.perhost_streaming import (
                    build_perhost_streaming_manifest,
                )

                budget = (
                    int(p.re_memory_budget_mb * 1e6)
                    if p.re_memory_budget_mb is not None else None
                )
                cache = cache_key = None
                block_cache = block_key_base = None
                if p.tensor_cache_dir:
                    from photon_ml_tpu.io.tensor_cache import (
                        TensorCache,
                        content_key,
                        process_shard_scope,
                    )

                    cache = TensorCache(
                        p.tensor_cache_dir,
                        shard_scope=process_shard_scope(
                            mh.process_id, mh.num_processes
                        ),
                    )
                    bk = plan.bucketer
                    # key on the GLOBAL file list (shared input dir): this
                    # host's cached blocks hold rows routed from EVERY
                    # host's files, so a peer's input change must miss
                    # here. The resolved ladder spec is part of the key —
                    # a --shape-canonicalization change alters the PADDED
                    # block tensors a hit would serve
                    key_config = {
                        "kind": "perhost_streaming_re_blocks",
                        "coord": name, "config": str(dc),
                        "budget": budget, "n_files": len(all_files),
                        "ladder": (
                            f"{bk.base}:{bk.growth:g}"
                            if bk is not None else None
                        ),
                    }
                    cache_key = cache.key_for(all_files, key_config)
                    # per-BLOCK entries keyed on owned-block IDENTITY with
                    # NO process scope: a block's tensors are a pure
                    # function of the global data + plan, so a membership/
                    # topology change keeps every unmoved block's entry
                    # warm — the old scoped dir key rebuilt the whole host
                    # layout on ANY fleet change
                    block_cache = TensorCache(p.tensor_cache_dir)
                    block_key_base = content_key(
                        all_files, dict(key_config, entry="block")
                    )
                streaming_manifests[name] = build_perhost_streaming_manifest(
                    rows, dc,
                    os.path.join(
                        p.output_dir, "streaming-re", name,
                        f"process-{mh.process_id}",
                    ),
                    ctx, mh.num_processes, mh.process_id,
                    block_entities=None if budget is not None else 1024,
                    memory_budget_bytes=budget,
                    # "off", never None: the plan already consumed
                    # PHOTON_SHAPE_LADDER — None would let the builder
                    # re-resolve the env underneath an explicit off
                    bucketer=plan.bucketer or "off",
                    tensor_cache=cache, cache_key=cache_key,
                    block_cache=block_cache, block_key_base=block_key_base,
                )
                coord_cache_keys[name] = cache_key
                logger.info(
                    f"streaming RE {name}: host {mh.process_id} owns "
                    f"{len(streaming_manifests[name].blocks)}/"
                    f"{streaming_manifests[name].num_blocks_total} blocks"
                )
                continue
            bucketed = (
                p.bucketed_random_effects and name not in p.factored_configs
            )
            re_datasets[name] = per_host_re_dataset(
                rows, ctx, mh.num_processes, mh.process_id,
                active_upper_bound=dc.active_upper_bound,
                size_buckets=8 if bucketed else 1,
                projector=dc.projector.upper(),
                projection_dim=dc.random_projection_dim,
                projection_seed=dc.seed,
                projection_keep_intercept=dc.random_projection_intercept,
            )

    # fresh ingest: record the ACTUAL fixed-effect chunk ownership (the
    # host_file_share split above) into the versioned plan sidecars, so a
    # later relaunch re-plan re-bases FE chunks exactly like RE blocks
    if streaming_manifests and not adopted:
        _attach_fe_ownership(
            mh, all_files, g_file_counts, streaming_manifests, logger
        )

    # ---- --warm-start-from: fleet-wide delta retrain ----------------------
    # per-host delta plans agreed collectively; disagreement (or any host's
    # unusable prior) degrades EVERY host to a recorded cold run
    warm_init_mh, mh_frozen_blocks, frozen_names = _prepare_multihost_warm(
        p, mh, ctx, logger, plan, shard_maps, all_files,
        streaming_manifests, combos,
    )

    stream_state_seq = [0]

    def build_coords(combo: Dict[str, CoordinateOptConfig]) -> Dict[str, object]:
        from photon_ml_tpu.parallel.perhost_factored import (
            PerHostFactoredRandomEffectCoordinate,
        )
        from photon_ml_tpu.parallel.perhost_ingest import (
            BucketedShardedREData,
            PerHostBucketedRandomEffectSolver,
        )
        from photon_ml_tpu.algorithm.streaming_fixed_effect import (
            PerHostStreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.parallel.perhost_streaming import (
            PerHostStreamingRandomEffectCoordinate,
        )

        coords: Dict[str, object] = {}
        for name in p.updating_sequence:
            cfg = combo.get(name, CoordinateOptConfig())
            if name in fe_chunks:
                chunk_sizes, owned_loaders, dim = fe_chunks[name]
                coords[name] = PerHostStreamingFixedEffectCoordinate(
                    chunk_sizes, owned_loaders, dim,
                    GLMOptimizationProblem(
                        p.task_type, cfg.optimizer, cfg.optimizer_config(),
                        cfg.regularization_context(),
                    ),
                    ctx=ctx, num_processes=mh.num_processes,
                    plan=plan,
                )
            elif name in streaming_manifests:
                stream_state_seq[0] += 1
                coords[name] = PerHostStreamingRandomEffectCoordinate(
                    manifest=streaming_manifests[name],
                    task=p.task_type,
                    optimizer=cfg.optimizer,
                    optimizer_config=cfg.optimizer_config(),
                    regularization=cfg.regularization_context(),
                    # spilled state per host + combo instance, under OUR
                    # output dir (never inside a shared cache entry)
                    state_root=os.path.join(
                        p.output_dir, "streaming-re-state",
                        f"{name}-host{mh.process_id}-{stream_state_seq[0]}",
                    ),
                    # the plan threads the solve schedule, the per-block
                    # sparse-kernel race, and the prefetch depth — the
                    # PR 4 / PR 7 wins on the billion-coefficient path
                    plan=plan,
                    ctx=ctx, num_processes=mh.num_processes,
                    # delta retrain: LOCAL block indices whose solves are
                    # skipped bitwise (coefficients carried from the warm
                    # seed) — set only when the delta plan froze this
                    # coordinate on every host
                    frozen_blocks=mh_frozen_blocks.get(name),
                )
            elif name in p.fixed_effect_data_configs:
                coords[name] = fe_tensors[name].rebind(
                    GLMOptimizationProblem(
                        p.task_type, cfg.optimizer, cfg.optimizer_config(),
                        cfg.regularization_context(),
                    )
                )
            elif name in p.factored_configs:
                from photon_ml_tpu.algorithm.factored_random_effect import (
                    MFOptimizationConfig,
                )

                spec = p.factored_configs[name]
                coords[name] = PerHostFactoredRandomEffectCoordinate(
                    re_datasets[name], p.task_type,
                    mf_config=MFOptimizationConfig(
                        spec.mf_num_iterations, spec.latent_dim
                    ),
                    re_optimizer=spec.random_effect.optimizer,
                    re_optimizer_config=spec.random_effect.optimizer_config(),
                    re_regularization=spec.random_effect.regularization_context(),
                    latent_optimizer=spec.latent_factor.optimizer,
                    latent_optimizer_config=spec.latent_factor.optimizer_config(),
                    latent_regularization=spec.latent_factor.regularization_context(),
                    ctx=ctx,
                )
            else:
                sd = re_datasets[name]
                solver_cls = (
                    PerHostBucketedRandomEffectSolver
                    if isinstance(sd, BucketedShardedREData)
                    else PerHostRandomEffectSolver
                )
                coords[name] = solver_cls(
                    sd, p.task_type, cfg.optimizer, cfg.optimizer_config(),
                    cfg.regularization_context(), ctx,
                )
        return coords

    # ---- validation data decoded once (combo-invariant) -------------------
    val_data = None
    if p.validate_input_dirs:
        val_data = _decode_validation(p, mh, ctx, shard_maps, needed_shards,
                                      id_types)

    # ---- warm-started grid sweep ------------------------------------------
    from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.evaluation.evaluators import evaluator_for
    from photon_ml_tpu.cli.game_training_driver import _default_evaluators

    loss = losses_mod.for_task(p.task_type)
    loss_fn = lambda scores: jnp.sum(weights_g * loss.loss(scores, labels_g))
    specs = p.evaluators or _default_evaluators(p.task_type)
    primary = specs[0]
    primary_key = (
        primary[0].value if primary[1] is None
        else f"{primary[0].value}@{primary[1]}"
    )
    primary_ev = evaluator_for(primary[0], primary[1] or 10)

    best_index = 0
    best_value: Optional[float] = None
    best_result = None
    best_coords = None
    all_metrics: List[Dict[str, float]] = []
    prev_coefficients = None
    # per-host heartbeats (multihost health fencing): every host stamps the
    # shared dir at its safe boundaries; the coordinator logs the ages so a
    # wedged host — the one whose barrier everyone else is stuck in — is
    # diagnosable by name instead of by silence
    hb_dir = os.path.join(p.output_dir, "heartbeats")
    mh.write_heartbeat(hb_dir, step=None)
    if mh.coordinator_only_io():
        logger.info(mh.describe_heartbeats(hb_dir))
    for i, combo in enumerate(combos):
        coords = build_coords(combo)
        checkpointer = None
        if p.checkpoint_dir:
            from photon_ml_tpu.checkpoint import (
                CoordinateDescentCheckpointer,
                fingerprint,
            )
            from photon_ml_tpu.checkpoint_async import maybe_async

            # multihost-safe: sharded leaves are allgathered for the write,
            # the coordinator writes, barriers fence (checkpoint.py
            # multihost mode; restore agrees on the step via collective min)
            checkpointer = maybe_async(
                CoordinateDescentCheckpointer(
                    os.path.join(p.checkpoint_dir, f"combo-{i}"),
                    run_fingerprint=fingerprint({
                        # cohort-INVARIANT marker, deliberately not
                        # num_processes: a supervised relaunch onto a
                        # smaller/larger cohort must restore this
                        # plan-versioned checkpoint and resume — per-host
                        # streaming state re-bases through the plan
                        # sidecars (see MIGRATION.md)
                        "multihost": True,
                        "coordinates": p.updating_sequence,
                        "num_rows": n_global,
                        "combo": i,
                        "warm_start": mh_args["grid_warm_start"],
                        # a config change must NOT silently resume the old run
                        # (same rule as the single-process driver's fingerprint)
                        "configs": {k: str(v) for k, v in combo.items()},
                    }),
                    multihost=mh,
                ),
                p.checkpoint_async,
            )
        cd = CoordinateDescent(coords, loss_fn)
        try:
            result = cd.run(
                num_iterations=p.num_iterations, num_rows=n_global,
                checkpointer=checkpointer,
                # combo 0 (or the whole run, without --grid-warm-start)
                # seeds from the delta-retrain warm start; later combos
                # under --grid-warm-start keep the previous combo's
                # coefficients (the stronger start)
                initial_params=(
                    prev_coefficients
                    if mh_args["grid_warm_start"] and prev_coefficients
                    is not None else warm_init_mh
                ),
                # non-empty only for a single-combo run (a sweep compares
                # configurations, so nothing may be skipped)
                frozen=frozen_names,
            )
        finally:
            # async fence before this combo retires (preemption already
            # fenced inside the emergency save)
            if checkpointer is not None and hasattr(checkpointer, "close"):
                checkpointer.close()
        prev_coefficients = result.coefficients
        mh.write_heartbeat(hb_dir, step=(i + 1) * p.num_iterations)
        if mh.coordinator_only_io():
            logger.info(mh.describe_heartbeats(hb_dir))
        logger.info(
            f"combo {i}: objective history "
            + " ".join(f"{v:.6g}" for v in result.objective_history)
        )
        metrics: Dict[str, float] = {}
        if val_data is not None:
            metrics = _validate(
                p, mh, ctx, coords=coords, result=result, logger=logger,
                val_data=val_data,
            )
            logger.info(
                f"combo {i} validation: "
                + " ".join(f"{k}={v:.6g}" for k, v in metrics.items())
            )
        all_metrics.append(metrics)
        if metrics and primary_key in metrics:
            value = metrics[primary_key]
            if best_value is None or primary_ev.better_than(value, best_value):
                best_value, best_index = value, i
                best_result, best_coords = result, coords
        elif best_result is None:
            best_result, best_coords = result, coords
    if len(combos) > 1:
        logger.info(
            f"best combo: {best_index}"
            + (f" ({primary_key}={best_value:.6g})" if best_value is not None else "")
        )
    result, coords = best_result, best_coords
    metrics = all_metrics[best_index]

    # ---- save (reference layout; RE parts written per host) ---------------
    out = os.path.join(p.output_dir, "best")
    mh.barrier("pre-save")
    if mh.coordinator_only_io():
        os.makedirs(out, exist_ok=True)
    mh.barrier("outdir")
    for name in p.updating_sequence:
        coord = coords[name]
        w = result.coefficients[name]
        if name in p.fixed_effect_data_configs:
            # replicated (D,) model either way — in-memory psum coordinate
            # or the per-host streaming chunk coordinate
            if mh.coordinator_only_io():
                spec = p.fixed_effect_data_configs[name]
                model_io.save_fixed_effect(
                    out, name, p.task_type,
                    np.asarray(jax.device_get(w)),
                    shard_maps[spec.feature_shard_id],
                    feature_shard_id=spec.feature_shard_id,
                )
        elif name in p.factored_configs:
            dc = p.random_effect_data_configs[name]
            _save_factored_parts(
                out, name, p, dc, coord, w,
                shard_maps[dc.feature_shard_id], mh,
            )
        elif name in streaming_manifests:
            dc = p.random_effect_data_configs[name]
            _save_streaming_re_parts(
                out, name, p, dc, coord, w, shard_maps[dc.feature_shard_id], mh
            )
        else:
            dc = p.random_effect_data_configs[name]
            _save_random_effect_parts(
                out, name, p, dc, coord, w, shard_maps[dc.feature_shard_id], mh
            )
        mh.barrier(f"saved-{name}")
    logger.info(f"model saved to {out}")
    # the coordinator leaves this run's retrain.json so the NEXT run (and
    # the fleet rollout's provenance check) can diff against it — the
    # multihost leg of the retrain -> re-shard -> export -> swap loop
    if mh.coordinator_only_io():
        try:
            _write_mh_retrain_manifest(
                p, plan, out, shard_maps, combos, best_index,
                streaming_manifests, coord_cache_keys, train_file_stats,
                logger, coord_objs=coords,
            )
        except (OSError, TypeError, ValueError) as e:
            # a failed manifest write degrades tomorrow's run to cold — it
            # must not fail TODAY's completed training run
            logger.warn(f"retrain manifest write failed ({e}); the next "
                        "run retrains cold")
    mh.barrier("retrain-manifest")
    from photon_ml_tpu.compile import compile_stats

    logger.info(compile_stats.summary())
    if plan.schedule is not None or plan.adaptive is not None:
        from photon_ml_tpu.optim.scheduler import solve_stats

        logger.info(solve_stats.summary())
    if plan.adaptive is not None:
        # every adaptive skip/degrade is a recorded decision — per host,
        # like the plan's own composition decisions above
        for name, coord in coords.items():
            for dec in getattr(coord, "skip_decisions", ()) or ():
                logger.info(f"[{name}] {dec.describe()}")
    logger.close()
    return {
        "objective_history": result.objective_history,
        "validation_metrics": metrics,
        "all_metrics": all_metrics,
        "best_index": best_index,
        "num_rows": n_global,
        "process_id": mh.process_id,
        "output": out,
    }


def _save_random_effect_parts(out, name, p, dc, coord, w, imap, mh):
    """Each host writes ONE part file with ITS devices' entities — the
    coefficient slab never crosses hosts (ModelProcessingUtils.scala:205-219
    writes per-partition part files the same way). Raw entity ids come from
    the host's own decode (key -> raw id map built during ingest)."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.model_io import (
        COEFFICIENTS,
        ID_INFO,
        RANDOM_EFFECT,
        _model_record,
    )

    from photon_ml_tpu.parallel.perhost_ingest import BucketedShardedREData

    sd = coord.data
    base = os.path.join(out, RANDOM_EFFECT, name)
    if mh.coordinator_only_io():
        os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
        with open(os.path.join(base, ID_INFO), "w") as f:
            f.write(f"{dc.random_effect_id}\n{dc.feature_shard_id}\n")
    mh.barrier(f"re-dir-{name}")
    # this host's slab rows (addressable shards of the sharded arrays);
    # raw ids rode the exchange (ShardedREData.raw_ids_by_key), so the
    # OWNER can name every entity it holds without any model gather.
    # Bucketed datasets contribute one group per size bucket (the
    # coefficients arrive as the solver's per-bucket tuple).
    if isinstance(sd, BucketedShardedREData):
        groups = [
            (wb, b.entity_keys, b.entity_mask, b.local_to_global)
            for b, wb in zip(sd.buckets, w)
        ]
    else:
        groups = [(w, sd.entity_keys, sd.entity_mask, sd.local_to_global)]
    pm = getattr(sd, "projection_matrix", None)
    records = []
    for warr, karr, marr, larr in groups:
        local = {}
        for arr, field in ((warr, "w"), (karr, "keys"),
                           (marr, "mask"), (larr, "l2g")):
            # local_shards orders by slab position so the four arrays' lanes
            # align (addressable_shards iteration order is unspecified)
            local[field] = np.concatenate(local_shards(arr))
        mask = local["mask"].astype(bool)
        for lane in np.nonzero(mask)[0]:
            key = int(_unpack_u64(local["keys"][lane, :1], local["keys"][lane, 1:2])[0])
            raw = sd.raw_ids_by_key[key]
            if pm is not None:
                # RANDOM projector: coefficients live in the shared
                # projected space — back-project through the matrix
                # (RandomEffectModelInProjectedSpace.toRandomEffectModel)
                dense = np.asarray(pm).T @ np.asarray(
                    local["w"][lane], np.float32
                )
            else:
                dense = np.zeros(sd.global_dim, np.float32)
                valid = local["l2g"][lane] >= 0
                dense[local["l2g"][lane][valid]] = local["w"][lane][valid]
            records.append(_model_record(raw, p.task_type, dense, None, imap))
    avro_io.write_container(
        os.path.join(base, COEFFICIENTS, f"part-{mh.process_id:05d}.avro"),
        records,
        schemas.BAYESIAN_LINEAR_MODEL,
    )


def _save_streaming_re_parts(out, name, p, dc, coord, state, imap, mh):
    """Per-host streaming model save: each host writes ONE part file with
    the entities whose blocks it owns (the spilled coefficient state never
    crosses hosts; back-projection streams block metadata, not data slabs).
    Owner-computes end to end — the write-side mirror of the solve."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.model_io import (
        COEFFICIENTS,
        ID_INFO,
        RANDOM_EFFECT,
        _model_record,
    )

    base = os.path.join(out, RANDOM_EFFECT, name)
    if mh.coordinator_only_io():
        os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
        with open(os.path.join(base, ID_INFO), "w") as f:
            f.write(f"{dc.random_effect_id}\n{dc.feature_shard_id}\n")
    mh.barrier(f"re-dir-{name}")
    means = coord.entity_means_by_raw_id(state)
    records = [
        _model_record(raw, p.task_type, np.asarray(vec, np.float32), None, imap)
        for raw, vec in sorted(means.items())
    ]
    avro_io.write_container(
        os.path.join(base, COEFFICIENTS, f"part-{mh.process_id:05d}.avro"),
        records,
        schemas.BAYESIAN_LINEAR_MODEL,
    )


def _save_factored_parts(out, name, p, dc, coord, state, imap, mh):
    """Factored random effect under multihost: each host writes ITS
    entities' flattened-W coefficients part AND latent-factor part; the
    coordinator writes the shared latent matrix + id-info (the factored
    STRUCTURE persists, model_io.save_factored_random_effect layout —
    AvroUtils.scala:244-266 semantics, per-host part files)."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.model_io import (
        ID_INFO,
        LATENT_FACTORS,
        LATENT_MATRIX,
        RANDOM_EFFECT,
        save_latent_factors,
    )

    base = os.path.join(out, RANDOM_EFFECT, name)
    if mh.coordinator_only_io():
        os.makedirs(os.path.join(base, LATENT_FACTORS), exist_ok=True)
        matrix = np.asarray(jax.device_get(state.matrix), np.float32)
        save_latent_factors(
            os.path.join(base, LATENT_MATRIX),
            {str(k): matrix[k] for k in range(matrix.shape[0])},
        )
    mh.barrier(f"fre-dir-{name}")
    # flattened W = V M part (scoring compat) via the shared RE writer
    w_flat = coord.random_effect_coefficients(state)
    _save_random_effect_parts(out, name, p, dc, coord, w_flat, imap, mh)
    # the factored marker goes LAST: the shared RE writer writes the plain
    # 2-line id-info, and is_factored_random_effect keys off the 3rd line
    if mh.coordinator_only_io():
        import json as _json

        from photon_ml_tpu.io.model_io import (
            LATENT_MATRIX_FEATURES,
            _split_key,
        )

        with open(os.path.join(base, ID_INFO), "w") as f:
            f.write(f"{dc.random_effect_id}\n{dc.feature_shard_id}\nfactored\n")
        # column -> feature-key binding (same artifact as the single-process
        # save): lets a consumer with a different index map realign columns
        pairs = [
            list(_split_key(imap.get_feature_name(j) or str(j)))
            for j in range(matrix.shape[1])
        ]
        with open(os.path.join(base, LATENT_MATRIX_FEATURES), "w") as f:
            _json.dump({"columns": pairs}, f)
    # this host's latent factors part
    factors = coord.latent_factors_by_raw_id(state)
    recs = [
        {"effectId": str(eid), "latentFactor": [float(v) for v in vec]}
        for eid, vec in sorted(factors.items())
    ]
    avro_io.write_container(
        os.path.join(base, LATENT_FACTORS, f"part-{mh.process_id:05d}.avro"),
        recs,
        schemas.LATENT_FACTOR,
    )




def _decode_validation(p, mh, ctx, shard_maps, needed_shards, id_types):
    """Per-host decode of the validation slice + the merged replicated
    label/weight/offset vectors — combo-invariant, decoded ONCE per run."""
    from photon_ml_tpu.cli.game_training_driver import (
        _default_evaluators,
        _input_files,
        resolve_date_range_dirs,
    )

    specs = p.evaluators or _default_evaluators(p.task_type)
    grouped_ids = sorted({idn for _, _, idn in specs if idn is not None})
    id_types = sorted(set(id_types) | set(grouped_ids))
    val_files = _input_files(resolve_date_range_dirs(
        p.validate_input_dirs, p.validate_date_range,
        p.validate_date_range_days_ago,
    ))
    host_files = host_file_share(val_files, mh.num_processes, mh.process_id)
    vgds = []
    for f, ordinal in host_files:
        gd = read_game_data(
            [f], shard_maps,
            {s: p.feature_shard_sections.get(s) or ["features"]
             for s in needed_shards},
            id_types,
            shard_intercepts={
                s: p.feature_shard_intercepts.get(s, True) for s in needed_shards
            },
        )
        vgds.append((ordinal, gd))
    file_base, nv = global_row_layout(
        len(val_files), vgds, ctx, mh.num_processes
    )

    def merge(vec_per_gd):
        return merge_row_vectors(
            vgds, file_base, nv, ctx, mh.num_processes, vec_per_gd
        )

    return {
        "specs": specs,
        "grouped_ids": grouped_ids,
        "vgds": vgds,
        "file_base": file_base,
        "nv": nv,
        "labels": merge(lambda gd: gd.response.astype(np.float32)),
        "weights": merge(lambda gd: gd.weight.astype(np.float32)),
        "offsets": merge(lambda gd: gd.offset.astype(np.float32)),
    }


def _validate(p, mh, ctx, coords, result, logger, val_data):
    """Validation metrics under multihost: each host decodes only its slice
    of the validation files; fixed-effect margins are computed locally (the
    model is replicated) and random-effect rows are ROUTED to their
    entity's owner with the training shuffle's agreed owner map
    (score_routed_rows) — cold entities/features contribute 0. Factored
    coordinates route against the flattened W = V M slab; bucketed
    coordinates against the per-bucket tuple. Scores merge with one
    collective sum; every host computes the same metric values and the
    coordinator logs them."""
    from photon_ml_tpu.evaluation.evaluators import evaluator_for
    from photon_ml_tpu.parallel.perhost_factored import (
        PerHostFactoredRandomEffectCoordinate,
    )
    from photon_ml_tpu.parallel.perhost_ingest import score_routed_rows

    specs = val_data["specs"]
    grouped_ids = val_data["grouped_ids"]
    vgds = val_data["vgds"]
    file_base = val_data["file_base"]
    nv = val_data["nv"]
    labels_v = val_data["labels"]
    weights_v = val_data["weights"]
    offsets_v = val_data["offsets"]

    scores = offsets_v.astype(np.float64).copy()
    for name in p.updating_sequence:
        coord = coords[name]
        w = result.coefficients[name]
        if name in p.fixed_effect_data_configs:
            # replicated (D,) model: in-memory psum coordinate and the
            # per-host streaming chunk coordinate score identically here
            spec = p.fixed_effect_data_configs[name]
            w_host = np.asarray(jax.device_get(w))
            local = np.zeros(nv, np.float32)
            for ordinal, gd in vgds:
                f = gd.shards[spec.feature_shard_id]
                fi, fv = csr_to_padded(f, gd.num_rows)
                sel = np.where(fi >= 0, w_host[np.maximum(fi, 0)], 0.0)
                local[file_base[ordinal] + np.arange(gd.num_rows)] = np.sum(
                    sel * fv, axis=1
                )
            scores += collective_sum(local, ctx, mh.num_processes)
        else:
            dc = p.random_effect_data_configs[name]
            parts = []
            for ordinal, gd in vgds:
                f = gd.shards[dc.feature_shard_id]
                fi, fv = csr_to_padded(f, gd.num_rows)
                vocab = gd.id_vocabs[dc.random_effect_id]
                parts.append(HostRows(
                    entity_raw_ids=[vocab[i] for i in gd.ids[dc.random_effect_id]],
                    row_index=file_base[ordinal] + np.arange(gd.num_rows, dtype=np.int64),
                    labels=gd.response.astype(np.float32),
                    weights=gd.weight.astype(np.float32),
                    offsets=gd.offset.astype(np.float32),
                    feat_idx=fi, feat_val=fv,
                    global_dim=f.dim,
                ))
            from photon_ml_tpu.parallel.perhost_streaming import (
                PerHostStreamingRandomEffectCoordinate,
                score_routed_rows_streaming,
            )

            if isinstance(coord, PerHostStreamingRandomEffectCoordinate):
                # streaming models: route rows to the block-owner host, who
                # dots them against its back-projected entity means
                vrows = concat_host_rows(parts, coord.manifest.global_dim)
                scores += score_routed_rows_streaming(
                    coord.manifest, coord.entity_means_by_raw_id(w), vrows,
                    nv, ctx, mh.num_processes, mh.process_id,
                )
                continue
            vrows = concat_host_rows(parts, coord.data.global_dim)
            if isinstance(coord, PerHostFactoredRandomEffectCoordinate):
                # route against the flattened per-entity coefficients
                # W = V M (IDENTITY local space, so the l2g lookup is exact)
                w = coord.random_effect_coefficients(w)
            scores += score_routed_rows(
                coord.data, w, vrows, nv, ctx, mh.num_processes, mh.process_id
            )

    metrics: Dict[str, float] = {}
    s = jnp.asarray(scores.astype(np.float32))
    # one hash-merge per distinct id column, shared across evaluators
    group_cols = {
        idn: jnp.asarray(merge_group_ids(vgds, file_base, nv, idn, ctx, mh.num_processes))
        for idn in grouped_ids
    }
    for etype, k, id_name in specs:
        ev = evaluator_for(etype, k or 10)
        kwargs = {"labels": jnp.asarray(labels_v), "weights": jnp.asarray(weights_v)}
        if id_name is not None:
            kwargs["group_ids"] = group_cols[id_name]
        key = etype.value if k is None else f"{etype.value}@{k}"
        metrics[key] = float(ev.evaluate(s, **kwargs))
    return metrics


if __name__ == "__main__":
    main()
