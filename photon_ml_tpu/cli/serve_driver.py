"""Online scoring server driver.

Brings a persistent GAME scoring process up warm and serves JSON-lines
requests on stdin/stdout (photon_ml_tpu/serve). A designed upgrade over
the reference, which only ships a batch scoring Driver — the startup
sequence is the whole point:

  1. resolve the model store (export a saved GAME model into the mmap'd
     serving layout if the store does not exist yet),
  2. enable the persistent XLA cache (compat.enable_persistent_cache),
  3. warm every (rows, nnz) ladder rung the request path can produce,
  4. log ``compile_stats.summary()`` and — on a warm cache — "serving
     fully warm: zero new XLA compiles" (``--assert-warm`` makes that a
     hard startup gate),
  5. serve; a ``{"cmd": "swap", "store_dir": ...}`` line rolls the model
     live through the by-reference swap path.

Usage::

    python -m photon_ml_tpu.cli.serve_driver \
        --model-store-dir /models/store \
        --game-model-input-dir /models/best \
        --persistent-cache /cache/xla --assert-warm true < requests.jsonl
"""

from __future__ import annotations

import sys
from typing import List, Optional

from photon_ml_tpu.cli.game_params import GameServeParams, parse_serve_params
from photon_ml_tpu.utils.logging import PhotonLogger


class GameServeDriver:
    """Builds/opens the store, warms the server, runs the request loop."""

    def __init__(self, params: GameServeParams, logger: Optional[PhotonLogger] = None):
        params.validate()
        self.params = params
        self._own_logger = logger is None
        self.logger = logger or PhotonLogger(params.log_path)
        self.server = None
        self.swapper = None
        self.warm_report: Optional[dict] = None
        self.handled = 0

    # ------------------------------------------------------------------
    def resolve_store(self):
        from photon_ml_tpu.compile import resolve_bucketer
        from photon_ml_tpu.serve import ModelStore, build_model_store, is_model_store

        p = self.params
        if not is_model_store(p.model_store_dir):
            if not p.game_model_input_dir:
                raise ValueError(
                    f"{p.model_store_dir} is not a serve store and no "
                    "--game-model-input-dir was given to export from"
                )
            self.logger.info(
                f"exporting {p.game_model_input_dir} -> serve store "
                f"{p.model_store_dir}"
            )
            build_model_store(
                p.game_model_input_dir,
                p.model_store_dir,
                num_partitions=p.num_store_partitions,
                bucketer=resolve_bucketer(p.shape_canonicalization),
                store_dtype=p.store_dtype,
            )
        store = ModelStore(p.model_store_dir)
        self.logger.info(store.describe())
        fp = store.footprint()
        self.logger.info(
            f"store footprint: dtype {fp['store_dtype']}, "
            f"{fp['slab_bytes_disk']} slab bytes on disk, "
            f"{fp['mapped_bytes']} bytes mapped"
        )
        return store

    def start(self):
        """Everything up to (not including) the blocking request loop."""
        from photon_ml_tpu import compat
        from photon_ml_tpu.compile import compile_stats
        from photon_ml_tpu.serve import ModelSwapper, ScoringServer

        p = self.params
        cache_ok = False
        if p.persistent_cache_dir:
            cache_ok = compat.enable_persistent_cache(p.persistent_cache_dir)
            if cache_ok:
                self.logger.info(
                    f"persistent XLA compilation cache: {p.persistent_cache_dir}"
                )
            else:
                self.logger.warn(
                    "--persistent-cache requested but this jax has no "
                    "compilation-cache API; compiling uncached"
                )
        listeners_ok = compile_stats.install_xla_listeners()
        if p.assert_warm and not (cache_ok and listeners_ok):
            # the gate must not be vacuously satisfiable: with no cache the
            # start cannot be warm, and with no monitoring API the miss
            # counter would stay 0 no matter how much XLA compiled
            raise RuntimeError(
                "--assert-warm needs a working persistent cache "
                f"(enabled={cache_ok}) and the jax.monitoring compile "
                f"listeners (installed={listeners_ok}) to be verifiable "
                "on this jax version"
            )
        store = self.resolve_store()
        if p.build_store_only:
            store.close()
            return None
        self.server = ScoringServer(
            store,
            shard_sections=p.feature_shard_sections,
            bucketer=p.shape_canonicalization,
            max_batch_rows=p.max_batch_rows,
            max_wait_ms=p.max_wait_ms,
        )
        self.swapper = ModelSwapper(self.server)
        if p.warmup:
            self.warm_report = self.server.warmup(warm_nnz=p.warm_nnz)
            self.logger.info(
                f"warmup: {self.warm_report['warm_batches']} batches over "
                f"row rungs {self.warm_report['row_rungs']} x nnz rungs "
                f"{self.warm_report['nnz_rungs']}; "
                f"{self.warm_report['new_traces']} traces, "
                f"{self.warm_report['new_xla_misses']} new XLA compiles"
            )
        self.logger.info(compile_stats.summary())
        if cache_ok and listeners_ok and self.server.fully_warm():
            self.logger.info("serving fully warm: zero new XLA compiles")
        elif p.assert_warm:
            raise RuntimeError(
                f"--assert-warm: startup compiled "
                f"{compile_stats.xla_cache_misses} new XLA executables "
                "(persistent cache cold or ladder changed)"
            )
        return self.server

    def run(self, in_stream=None, out_stream=None) -> None:
        from photon_ml_tpu.serve import serve_json_lines

        try:
            if self.start() is None:
                return  # --build-store-only
            self.logger.info(
                f"serving (max_batch_rows={self.params.max_batch_rows}, "
                f"max_wait_ms={self.params.max_wait_ms})"
            )
            self.handled = serve_json_lines(
                self.server,
                in_stream if in_stream is not None else sys.stdin,
                out_stream if out_stream is not None else sys.stdout,
                swapper=self.swapper,
            )
        finally:
            if self.server is not None:
                self.logger.info(self.server.stats.summary())
                if self.server.new_request_compiles():
                    self.logger.warn(
                        f"{self.server.new_request_compiles()} request-path "
                        "compiles AFTER warmup — a request shape escaped the "
                        "warmed ladder (raise --warm-nnz or --max-batch-rows)"
                    )
                self.server.close()
            if self._own_logger:
                self.logger.close()


def main(argv: Optional[List[str]] = None) -> GameServeDriver:
    driver = GameServeDriver(parse_serve_params(argv))
    driver.run()
    return driver


if __name__ == "__main__":
    main()
