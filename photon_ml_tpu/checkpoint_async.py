"""Asynchronous checkpoint commits: snapshot now, write in the background.

A synchronous checkpoint save stalls the descent for the whole
``np.savez`` + fsync + rename, which on a shared filesystem is easily the
longest host-side pause in the loop — and an EMERGENCY checkpoint written
under a preemption deadline wants the device drained, not blocked on disk.
This module splits the save the same way the data path split ingest
(io/pipeline.py): :class:`AsyncCheckpointer` wraps a
:class:`~photon_ml_tpu.checkpoint.CoordinateDescentCheckpointer`, takes the
host snapshot synchronously (``_prepare`` — the arrays are pulled host-side
there, and under multihost it is a collective), and commits through the
SAME retry + atomic-rename path (``_commit``) on a single background
worker thread.

Contracts (mirroring the :class:`~photon_ml_tpu.io.pipeline.Prefetcher`):

  * **in-order failure propagation** — a commit that exhausts its retries
    surfaces on the NEXT ``save()`` / :meth:`wait` / :meth:`close`, and
    commits queued AFTER the failing one are dropped (never silently
    committed past a hole).
  * **wait() fences** — :meth:`wait` blocks until every enqueued commit is
    durable (and re-raises a pending failure) BEFORE model save, retire,
    process exit, or a supervised relaunch. Under multihost it also
    barriers, replacing the per-save barrier the sync path uses.
  * **no tmp-dir interleaving** — commits are serialized on one worker, so
    concurrent save pressure never interleaves ``.ckpt-*`` temp dirs; the
    stale-tmp sweep invariants of the sync path hold unchanged.

Queue depth is bounded: ``save()`` blocks once ``max_pending`` snapshots
are in flight, so a slow disk applies backpressure instead of accumulating
unbounded host copies of the model state.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

from photon_ml_tpu.checkpoint import (
    STEP_PREFIX,
    CheckpointState,
    CoordinateDescentCheckpointer,
)

__all__ = ["AsyncCheckpointer"]

logger = logging.getLogger(__name__)


class AsyncCheckpointer:
    """Background-commit wrapper around a CoordinateDescentCheckpointer.

    Drop-in for every call site that takes a checkpointer (save / restore /
    latest_step / save_every); only the durability point moves: ``save()``
    returns once the host snapshot exists, :meth:`wait` is the fence that
    makes everything durable.
    """

    def __init__(self, inner: CoordinateDescentCheckpointer, max_pending: int = 2):
        self.inner = inner
        self._queue: "queue.Queue[Optional[tuple]]" = queue.Queue(
            maxsize=max(max_pending, 1)
        )
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- delegation ----------------------------------------------------
    @property
    def directory(self) -> str:
        return self.inner.directory

    @property
    def save_every(self) -> int:
        return self.inner.save_every

    @property
    def multihost(self):
        return self.inner.multihost

    def latest_step(self):
        return self.inner.latest_step()

    def restore(self, *args, **kwargs):
        return self.inner.restore(*args, **kwargs)

    # -- worker --------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-async-commit", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._queue.get()
            try:
                if job is None:
                    return
                step, arrays, meta = job
                with self._error_lock:
                    pending = self._error
                if pending is not None:
                    # in-order: a commit after a failed one is DROPPED, not
                    # committed past the hole — the caller sees the first
                    # failure on its next save()/wait()
                    logger.warning(
                        "dropping async checkpoint step %d (pending commit "
                        "failure: %s)", step, pending
                    )
                    continue
                try:
                    self.inner._commit(step, arrays, meta)
                except BaseException as e:  # noqa: BLE001 — crossing the
                    # thread boundary, re-raised in the caller (the
                    # Prefetcher contract); never swallowed
                    with self._error_lock:
                        if self._error is None:
                            self._error = e
            finally:
                self._queue.task_done()

    def _raise_pending(self) -> None:
        with self._error_lock:
            e, self._error = self._error, None
        if e is not None:
            raise e

    # -- the checkpointer protocol --------------------------------------
    def save(self, state: CheckpointState) -> str:
        """Snapshot synchronously (collective under multihost), commit in
        the background. Raises a PENDING commit failure first — in order —
        so a broken checkpoint directory is never papered over by later
        successful-looking saves."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        # the host snapshot (and, multihost, the sharded-leaf allgather)
        # must happen NOW, while the state arrays are still live
        arrays, meta = self.inner._prepare(state)
        final_dir = f"{self.inner.directory}/{STEP_PREFIX}{state.step}"
        if (
            self.inner.multihost is not None
            and not self.inner.multihost.coordinator_only_io()
        ):
            # non-coordinators are done: no per-save barrier in async mode —
            # wait() is the fence that keeps hosts from racing past an
            # uncommitted checkpoint
            return final_dir
        self._ensure_worker()
        self._queue.put((state.step, arrays, meta))
        return final_dir

    def wait(self) -> None:
        """Fence: block until every enqueued commit is durable; re-raise a
        commit failure. Under multihost, barrier afterwards so no host
        proceeds (retire / model save / relaunch) past an uncommitted
        step."""
        self._queue.join()
        try:
            self._raise_pending()
        finally:
            if self.inner.multihost is not None:
                self.inner.multihost.barrier("ckpt-async-fence")

    def close(self) -> None:
        """Drain, stop the worker, surface any pending failure."""
        if self._closed:
            return
        self._queue.join()
        if self._thread is not None and self._thread.is_alive():
            self._queue.put(None)
            self._thread.join()
        self._closed = True
        self._raise_pending()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        return None


def maybe_async(
    checkpointer: Optional[CoordinateDescentCheckpointer],
    enabled: bool,
    max_pending: int = 2,
):
    """Driver convenience: wrap when ``--checkpoint-async`` is on."""
    if checkpointer is None or not enabled:
        return checkpointer
    return AsyncCheckpointer(checkpointer, max_pending=max_pending)
