"""Off-heap partitioned feature index store (PalDB analogue).

Reference spec: util/PalDBIndexMap.scala:43-230 + FeatureIndexingJob.scala
:148-174 — feature names are hash-partitioned; each partition is an off-heap
key-value store shared across processes; a feature's global index is its
partition's global offset + its local index, and reverse lookup binary-
searches the offsets (PalDBIndexMap.scala:105-130).

This build keeps those exact semantics over a native memory-mapped store
(native/pmix_store.cpp, C API via ctypes): open is one mmap (the page cache
is the share mechanism — no JVM, no JSON parse), name->index is a hash-table
probe in mapped memory, index->name is an offset slice. Partitioning and
within-partition sort match IndexMap.build exactly, so the off-heap store
and the in-memory map assign identical indices for the same key set.

The native library compiles lazily with g++ into a user cache dir; if no
compiler is available a pure-Python reader/writer of the same file format
takes over (slower, same bytes).
"""

from __future__ import annotations

import ctypes
import json
import logging
import mmap as mmap_mod
import os
import struct
import subprocess
import tempfile
import zlib
from typing import Dict, Iterable, List, Optional

import numpy as np

from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap, partition_keys

logger = logging.getLogger(__name__)

META_FILE = "meta.json"
PARTITION_PREFIX = "partition-"
PARTITION_SUFFIX = ".pmix"

_HEADER = struct.Struct("<IIQQQ")  # magic, version, num_keys, capacity, blob size
_MAGIC = 0x58494D50
_VERSION = 1
_SLOT = struct.Struct("<IQ")  # local index + 1, fnv1a hash

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_U64 = 0xFFFFFFFFFFFFFFFF


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _U64
    return h


def _next_pow2(v: int) -> int:
    c = 1
    while c < v:
        c <<= 1
    return c


# ---------------------------------------------------------------------------
# native library (lazy compile + ctypes)
# ---------------------------------------------------------------------------

_NATIVE_SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "pmix_store.cpp",
)
_native_lib = None
_native_failed = False


def _load_native():
    """Compile (once, cached by source hash) and load the C++ store."""
    global _native_lib, _native_failed
    if _native_lib is not None or _native_failed:
        return _native_lib
    try:
        with open(_NATIVE_SOURCE, "rb") as f:
            src = f.read()
        tag = f"{zlib.crc32(src):08x}"
        cache_dir = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "photon_ml_tpu",
        )
        os.makedirs(cache_dir, exist_ok=True)
        lib_path = os.path.join(cache_dir, f"libpmix-{tag}.so")
        if not os.path.exists(lib_path):
            with tempfile.TemporaryDirectory() as tmp:
                tmp_lib = os.path.join(tmp, "libpmix.so")
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp_lib, _NATIVE_SOURCE],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_lib, lib_path)
        lib = ctypes.CDLL(lib_path)
        lib.pmix_open.restype = ctypes.c_void_p
        lib.pmix_open.argtypes = [ctypes.c_char_p]
        lib.pmix_close.argtypes = [ctypes.c_void_p]
        lib.pmix_size.restype = ctypes.c_long
        lib.pmix_size.argtypes = [ctypes.c_void_p]
        lib.pmix_get_index.restype = ctypes.c_long
        lib.pmix_get_index.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
        lib.pmix_get_name.restype = ctypes.c_long
        lib.pmix_get_name.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.pmix_build.restype = ctypes.c_int
        lib.pmix_build.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
        ]
        _native_lib = lib
    except (OSError, subprocess.CalledProcessError, AttributeError) as e:
        # expected degradations: no source file / no g++ / CDLL load failure /
        # a library missing an entry point — fall back to the pure-Python
        # reader, loudly (anything else, e.g. a ctypes misuse bug, raises)
        logger.warning("native pmix store unavailable (%s); using pure-Python reader", e)
        _native_failed = True
        _native_lib = None
    return _native_lib


def native_available() -> bool:
    return _load_native() is not None


# ---------------------------------------------------------------------------
# single-partition access (native or pure-Python, same file format)
# ---------------------------------------------------------------------------


def _build_partition_file(path: str, keys: List[str], force_python: bool = False) -> None:
    """Write one partition; key i gets local index i.

    The native (g++/ctypes) and pure-Python writers emit IDENTICAL bytes
    (pinned by tests/test_offheap_index.py::TestWriterBytesIdentity), so a
    store built wherever a compiler happens to exist opens everywhere.
    """
    encoded = [k.encode("utf-8") for k in keys]
    blob = b"".join(encoded)
    offsets = np.zeros(len(keys) + 1, np.uint64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    lib = None if force_python else _load_native()
    if lib is not None:
        err = lib.pmix_build(
            path.encode(),
            blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(keys),
        )
        if err != 0:
            raise IOError(f"pmix_build failed with code {err} for {path}")
        return
    # pure-Python writer (identical bytes)
    n = len(keys)
    cap = _next_pow2(n * 2 if n else 1)
    table = bytearray(cap * _SLOT.size)
    mask = cap - 1
    for i, e in enumerate(encoded):
        h = _fnv1a(e)
        slot = h & mask
        while _SLOT.unpack_from(table, slot * _SLOT.size)[0] != 0:
            slot = (slot + 1) & mask
        _SLOT.pack_into(table, slot * _SLOT.size, i + 1, h)
    with open(path, "wb") as f:
        f.write(_HEADER.pack(_MAGIC, _VERSION, n, cap, len(blob)))
        f.write(bytes(table))
        f.write(offsets.tobytes())
        f.write(blob)


class _NativePartition:
    """ctypes wrapper over one mapped partition."""

    def __init__(self, path: str, lib):
        self._lib = lib
        self._handle = lib.pmix_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open pmix store {path}")
        self.num_keys = int(lib.pmix_size(self._handle))
        self._buf = ctypes.create_string_buffer(4096)

    def get_index(self, key: bytes) -> int:
        return int(self._lib.pmix_get_index(self._handle, key, len(key)))

    def get_name(self, idx: int) -> Optional[str]:
        n = int(self._lib.pmix_get_name(self._handle, idx, self._buf, len(self._buf)))
        if n < 0:
            return None
        if n > len(self._buf):
            self._buf = ctypes.create_string_buffer(n)
            n = int(self._lib.pmix_get_name(self._handle, idx, self._buf, len(self._buf)))
        return self._buf.raw[:n].decode("utf-8")

    def close(self) -> None:
        if self._handle:
            self._lib.pmix_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except OSError as e:
            # interpreter-shutdown close can fail; never raise from __del__
            logger.warning("pmix partition close failed during GC: %s", e)


class _PythonPartition:
    """mmap + struct reader of the same format (no native lib needed)."""

    def __init__(self, path: str):
        self._f = open(path, "rb")
        self._mm = mmap_mod.mmap(self._f.fileno(), 0, access=mmap_mod.ACCESS_READ)
        magic, version, self.num_keys, self._cap, blob_size = _HEADER.unpack_from(
            self._mm, 0
        )
        if magic != _MAGIC or version != _VERSION:
            raise IOError(f"bad pmix store {path}")
        self._table_off = _HEADER.size
        self._offsets_off = self._table_off + self._cap * _SLOT.size
        self._blob_off = self._offsets_off + (self.num_keys + 1) * 8
        self._offsets = np.frombuffer(
            self._mm, np.uint64, self.num_keys + 1, self._offsets_off
        )

    def get_index(self, key: bytes) -> int:
        if self.num_keys == 0:
            return -1
        h = _fnv1a(key)
        mask = self._cap - 1
        for probe in range(self._cap):
            slot = (h + probe) & mask
            idx1, slot_hash = _SLOT.unpack_from(
                self._mm, self._table_off + slot * _SLOT.size
            )
            if idx1 == 0:
                return -1
            if slot_hash == h:
                i = idx1 - 1
                s, e = int(self._offsets[i]), int(self._offsets[i + 1])
                if self._mm[self._blob_off + s : self._blob_off + e] == key:
                    return i
        return -1

    def get_name(self, idx: int) -> Optional[str]:
        if not (0 <= idx < self.num_keys):
            return None
        s, e = int(self._offsets[idx]), int(self._offsets[idx + 1])
        return self._mm[self._blob_off + s : self._blob_off + e].decode("utf-8")

    def close(self) -> None:
        self._offsets = None
        self._mm.close()
        self._f.close()


def _open_partition(path: str, force_python: bool = False):
    lib = None if force_python else _load_native()
    if lib is not None:
        return _NativePartition(path, lib)
    return _PythonPartition(path)


# ---------------------------------------------------------------------------
# partitioned store: build + load
# ---------------------------------------------------------------------------


def build_offheap_store(
    output_dir: str,
    feature_keys: Iterable[str],
    add_intercept: bool = True,
    num_partitions: int = 1,
    force_python: bool = False,
) -> None:
    """Hash-partition keys (IndexMap.build parity: crc32 % P, sorted within
    partition), write one pmix file per partition + meta.json."""
    os.makedirs(output_dir, exist_ok=True)
    parts = partition_keys(feature_keys, num_partitions)
    offsets = []
    total = 0
    for i, p in enumerate(parts):
        offsets.append(total)
        total += len(p)
        _build_partition_file(
            os.path.join(output_dir, f"{PARTITION_PREFIX}{i}{PARTITION_SUFFIX}"),
            p,
            force_python=force_python,
        )
    meta = {
        "format": "pmix",
        "version": _VERSION,
        "num_partitions": num_partitions,
        "partition_offsets": offsets,
        "num_features": total + (1 if add_intercept else 0),
        "intercept": add_intercept,
    }
    with open(os.path.join(output_dir, META_FILE), "w") as f:
        json.dump(meta, f)


def is_offheap_store(path: str) -> bool:
    try:
        with open(os.path.join(path, META_FILE)) as f:
            return json.load(f).get("format") == "pmix"
    except (OSError, ValueError):
        return False


class OffHeapIndexMap:
    """Drop-in IndexMap replacement backed by mapped partition files.

    Global index scheme (PalDBIndexMap.scala:105-130 parity): partition p's
    keys occupy [offset_p, offset_p + size_p); the intercept, when present,
    is the final index. Reverse lookup binary-searches the offsets.
    """

    def __init__(self, store_dir: str, force_python: bool = False):
        from photon_ml_tpu import resilience
        from photon_ml_tpu.resilience import faults

        policy = resilience.current_config().io_policy

        def read_meta() -> dict:
            faults.inject("io.index_load", path=store_dir)
            with open(os.path.join(store_dir, META_FILE)) as f:
                return json.load(f)

        self._meta = resilience.call_with_retry(
            read_meta, policy, describe=f"load {store_dir} meta"
        )
        if self._meta.get("format") != "pmix":
            raise IOError(f"{store_dir} is not a pmix off-heap store")
        self._partitions = [
            resilience.call_with_retry(
                lambda p=os.path.join(
                    store_dir, f"{PARTITION_PREFIX}{i}{PARTITION_SUFFIX}"
                ): _open_partition(p, force_python),
                policy,
                describe=f"open {store_dir} partition {i}",
            )
            for i in range(self._meta["num_partitions"])
        ]
        self._offsets = list(self._meta["partition_offsets"])
        self._num_features = int(self._meta["num_features"])
        self._intercept = bool(self._meta["intercept"])
        self._name_to_index_cache: Optional[Dict[str, int]] = None

    # -- IndexMap protocol --------------------------------------------------
    def __len__(self) -> int:
        return self._num_features

    @property
    def intercept_index(self) -> int:
        return self._num_features - 1 if self._intercept else -1

    def get_index(self, key: str) -> int:
        if key == INTERCEPT_KEY:
            return self.intercept_index
        p = zlib.crc32(key.encode()) % len(self._partitions)
        local = self._partitions[p].get_index(key.encode("utf-8"))
        return self._offsets[p] + local if local >= 0 else -1

    def get_feature_name(self, idx: int) -> Optional[str]:
        if idx < 0 or idx >= self._num_features:
            return None
        if self._intercept and idx == self._num_features - 1:
            return INTERCEPT_KEY
        # binary search over partition offsets (:105-130)
        lo, hi = 0, len(self._offsets) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self._offsets[mid] <= idx:
                lo = mid
            else:
                hi = mid - 1
        return self._partitions[lo].get_name(idx - self._offsets[lo])

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    @property
    def name_to_index(self) -> Dict[str, int]:
        """Materialized dict view (built on demand — used only by host-side
        config parsing like box constraints, never by the ingest hot path)."""
        if self._name_to_index_cache is None:
            self._name_to_index_cache = {
                self.get_feature_name(i): i for i in range(self._num_features)
            }
        return self._name_to_index_cache

    def close(self) -> None:
        for p in self._partitions:
            p.close()
        self._partitions = []


def load_index_map(path: str):
    """Auto-detect loader: pmix store dir, else JSON IndexMap file/dir."""
    if os.path.isdir(path) and is_offheap_store(path):
        return OffHeapIndexMap(path)
    if os.path.isdir(path):
        return IndexMap.load(os.path.join(path, "feature-index.json"))
    return IndexMap.load(path)


# ---------------------------------------------------------------------------
# coefficient-slab row lookup (the feature-index machinery generalized)
# ---------------------------------------------------------------------------


class SlabRowIndex(OffHeapIndexMap):
    """Entity raw id -> coefficient-slab row, over the same mapped ``.pmix``
    partition files as the feature index (the PalDB machinery generalized
    from feature indices to coefficient slabs): the serving
    :class:`~photon_ml_tpu.serve.model_store.ModelStore` keeps each random
    effect's per-entity coefficients as one ``(E, D)`` mmap'd slab whose row
    order IS this store's global index order, so ``get_row(raw_id)`` is a
    hash probe in mapped memory — no JSON parse, no dict materialization,
    shared page cache across server processes."""

    def __init__(self, store_dir: str, force_python: bool = False):
        super().__init__(store_dir, force_python=force_python)
        if self._intercept:
            raise IOError(
                f"{store_dir} was built with an intercept slot — not a slab "
                "row index (build with build_slab_index)"
            )

    @property
    def num_rows(self) -> int:
        return self._num_features

    def get_row(self, key: str) -> int:
        """Slab row of ``key``; -1 when the entity has no model."""
        return self.get_index(key)

    def row_key(self, row: int) -> Optional[str]:
        return self.get_feature_name(row)


def build_slab_index(
    output_dir: str,
    keys: Iterable[str],
    num_partitions: int = 1,
    force_python: bool = False,
) -> None:
    """Write an entity->slab-row lookup store: ``build_offheap_store``
    without the intercept slot (slab rows are exactly the key set). Row
    assignment matches ``IndexMap.build`` partitioning, so the builder can
    lay slab rows down in this store's enumeration order."""
    build_offheap_store(
        output_dir,
        keys,
        add_intercept=False,
        num_partitions=num_partitions,
        force_python=force_python,
    )


def open_slab_index(store_dir: str, force_python: bool = False) -> SlabRowIndex:
    return SlabRowIndex(store_dir, force_python=force_python)


def load_shard_index_map(base_dir: str, shard: str):
    """Per-feature-shard loader used by the GAME drivers: a pmix store at
    ``<base>/<shard>/`` wins over ``<base>/feature-index-<shard>.json``."""
    candidate = os.path.join(base_dir, shard)
    if os.path.isdir(candidate) and is_offheap_store(candidate):
        return OffHeapIndexMap(candidate)
    return IndexMap.load(os.path.join(base_dir, f"feature-index-{shard}.json"))
