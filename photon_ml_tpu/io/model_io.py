"""GAME / GLM model persistence in the reference's on-disk layout.

Reference spec: avro/model/ModelProcessingUtils.scala:40-148 —

  outputDir/fixed-effect/<coordinateName>/id-info            (text: ids)
  outputDir/fixed-effect/<coordinateName>/coefficients/part-00000.avro
  outputDir/random-effect/<coordinateName>/id-info
  outputDir/random-effect/<coordinateName>/coefficients/part-*.avro

Coefficients are BayesianLinearModelAvro records whose means/variances are
NameTermValueAvro (feature name/term -> value); per-entity models use
modelId = raw entity id. The feature name/term strings come from an
IndexMap (feature key = "name\\x01term").
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.index_map import DELIMITER, IndexMap
from photon_ml_tpu.types import TaskType

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
ID_INFO = "id-info"
COEFFICIENTS = "coefficients"


def _split_key(key: str) -> Tuple[str, str]:
    if DELIMITER in key:
        name, term = key.split(DELIMITER, 1)
        return name, term
    return key, ""


def _coeff_records(means: np.ndarray, variances: Optional[np.ndarray],
                   index_map: IndexMap) -> Tuple[List[dict], Optional[List[dict]]]:
    # sparse encoding keeps every index where EITHER the mean or the variance
    # is nonzero (an exactly-zero mean — common under OWL-QN — must not drop
    # its posterior variance)
    nz = np.nonzero(means)[0]
    if variances is not None:
        nz = np.union1d(nz, np.nonzero(variances)[0])
    means_rec = []
    for j in nz:
        name, term = _split_key(index_map.get_feature_name(int(j)) or str(int(j)))
        means_rec.append({"name": name, "term": term, "value": float(means[j])})
    var_rec = None
    if variances is not None:
        var_rec = []
        for j in nz:
            name, term = _split_key(index_map.get_feature_name(int(j)) or str(int(j)))
            var_rec.append({"name": name, "term": term, "value": float(variances[j])})
    return means_rec, var_rec


def _model_record(model_id: str, task: TaskType, means: np.ndarray,
                  variances: Optional[np.ndarray], index_map: IndexMap) -> dict:
    means_rec, var_rec = _coeff_records(means, variances, index_map)
    return {
        "modelId": model_id,
        "modelClass": schemas.MODEL_CLASS_BY_TASK[task.value],
        "means": means_rec,
        "variances": var_rec,
        "lossFunction": None,
    }


def ntv_index(ntv: dict, index_map: IndexMap) -> int:
    """BayesianLinearModelAvro name/term -> feature index, with the bare-name
    fallback for termless keys like (INTERCEPT); -1 when absent."""
    idx = index_map.get_index(f"{ntv['name']}{DELIMITER}{ntv['term']}")
    if idx < 0 and ntv["term"] == "":
        idx = index_map.get_index(ntv["name"])
    return idx


def _record_to_dense(rec: dict, index_map: IndexMap) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    d = len(index_map)

    def lookup(ntv) -> int:
        return ntv_index(ntv, index_map)

    means = np.zeros(d, np.float32)
    for ntv in rec["means"]:
        idx = lookup(ntv)
        if idx >= 0:
            means[idx] = ntv["value"]
    variances = None
    if rec.get("variances"):
        variances = np.zeros(d, np.float32)
        for ntv in rec["variances"]:
            idx = lookup(ntv)
            if idx >= 0:
                variances[idx] = ntv["value"]
    return means, variances


# ---------------------------------------------------------------------------
# fixed effect
# ---------------------------------------------------------------------------


def save_fixed_effect(output_dir: str, name: str, task: TaskType, means: np.ndarray,
                      index_map: IndexMap, variances: Optional[np.ndarray] = None,
                      feature_shard_id: str = "global") -> None:
    base = os.path.join(output_dir, FIXED_EFFECT, name)
    os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
    with open(os.path.join(base, ID_INFO), "w") as f:
        f.write(feature_shard_id + "\n")
    avro_io.write_container(
        os.path.join(base, COEFFICIENTS, "part-00000.avro"),
        [_model_record(name, task, means, variances, index_map)],
        schemas.BAYESIAN_LINEAR_MODEL,
    )


def load_fixed_effect(input_dir: str, name: str, index_map: IndexMap
                      ) -> Tuple[np.ndarray, Optional[np.ndarray], TaskType, str]:
    base = os.path.join(input_dir, FIXED_EFFECT, name)
    with open(os.path.join(base, ID_INFO)) as f:
        shard = f.read().strip()
    recs = list(avro_io.read_directory(os.path.join(base, COEFFICIENTS)))
    rec = recs[0]
    means, variances = _record_to_dense(rec, index_map)
    task = TaskType(schemas.TASK_BY_MODEL_CLASS.get(
        rec.get("modelClass"), "LOGISTIC_REGRESSION"))
    return means, variances, task, shard


# ---------------------------------------------------------------------------
# random effect (per-entity models in original feature space)
# ---------------------------------------------------------------------------


def save_random_effect(
    output_dir: str,
    name: str,
    task: TaskType,
    entity_means: Dict[str, np.ndarray],  # raw entity id -> dense global coeffs
    index_map: IndexMap,
    random_effect_id: str = "",
    feature_shard_id: str = "",
    num_files: int = 1,
    entity_variances: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """(num_files = numberOfOutputFilesForRandomEffectModel parity;
    entity_variances fills the BayesianLinearModelAvro variances list when
    the driver ran with --compute-variance.)"""
    base = os.path.join(output_dir, RANDOM_EFFECT, name)
    os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
    with open(os.path.join(base, ID_INFO), "w") as f:
        f.write(f"{random_effect_id}\n{feature_shard_id}\n")
    items = sorted(entity_means.items())
    shards: List[List[dict]] = [[] for _ in range(max(num_files, 1))]
    for i, (eid, means) in enumerate(items):
        var = entity_variances.get(eid) if entity_variances else None
        shards[i % len(shards)].append(_model_record(eid, task, means, var, index_map))
    for i, recs in enumerate(shards):
        avro_io.write_container(
            os.path.join(base, COEFFICIENTS, f"part-{i:05d}.avro"),
            recs,
            schemas.BAYESIAN_LINEAR_MODEL,
        )


def load_random_effect(
    input_dir: str, name: str, index_map: IndexMap,
    variances_out: Optional[Dict[str, np.ndarray]] = None,
) -> Tuple[Dict[str, np.ndarray], TaskType, str, str]:
    """Pass ``variances_out`` (a dict) to also collect per-entity variance
    rows for records that carry them."""
    base = os.path.join(input_dir, RANDOM_EFFECT, name)
    with open(os.path.join(base, ID_INFO)) as f:
        lines = f.read().splitlines()
    re_id = lines[0] if lines else ""
    shard = lines[1] if len(lines) > 1 else ""
    out: Dict[str, np.ndarray] = {}
    task = TaskType.LOGISTIC_REGRESSION
    for rec in avro_io.read_directory(os.path.join(base, COEFFICIENTS)):
        means, variances = _record_to_dense(rec, index_map)
        out[rec["modelId"]] = means
        if variances_out is not None and variances is not None:
            variances_out[rec["modelId"]] = variances
        if rec.get("modelClass") in schemas.TASK_BY_MODEL_CLASS:
            task = TaskType(schemas.TASK_BY_MODEL_CLASS[rec["modelClass"]])
    return out, task, re_id, shard


# ---------------------------------------------------------------------------
# latent factors (LatentFactorAvro wire format — AvroUtils.scala:244-266;
# on-disk layout ModelProcessingUtils.scala:251-311: one subdir per effect
# type holding part-*.avro of {effectId, latentFactor: array<double>})
# ---------------------------------------------------------------------------

LATENT_FACTORS = "latent-factors"
LATENT_MATRIX = "latent-matrix"


def save_latent_factors(path: str, factors: Dict[str, np.ndarray],
                        num_files: int = 1) -> None:
    """Write {effectId -> latent vector} as LatentFactorAvro part files."""
    os.makedirs(path, exist_ok=True)
    items = sorted(factors.items())
    shards: List[List[dict]] = [[] for _ in range(max(num_files, 1))]
    for i, (eid, vec) in enumerate(items):
        shards[i % len(shards)].append(
            {"effectId": str(eid), "latentFactor": [float(v) for v in np.asarray(vec)]}
        )
    for i, recs in enumerate(shards):
        avro_io.write_container(
            os.path.join(path, f"part-{i:05d}.avro"), recs, schemas.LATENT_FACTOR
        )


def load_latent_factors(path: str) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for rec in avro_io.read_directory(path):
        out[rec["effectId"]] = np.asarray(rec["latentFactor"], np.float64)
    return out


def save_matrix_factorization(output_dir: str, row_effect_type: str,
                              col_effect_type: str,
                              row_factors: Dict[str, np.ndarray],
                              col_factors: Dict[str, np.ndarray],
                              num_files: int = 1) -> None:
    """MatrixFactorizationModel layout parity
    (ModelProcessingUtils.scala:251-272): outputDir/<rowEffectType>/ and
    outputDir/<colEffectType>/ of LatentFactorAvro part files."""
    save_latent_factors(os.path.join(output_dir, row_effect_type), row_factors, num_files)
    save_latent_factors(os.path.join(output_dir, col_effect_type), col_factors, num_files)


def load_matrix_factorization(input_dir: str, row_effect_type: str,
                              col_effect_type: str
                              ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """ModelProcessingUtils.scala:291-311 parity (missing dirs raise)."""
    row_path = os.path.join(input_dir, row_effect_type)
    col_path = os.path.join(input_dir, col_effect_type)
    for p in (row_path, col_path):
        if not os.path.isdir(p):
            raise FileNotFoundError(f"latent factor directory not found: {p}")
    return load_latent_factors(row_path), load_latent_factors(col_path)


LATENT_MATRIX_FEATURES = "latent-matrix-features"


def save_factored_random_effect(
    output_dir: str,
    name: str,
    entity_factors: Dict[str, np.ndarray],  # raw entity id -> (k,) latent coeffs
    matrix: np.ndarray,  # (k, D_global) latent projection matrix
    random_effect_id: str = "",
    feature_shard_id: str = "",
    num_files: int = 1,
    index_map: Optional[IndexMap] = None,
) -> None:
    """Persist a factored random effect WITHOUT flattening: per-entity latent
    coefficients as LatentFactorAvro (effectId = raw entity id) plus the
    shared latent matrix (one LatentFactorAvro per latent dim, effectId =
    dim index). Round-trips to an identical FactoredState — the lossy
    v @ matrix flatten (VERDICT r2 missing #3) is no longer the only
    persisted form."""
    base = os.path.join(output_dir, RANDOM_EFFECT, name)
    os.makedirs(base, exist_ok=True)
    with open(os.path.join(base, ID_INFO), "w") as f:
        f.write(f"{random_effect_id}\n{feature_shard_id}\nfactored\n")
    save_latent_factors(os.path.join(base, LATENT_FACTORS), entity_factors, num_files)
    matrix = np.asarray(matrix)
    save_latent_factors(
        os.path.join(base, LATENT_MATRIX),
        {str(k): matrix[k] for k in range(matrix.shape[0])},
    )
    if index_map is not None:
        # the matrix columns are POSITIONAL in the training feature space;
        # persist the column->feature-key binding so a consumer with a
        # different index map (e.g. a scoring run that rebuilt its map from
        # scoring inputs) can realign columns by NAME instead of silently
        # reading the wrong ones. JSON: feature names/terms are arbitrary
        # strings (tabs/newlines legal), so a line format would corrupt
        pairs = []
        for j in range(matrix.shape[1]):
            key = index_map.get_feature_name(j) or str(j)
            pairs.append(list(_split_key(key)))
        import json as _json

        with open(os.path.join(base, LATENT_MATRIX_FEATURES), "w") as f:
            _json.dump({"columns": pairs}, f)


def load_latent_matrix(input_dir: str, name: str) -> np.ndarray:
    """ONLY the shared (k, D) latent matrix — what SPMD scoring replicates;
    the per-entity factors stay in their part files for per-host loading."""
    rows = load_latent_factors(
        os.path.join(input_dir, RANDOM_EFFECT, name, LATENT_MATRIX)
    )
    return np.stack([rows[str(k)] for k in range(len(rows))])


def load_factored_random_effect(input_dir: str, name: str
                                ) -> Tuple[Dict[str, np.ndarray], np.ndarray, str, str]:
    """Returns (entity latent factors, (k, D_global) matrix, reId, shard)."""
    base = os.path.join(input_dir, RANDOM_EFFECT, name)
    with open(os.path.join(base, ID_INFO)) as f:
        lines = f.read().splitlines()
    re_id = lines[0] if lines else ""
    shard = lines[1] if len(lines) > 1 else ""
    factors = load_latent_factors(os.path.join(base, LATENT_FACTORS))
    rows = load_latent_factors(os.path.join(base, LATENT_MATRIX))
    matrix = np.stack([rows[str(k)] for k in range(len(rows))])
    return factors, matrix, re_id, shard


def load_latent_matrix_feature_keys(input_dir: str, name: str):
    """Training-order feature keys of the latent matrix columns, or None
    when the model predates the binding file."""
    import json as _json

    path = os.path.join(input_dir, RANDOM_EFFECT, name, LATENT_MATRIX_FEATURES)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        text = f.read()
    try:
        pairs = _json.loads(text)["columns"]
    except _json.JSONDecodeError:
        # earlier binding files were 'name\tterm' lines (fragile for names
        # containing tabs/newlines, which is why the format moved to JSON) —
        # keep them loadable
        pairs = [
            line.partition("\t")[::2]
            for line in text.splitlines()
            if line
        ]
    # ALWAYS the delimiter form — feature_key(name, "") is "name\x01", not
    # bare "name" (a bare key would miss every empty-term feature)
    return [f"{nm}{DELIMITER}{term}" for nm, term in pairs]


def is_factored_random_effect(input_dir: str, name: str) -> bool:
    base = os.path.join(input_dir, RANDOM_EFFECT, name)
    info = os.path.join(base, ID_INFO)
    if not os.path.isfile(info):
        return False
    with open(info) as f:
        lines = f.read().splitlines()
    return len(lines) > 2 and lines[2] == "factored"


def list_game_model(input_dir: str) -> Dict[str, List[str]]:
    """Enumerate coordinate names present in a saved GAME model dir."""
    out = {FIXED_EFFECT: [], RANDOM_EFFECT: []}
    for kind in (FIXED_EFFECT, RANDOM_EFFECT):
        d = os.path.join(input_dir, kind)
        if os.path.isdir(d):
            out[kind] = sorted(os.listdir(d))
    return out


def aligned_latent_matrix(input_dir: str, name: str, index_map: IndexMap,
                          matrix: np.ndarray,
                          warn=None) -> np.ndarray:
    """Realign a factored model's (k, D_train) latent matrix columns to the
    CURRENT index map by feature NAME (the columns are positional in the
    training feature space; a scoring run may have rebuilt its map). Falls
    back to positional when the model predates the binding file — warns
    when that assumption is unprovable."""
    train_keys = load_latent_matrix_feature_keys(input_dir, name)
    if train_keys is None:
        if len(index_map) != matrix.shape[1]:
            raise ValueError(
                f"factored model {name!r} predates the latent-matrix "
                f"feature binding and this run's index map has "
                f"{len(index_map)} features vs the matrix's "
                f"{matrix.shape[1]} columns — cannot align; rebuild the "
                "model or pass the training offheap index maps"
            )
        if warn is not None:
            warn(
                f"factored model {name!r} has no latent-matrix feature "
                "binding: assuming this run's index map matches the "
                "training map POSITIONALLY (same size only proves length, "
                "not order) — scores are wrong if the feature sets differ; "
                "rebuild the model to get the binding"
            )
        return matrix.astype(np.float32)
    aligned = np.zeros((matrix.shape[0], len(index_map)), np.float32)
    for j, key in enumerate(train_keys):
        tgt = index_map.get_index(key)
        if tgt < 0 and key.endswith(DELIMITER):
            # empty-term fallback, e.g. the (INTERCEPT) pseudo-feature
            # stored without a delimiter
            tgt = index_map.get_index(key[: -len(DELIMITER)])
        if tgt >= 0:
            aligned[:, tgt] = matrix[:, j]
    return aligned
