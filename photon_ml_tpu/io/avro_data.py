"""Avro training-data ingest: TrainingExampleAvro -> columnar host datasets.

Reference spec: avro/data/DataProcessingUtils.scala:33-200 (GenericRecord ->
GameDatum: feature key = "name\\x01term", per-shard sparse vector assembly
with intercept append, id lookup from record field or metadataMap) and
io/GLMSuite.readLabeledPointsFromAvro (io/GLMSuite.scala:98-139).

Host-side, vectorized where it matters; produces the same HostDataset /
GameData containers the LIBSVM path produces.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.types import real_dtype

from photon_ml_tpu.data.game import GameData, HostFeatures
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.io.libsvm import HostDataset


def _iter_records(paths: Sequence[str]) -> Iterable[dict]:
    for p in paths:
        yield from avro_io.read_directory(p)


def collect_feature_keys(
    paths: Sequence[str], sections: Sequence[str] = ("features",)
) -> List[str]:
    """Whole-dataset feature vocabulary (NameAndTermFeatureSetContainer
    analogue). ``sections`` are the record fields holding FeatureAvro arrays
    (the reference's feature sections/bags)."""
    keys = set()
    for rec in _iter_records(paths):
        for section in sections:
            for f in rec.get(section) or []:
                keys.add(feature_key(f["name"], f["term"]))
    return sorted(keys)


def read_training_examples(
    paths: Sequence[str],
    index_map: IndexMap,
    add_intercept: bool = True,
    label_field: str = "label",
) -> HostDataset:
    """TrainingExampleAvro files -> HostDataset (single feature space).

    ``label_field``: "label" for TRAINING_EXAMPLE records, "response" for
    RESPONSE_PREDICTION ones (io/FieldNamesType.scala parity).
    """
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    indptr: List[int] = [0]
    indices: List[int] = []
    values: List[float] = []
    intercept_idx = index_map.intercept_index
    for rec in _iter_records(paths):
        labels.append(float(rec[label_field]))
        offsets.append(float(rec.get("offset") or 0.0))
        weights.append(float(rec.get("weight") if rec.get("weight") is not None else 1.0))
        for f in rec["features"]:
            idx = index_map.get_index(feature_key(f["name"], f["term"]))
            if idx >= 0:
                indices.append(idx)
                values.append(float(f["value"]))
        if add_intercept and intercept_idx >= 0:
            indices.append(intercept_idx)
            values.append(1.0)
        indptr.append(len(indices))
    return HostDataset(
        labels=np.asarray(labels, real_dtype()),
        indptr=np.asarray(indptr, np.int64),
        indices=np.asarray(indices, np.int32),
        values=np.asarray(values, real_dtype()),
        dim=len(index_map),
        offsets=np.asarray(offsets, real_dtype()),
        weights=np.asarray(weights, real_dtype()),
    )


def read_game_data(
    paths: Sequence[str],
    shard_index_maps: Dict[str, IndexMap],
    shard_sections: Dict[str, List[str]],
    id_types: Sequence[str],
    shard_intercepts: Optional[Dict[str, bool]] = None,
    id_vocabs: Optional[Dict[str, List[str]]] = None,
    response_required: bool = True,
) -> GameData:
    """TrainingExampleAvro -> GameData with per-shard feature spaces.

    ``shard_sections`` maps feature-shard id -> feature-bag names. The
    reference keys feature bags by Avro *section* (separate record fields);
    the common convention in photon datasets encodes the bag in the feature
    ``name`` prefix or uses one default section — here, a feature belongs to
    shard s iff its key is present in s's index map, which subsumes both.

    Entity ids are read from ``metadataMap`` (DataProcessingUtils.scala:
    90-114: field or metadata map lookup).
    """
    shard_intercepts = shard_intercepts or {s: True for s in shard_index_maps}
    n = 0
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    raw_ids: Dict[str, List[str]] = {t: [] for t in id_types}
    per_shard: Dict[str, Tuple[List[int], List[int], List[float]]] = {
        s: ([0], [], []) for s in shard_index_maps
    }
    for rec in _iter_records(paths):
        # response may be absent when scoring unlabeled data
        # (cli/game/scoring/Driver.scala isResponseRequired=false :83)
        label = rec.get("label", rec.get("response"))
        if label is None:
            if response_required:
                raise ValueError(f"row {n}: label/response missing")
            label = float("nan")
        labels.append(float(label))
        offsets.append(float(rec.get("offset") or 0.0))
        weights.append(float(rec.get("weight") if rec.get("weight") is not None else 1.0))
        meta = rec.get("metadataMap") or {}
        for t in id_types:
            # record field first, then metadataMap (DataProcessingUtils.scala:
            # 90-114 lookup order)
            if t in rec and rec[t] is not None:
                raw_ids[t].append(str(rec[t]))
            elif t in meta:
                raw_ids[t].append(meta[t])
            else:
                raise ValueError(
                    f"row {n}: id type {t!r} found neither as a record field "
                    "nor in metadataMap"
                )
        # compute each section's keyed features once, then probe shard maps
        keyed_by_section: Dict[str, List[Tuple[str, float]]] = {}
        for s, imap in shard_index_maps.items():
            ptr, idx, val = per_shard[s]
            for section in shard_sections.get(s) or ["features"]:
                if section not in keyed_by_section:
                    keyed_by_section[section] = [
                        (feature_key(f["name"], f["term"]), float(f["value"]))
                        for f in rec.get(section) or []
                    ]
                for key, value in keyed_by_section[section]:
                    j = imap.get_index(key)
                    if j >= 0:
                        idx.append(j)
                        val.append(value)
            if shard_intercepts.get(s, True) and imap.intercept_index >= 0:
                idx.append(imap.intercept_index)
                val.append(1.0)
            ptr.append(len(idx))
        n += 1

    ids: Dict[str, np.ndarray] = {}
    vocabs: Dict[str, List[str]] = {}
    for t in id_types:
        if id_vocabs is not None and t in id_vocabs:
            # reuse an existing (training) vocab: unseen entities map to -1
            # ("no model", scores 0 — RandomEffectModel.scala:129-158). Only
            # for scoring/validation reads, NOT for dataset building.
            vocab = list(id_vocabs[t])
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup.get(v, -1) for v in raw_ids[t]], np.int32)
        else:
            vocab = sorted(set(raw_ids[t]))
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup[v] for v in raw_ids[t]], np.int32)
        vocabs[t] = vocab

    shards = {
        s: HostFeatures(
            np.asarray(ptr, np.int64),
            np.asarray(idx, np.int32),
            np.asarray(val, real_dtype()),
            len(shard_index_maps[s]),
        )
        for s, (ptr, idx, val) in per_shard.items()
    }
    return GameData(
        response=np.asarray(labels, real_dtype()),
        offset=np.asarray(offsets, real_dtype()),
        weight=np.asarray(weights, real_dtype()),
        ids=ids,
        id_vocabs=vocabs,
        shards=shards,
    )


def write_training_examples(
    path: str,
    ds: HostDataset,
    index_map: IndexMap,
    metadata: Optional[Sequence[Dict[str, str]]] = None,
    skip_intercept: bool = True,
) -> None:
    """HostDataset -> TrainingExampleAvro container (the
    dev-scripts/libsvm_text_to_trainingexample_avro.py analogue)."""
    from photon_ml_tpu.io.index_map import DELIMITER

    intercept_idx = index_map.intercept_index

    def records():
        for r in range(ds.num_rows):
            row_indices, row_values = ds.row_slice(r)
            feats = []
            for j, v in zip(row_indices, row_values):
                if skip_intercept and j == intercept_idx:
                    continue
                key = index_map.get_feature_name(int(j)) or str(int(j))
                if DELIMITER in key:
                    name, term = key.split(DELIMITER, 1)
                else:
                    name, term = key, ""
                feats.append({"name": name, "term": term, "value": float(v)})
            yield {
                "uid": str(r),
                "label": float(ds.labels[r]),
                "features": feats,
                "metadataMap": dict(metadata[r]) if metadata is not None else None,
                "weight": float(ds.weights[r]) if ds.weights is not None else None,
                "offset": float(ds.offsets[r]) if ds.offsets is not None else None,
            }

    avro_io.write_container(path, records(), schemas.TRAINING_EXAMPLE)
