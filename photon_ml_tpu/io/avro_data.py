"""Avro training-data ingest: TrainingExampleAvro -> columnar host datasets.

Reference spec: avro/data/DataProcessingUtils.scala:33-200 (GenericRecord ->
GameDatum: feature key = "name\\x01term", per-shard sparse vector assembly
with intercept append, id lookup from record field or metadataMap) and
io/GLMSuite.readLabeledPointsFromAvro (io/GLMSuite.scala:98-139).

Host-side, vectorized where it matters; produces the same HostDataset /
GameData containers the LIBSVM path produces.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.types import real_dtype

from photon_ml_tpu import resilience
from photon_ml_tpu.data.game import GameData, HostFeatures
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.index_map import IndexMap, feature_key
from photon_ml_tpu.io.libsvm import HostDataset

logger = logging.getLogger(__name__)


def _iter_records(paths: Sequence[str]) -> Iterable[dict]:
    # per-block retry + corrupt-shard policy live in avro.read_container,
    # driven by the process-wide resilience config
    for p in paths:
        yield from avro_io.read_directory(p)


def _expand_part_files(paths: Sequence[str]) -> List[str]:
    """Part files in read_directory order (one shared definition)."""
    out: List[str] = []
    for p in paths:
        out.extend(avro_io.list_part_files(p))
    return out


def _native_columns(paths: Sequence[str]):
    """NativeColumns per part file, or None if ANY file can't take the
    native fast path (all-or-nothing keeps the assembly uniform).

    Reads retry under the active policy (the ``io.read_block`` fault site
    covers the whole-file native parse, block=-1). A file the native decoder
    rejects as corrupt falls back to the python row loop, which owns the
    block-granular corrupt-shard skip/raise semantics.
    """
    from photon_ml_tpu.io import avro_native
    from photon_ml_tpu.resilience import faults

    policy = resilience.current_config().io_policy

    def read_one(f: str):
        faults.inject("io.read_block", path=f, block=-1, offset=0)
        return avro_native.read_columns(f)

    cols = []
    for f in _expand_part_files(paths):
        try:
            c = resilience.call_with_retry(
                lambda f=f: read_one(f), policy, describe=f"native read {f}"
            )
        except ValueError as e:
            logger.warning(
                "native decoder rejected %s (%s); falling back to python ingest", f, e
            )
            return None
        if c is None:
            return None
        cols.append(c)
    return cols or None


def _padded_matrix(heap: bytes, offsets: np.ndarray, total: int) -> Tuple[np.ndarray, np.ndarray]:
    """(total, maxlen) u8 matrix of zero-padded strings + (total,) lengths,
    built fully vectorized from the byte heap."""
    buf = np.frombuffer(heap, np.uint8)
    starts = offsets[:total]
    lengths = (offsets[1 : total + 1] - starts).astype(np.int64)
    maxlen = int(lengths.max()) if total else 1
    maxlen = max(maxlen, 1)
    pos = starts[:, None] + np.arange(maxlen)[None, :]
    mask = np.arange(maxlen)[None, :] < lengths[:, None]
    safe = np.clip(pos, 0, max(len(buf) - 1, 0))
    mat = np.where(mask, buf[safe] if len(buf) else 0, 0).astype(np.uint8)
    return mat, lengths


_NONE_BYTES = np.frombuffer(b"None", np.uint8)


def _ntv_keys_to_indices(raw: dict, index_map: IndexMap,
                         return_keys: bool = False):
    """Vectorized feature-key -> index over a raw NTV column bundle: build
    padded (name, term) byte matrices, dedupe rows with np.unique, and touch
    python strings only once per UNIQUE key (IndexMap probe)."""
    total = raw["total"]
    if total == 0:
        empty = np.zeros(0, np.int64)
        return (empty, []) if return_keys else empty
    name_mat, name_len = _padded_matrix(raw["name_heap"], raw["name_off"], total)
    term = raw["term"]
    if term[0] == "strings":
        term_mat, term_len = _padded_matrix(term[1], term[2], total)
    elif term[0] == "union":
        _, heap, off_str, str_mask = term
        n_str = int(str_mask.sum())
        smat, slen = _padded_matrix(heap, off_str, n_str)
        width = max(smat.shape[1], 4)  # room for the literal "None"
        term_mat = np.zeros((total, width), np.uint8)
        term_len = np.empty(total, np.int64)
        term_mat[str_mask, : smat.shape[1]] = smat
        term_len[str_mask] = slen
        # python-codec parity: feature_key(name, None) stringifies None
        term_mat[~str_mask, :4] = _NONE_BYTES
        term_len[~str_mask] = 4
    else:  # "empty"
        term_mat = np.zeros((total, 1), np.uint8)
        term_len = np.zeros(total, np.int64)

    combined = np.concatenate(
        [
            name_len[:, None].view(np.uint8).reshape(total, 8),
            term_len[:, None].view(np.uint8).reshape(total, 8),
            name_mat,
            term_mat,
        ],
        axis=1,
    )
    rows = np.ascontiguousarray(combined).view(
        np.dtype((np.void, combined.shape[1]))
    ).ravel()
    uniq, first, inverse = np.unique(rows, return_index=True, return_inverse=True)

    nbuf = raw["name_heap"]
    keys = []
    for i in first:
        nm = nbuf[raw["name_off"][i] : raw["name_off"][i + 1]].decode("utf-8")
        tl = int(term_len[i])
        tm = term_mat[i, :tl].tobytes().decode("utf-8")
        keys.append(feature_key(nm, tm))
    mapped = np.fromiter(
        (index_map.get_index(k) for k in keys), dtype=np.int64, count=len(keys)
    )
    idx = mapped[inverse]
    return (idx, keys) if return_keys else idx


def collect_feature_keys(
    paths: Sequence[str], sections: Sequence[str] = ("features",)
) -> List[str]:
    """Whole-dataset feature vocabulary (NameAndTermFeatureSetContainer
    analogue). ``sections`` are the record fields holding FeatureAvro arrays
    (the reference's feature sections/bags). Columnar through the native
    decoder when the files support it."""
    native = _native_columns(paths)
    if native is not None:
        keys = set()
        supported = True

        class _AllKeys:
            """Index-map stand-in: _ntv_keys_to_indices probes once per
            unique key; we only want the keys."""

            @staticmethod
            def get_index(_k):
                return -1

        for cols in native:
            for section in sections:
                if not cols.has_field(section):
                    continue
                ntv = cols.ntv_array_raw(section)
                if ntv is None:
                    supported = False
                    break
                _, uniq_keys = _ntv_keys_to_indices(ntv, _AllKeys, return_keys=True)
                keys.update(uniq_keys)
            if not supported:
                break
        if supported:
            return sorted(keys)
    keys = set()
    for rec in _iter_records(paths):
        for section in sections:
            for f in rec.get(section) or []:
                keys.add(feature_key(f["name"], f["term"]))
    return sorted(keys)


def collect_entity_ids(
    paths: Sequence[str], id_types: Sequence[str]
) -> Dict[str, set]:
    """Raw entity-id sets per id type across ``paths`` — the delta-retrain
    planner's dirty-set probe (photon_ml_tpu.retrain): reading only the
    CHANGED files' id columns identifies every entity whose data moved,
    without re-ingesting the unchanged majority. Ids resolve exactly like
    :func:`read_game_data` (record field first, then metadataMap); a row
    missing an id type simply contributes nothing to that type's set (the
    planner's job is classification, not validation)."""
    out: Dict[str, set] = {t: set() for t in id_types}
    for rec in _iter_records(paths):
        meta = rec.get("metadataMap") or {}
        for t in id_types:
            if t in rec and rec[t] is not None:
                out[t].add(str(rec[t]))
            elif t in meta:
                out[t].add(meta[t])
    return out


def read_training_examples(
    paths: Sequence[str],
    index_map: IndexMap,
    add_intercept: bool = True,
    label_field: str = "label",
) -> HostDataset:
    """TrainingExampleAvro files -> HostDataset (single feature space).

    ``label_field``: "label" for TRAINING_EXAMPLE records, "response" for
    RESPONSE_PREDICTION ones (io/FieldNamesType.scala parity).

    Runs columnar through the native decoder when the files support it
    (identical output; PHOTON_ML_TPU_NATIVE=0 forces the python row loop).
    """
    native = _native_columns(paths)
    if native is not None:
        fast = _read_training_examples_columnar(
            native, index_map, add_intercept, label_field
        )
        if fast is not None:
            return fast
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    indptr: List[int] = [0]
    indices: List[int] = []
    values: List[float] = []
    intercept_idx = index_map.intercept_index
    for rec in _iter_records(paths):
        labels.append(float(rec[label_field]))
        offsets.append(float(rec.get("offset") or 0.0))
        weights.append(float(rec.get("weight") if rec.get("weight") is not None else 1.0))
        for f in rec["features"]:
            idx = index_map.get_index(feature_key(f["name"], f["term"]))
            if idx >= 0:
                indices.append(idx)
                values.append(float(f["value"]))
        if add_intercept and intercept_idx >= 0:
            indices.append(intercept_idx)
            values.append(1.0)
        indptr.append(len(indices))
    return HostDataset(
        labels=np.asarray(labels, real_dtype()),
        indptr=np.asarray(indptr, np.int64),
        indices=np.asarray(indices, np.int32),
        values=np.asarray(values, real_dtype()),
        dim=len(index_map),
        offsets=np.asarray(offsets, real_dtype()),
        weights=np.asarray(weights, real_dtype()),
    )


def _read_training_examples_columnar(
    cols_list, index_map: IndexMap, add_intercept: bool, label_field: str
) -> Optional[HostDataset]:
    """Vectorized assembly from native columns; None -> caller falls back."""
    parts = []
    intercept_idx = index_map.intercept_index
    for cols in cols_list:
        lab = cols.scalar(label_field)
        feats = cols.ntv_array_raw("features")
        if lab is None or feats is None or not lab[1].all():
            return None
        labels, _ = lab
        counts, values = feats["counts"], feats["values"]
        off = cols.scalar("offset")
        wt = cols.scalar("weight")
        n = cols.n
        # rec.get("offset") or 0.0 / weight None -> 1.0 (python-loop parity)
        offsets = np.where(off[1].astype(bool), off[0], 0.0) if off else np.zeros(n)
        weights = np.where(wt[1].astype(bool), wt[0], 1.0) if wt else np.ones(n)

        idx = _ntv_keys_to_indices(feats, index_map)
        keep = idx >= 0
        row_of_item = np.repeat(np.arange(n, dtype=np.int64), counts)
        kept_rows = row_of_item[keep]
        kept_idx = idx[keep].astype(np.int32)
        kept_vals = values[keep]
        per_row = np.bincount(kept_rows, minlength=n).astype(np.int64)
        order = np.argsort(kept_rows, kind="stable")
        kept_idx, kept_vals = kept_idx[order], kept_vals[order]
        if add_intercept and intercept_idx >= 0:
            ptr = np.zeros(n + 1, np.int64)
            np.cumsum(per_row, out=ptr[1:])
            kept_idx = np.insert(kept_idx, ptr[1:], np.full(n, intercept_idx, np.int32))
            kept_vals = np.insert(kept_vals, ptr[1:], np.ones(n))
            per_row = per_row + 1
        parts.append((labels, offsets, weights, per_row, kept_idx, kept_vals))

    labels = np.concatenate([p[0] for p in parts])
    offsets = np.concatenate([p[1] for p in parts])
    weights = np.concatenate([p[2] for p in parts])
    per_row = np.concatenate([p[3] for p in parts])
    indices = np.concatenate([p[4] for p in parts])
    values = np.concatenate([p[5] for p in parts])
    indptr = np.zeros(len(labels) + 1, np.int64)
    np.cumsum(per_row, out=indptr[1:])
    return HostDataset(
        labels=labels.astype(real_dtype()),
        indptr=indptr,
        indices=indices.astype(np.int32),
        values=values.astype(real_dtype()),
        dim=len(index_map),
        offsets=offsets.astype(real_dtype()),
        weights=weights.astype(real_dtype()),
    )


def read_game_data(
    paths: Sequence[str],
    shard_index_maps: Dict[str, IndexMap],
    shard_sections: Dict[str, List[str]],
    id_types: Sequence[str],
    shard_intercepts: Optional[Dict[str, bool]] = None,
    id_vocabs: Optional[Dict[str, List[str]]] = None,
    response_required: bool = True,
) -> GameData:
    """TrainingExampleAvro -> GameData with per-shard feature spaces.

    ``shard_sections`` maps feature-shard id -> feature-bag names. The
    reference keys feature bags by Avro *section* (separate record fields);
    the common convention in photon datasets encodes the bag in the feature
    ``name`` prefix or uses one default section — here, a feature belongs to
    shard s iff its key is present in s's index map, which subsumes both.

    Entity ids are read from ``metadataMap`` (DataProcessingUtils.scala:
    90-114: field or metadata map lookup).

    Runs columnar through the native decoder when the files support it
    (identical output; PHOTON_ML_TPU_NATIVE=0 forces the python row loop).
    """
    shard_intercepts = shard_intercepts or {s: True for s in shard_index_maps}
    native = _native_columns(paths)
    if native is not None:
        fast = _read_game_data_columnar(
            native, shard_index_maps, shard_sections, id_types,
            shard_intercepts, id_vocabs, response_required,
        )
        if fast is not None:
            return fast
    n = 0
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    raw_ids: Dict[str, List[str]] = {t: [] for t in id_types}
    per_shard: Dict[str, Tuple[List[int], List[int], List[float]]] = {
        s: ([0], [], []) for s in shard_index_maps
    }
    for rec in _iter_records(paths):
        # response may be absent when scoring unlabeled data
        # (cli/game/scoring/Driver.scala isResponseRequired=false :83)
        label = rec.get("label", rec.get("response"))
        if label is None:
            if response_required:
                raise ValueError(f"row {n}: label/response missing")
            label = float("nan")
        labels.append(float(label))
        offsets.append(float(rec.get("offset") or 0.0))
        weights.append(float(rec.get("weight") if rec.get("weight") is not None else 1.0))
        meta = rec.get("metadataMap") or {}
        for t in id_types:
            # record field first, then metadataMap (DataProcessingUtils.scala:
            # 90-114 lookup order)
            if t in rec and rec[t] is not None:
                raw_ids[t].append(str(rec[t]))
            elif t in meta:
                raw_ids[t].append(meta[t])
            else:
                raise ValueError(
                    f"row {n}: id type {t!r} found neither as a record field "
                    "nor in metadataMap"
                )
        # compute each section's keyed features once, then probe shard maps
        keyed_by_section: Dict[str, List[Tuple[str, float]]] = {}
        for s, imap in shard_index_maps.items():
            ptr, idx, val = per_shard[s]
            for section in shard_sections.get(s) or ["features"]:
                if section not in keyed_by_section:
                    keyed_by_section[section] = [
                        (feature_key(f["name"], f["term"]), float(f["value"]))
                        for f in rec.get(section) or []
                    ]
                for key, value in keyed_by_section[section]:
                    j = imap.get_index(key)
                    if j >= 0:
                        idx.append(j)
                        val.append(value)
            if shard_intercepts.get(s, True) and imap.intercept_index >= 0:
                idx.append(imap.intercept_index)
                val.append(1.0)
            ptr.append(len(idx))
        n += 1

    ids: Dict[str, np.ndarray] = {}
    vocabs: Dict[str, List[str]] = {}
    for t in id_types:
        if id_vocabs is not None and t in id_vocabs:
            # reuse an existing (training) vocab: unseen entities map to -1
            # ("no model", scores 0 — RandomEffectModel.scala:129-158). Only
            # for scoring/validation reads, NOT for dataset building.
            vocab = list(id_vocabs[t])
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup.get(v, -1) for v in raw_ids[t]], np.int32)
        else:
            vocab = sorted(set(raw_ids[t]))
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup[v] for v in raw_ids[t]], np.int32)
        vocabs[t] = vocab

    shards = {
        s: HostFeatures(
            np.asarray(ptr, np.int64),
            np.asarray(idx, np.int32),
            np.asarray(val, real_dtype()),
            len(shard_index_maps[s]),
        )
        for s, (ptr, idx, val) in per_shard.items()
    }
    return GameData(
        response=np.asarray(labels, real_dtype()),
        offset=np.asarray(offsets, real_dtype()),
        weight=np.asarray(weights, real_dtype()),
        ids=ids,
        id_vocabs=vocabs,
        shards=shards,
    )


def _read_game_data_columnar(
    cols_list,
    shard_index_maps: Dict[str, IndexMap],
    shard_sections: Dict[str, List[str]],
    id_types: Sequence[str],
    shard_intercepts: Dict[str, bool],
    id_vocabs: Optional[Dict[str, List[str]]],
    response_required: bool,
) -> Optional[GameData]:
    """Vectorized GAME ingest from native columns; None -> python loop."""
    all_labels, all_offsets, all_weights = [], [], []
    raw_ids: Dict[str, List[str]] = {t: [] for t in id_types}
    shard_parts: Dict[str, list] = {s: [] for s in shard_index_maps}

    for cols in cols_list:
        n = cols.n
        lab = cols.scalar("label") or cols.scalar("response")
        if lab is None:
            if cols.has_field("label") or cols.has_field("response"):
                return None  # exotic label type -> python loop semantics
            if response_required:
                return None  # python loop raises the canonical error
            labels = np.full(n, np.nan)
        else:
            vals, present = lab
            if present.all():
                labels = vals.copy()
            elif response_required:
                return None
            else:
                labels = np.where(present.astype(bool), vals, np.nan)
        off = cols.scalar("offset")
        wt = cols.scalar("weight")
        all_labels.append(labels)
        all_offsets.append(
            np.where(off[1].astype(bool), off[0], 0.0) if off else np.zeros(n)
        )
        all_weights.append(
            np.where(wt[1].astype(bool), wt[0], 1.0) if wt else np.ones(n)
        )

        # ids: record field first, metadataMap PER RECORD otherwise
        # (DataProcessingUtils.scala:90-114 lookup order; the python loop's
        # `t in rec and rec[t] is not None` is a per-record decision)
        meta = None
        meta_tried = False

        def _meta_lookup(i, t):
            nonlocal meta, meta_tried
            if not meta_tried:
                meta_tried = True
                m = cols.string_map("metadataMap")
                if m is not None:
                    mcounts, mkeys, mvals, mpresent = m
                    mstarts = np.zeros(len(mcounts) + 1, np.int64)
                    np.cumsum(mcounts, out=mstarts[1:])
                    mdense = np.cumsum(mpresent.astype(np.int64)) - 1
                    meta = (mstarts, mkeys, mvals, mpresent, mdense)
            if meta is None:
                return None
            mstarts, mkeys, mvals, mpresent, mdense = meta
            if not mpresent[i]:
                return None
            di = int(mdense[i])
            for j in range(int(mstarts[di]), int(mstarts[di + 1])):
                if mkeys[j] == t:
                    return mvals[j]
            return None

        for t in id_types:
            ftype = cols.field_type(t)
            field_vals = None  # list with None where the field value is null
            if ftype in ("int", "long"):
                sc = cols.scalar(t)
                field_vals = [
                    str(int(v)) if pr else None for v, pr in zip(sc[0], sc[1])
                ]
            elif ftype is not None:
                st = cols.strings(t)
                if st is not None:
                    field_vals = list(st[0])
                else:
                    return None  # exotic id field type -> python loop
            got = []
            for i in range(n):
                v = field_vals[i] if field_vals is not None else None
                if v is None:
                    v = _meta_lookup(i, t)
                if v is None:
                    return None  # missing id -> python loop raises the error
                got.append(v)
            raw_ids[t].extend(got)

        # per-shard features: union of the shard's sections
        section_cache: Dict[str, tuple] = {}
        for s, imap in shard_index_maps.items():
            per_row = np.zeros(n, np.int64)
            idx_parts, val_parts, row_parts = [], [], []
            for section in shard_sections.get(s) or ["features"]:
                if section not in section_cache:
                    if not cols.has_field(section):
                        section_cache[section] = None
                    else:
                        ntv = cols.ntv_array_raw(section)
                        if ntv is None:
                            return None
                        rows = np.repeat(
                            np.arange(n, dtype=np.int64), ntv["counts"]
                        )
                        section_cache[section] = (rows, ntv)
                cached = section_cache[section]
                if cached is None:
                    continue  # absent section == no features (python parity)
                rows, ntv = cached
                values = ntv["values"]
                idx = _ntv_keys_to_indices(ntv, imap)
                keep = idx >= 0
                row_parts.append(rows[keep])
                idx_parts.append(idx[keep].astype(np.int32))
                val_parts.append(values[keep])
            if row_parts:
                rows_k = np.concatenate(row_parts)
                idx_k = np.concatenate(idx_parts)
                vals_k = np.concatenate(val_parts)
                order = np.argsort(rows_k, kind="stable")
                rows_k, idx_k, vals_k = rows_k[order], idx_k[order], vals_k[order]
                per_row = np.bincount(rows_k, minlength=n).astype(np.int64)
            else:
                idx_k = np.zeros(0, np.int32)
                vals_k = np.zeros(0)
            if shard_intercepts.get(s, True) and imap.intercept_index >= 0:
                ptr = np.zeros(n + 1, np.int64)
                np.cumsum(per_row, out=ptr[1:])
                idx_k = np.insert(
                    idx_k, ptr[1:], np.full(n, imap.intercept_index, np.int32)
                )
                vals_k = np.insert(vals_k, ptr[1:], np.ones(n))
                per_row = per_row + 1
            shard_parts[s].append((per_row, idx_k, vals_k))

    labels = np.concatenate(all_labels) if all_labels else np.zeros(0)
    n_total = len(labels)
    ids: Dict[str, np.ndarray] = {}
    vocabs: Dict[str, List[str]] = {}
    for t in id_types:
        if id_vocabs is not None and t in id_vocabs:
            vocab = list(id_vocabs[t])
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup.get(v, -1) for v in raw_ids[t]], np.int32)
        else:
            vocab = sorted(set(raw_ids[t]))
            lookup = {v: i for i, v in enumerate(vocab)}
            ids[t] = np.asarray([lookup[v] for v in raw_ids[t]], np.int32)
        vocabs[t] = vocab

    shards = {}
    for s, parts in shard_parts.items():
        per_row = np.concatenate([p[0] for p in parts]) if parts else np.zeros(0, np.int64)
        indices = np.concatenate([p[1] for p in parts]) if parts else np.zeros(0, np.int32)
        values = np.concatenate([p[2] for p in parts]) if parts else np.zeros(0)
        indptr = np.zeros(n_total + 1, np.int64)
        np.cumsum(per_row, out=indptr[1:])
        shards[s] = HostFeatures(
            indptr, indices.astype(np.int32), values.astype(real_dtype()),
            len(shard_index_maps[s]),
        )
    return GameData(
        response=labels.astype(real_dtype()),
        offset=np.concatenate(all_offsets).astype(real_dtype()),
        weight=np.concatenate(all_weights).astype(real_dtype()),
        ids=ids,
        id_vocabs=vocabs,
        shards=shards,
    )


def write_training_examples(
    path: str,
    ds: HostDataset,
    index_map: IndexMap,
    metadata: Optional[Sequence[Dict[str, str]]] = None,
    skip_intercept: bool = True,
) -> None:
    """HostDataset -> TrainingExampleAvro container (the
    dev-scripts/libsvm_text_to_trainingexample_avro.py analogue)."""
    from photon_ml_tpu.io.index_map import DELIMITER

    intercept_idx = index_map.intercept_index

    def records():
        for r in range(ds.num_rows):
            row_indices, row_values = ds.row_slice(r)
            feats = []
            for j, v in zip(row_indices, row_values):
                if skip_intercept and j == intercept_idx:
                    continue
                key = index_map.get_feature_name(int(j)) or str(int(j))
                if DELIMITER in key:
                    name, term = key.split(DELIMITER, 1)
                else:
                    name, term = key, ""
                feats.append({"name": name, "term": term, "value": float(v)})
            yield {
                "uid": str(r),
                "label": float(ds.labels[r]),
                "features": feats,
                "metadataMap": dict(metadata[r]) if metadata is not None else None,
                "weight": float(ds.weights[r]) if ds.weights is not None else None,
                "offset": float(ds.offsets[r]) if ds.offsets is not None else None,
            }

    avro_io.write_container(path, records(), schemas.TRAINING_EXAMPLE)
