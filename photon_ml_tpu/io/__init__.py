from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.io.pipeline import Prefetcher, device_pipelined, prefetched
from photon_ml_tpu.io.tensor_cache import TensorCache, content_key

__all__ = [
    "IndexMap",
    "Prefetcher",
    "TensorCache",
    "content_key",
    "device_pipelined",
    "prefetched",
    "read_libsvm",
]
