from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.libsvm import read_libsvm

__all__ = ["IndexMap", "read_libsvm"]
