"""Avro schemas matching the reference's wire formats.

Field names/structure mirror photon-avro-schemas/src/main/avro/*.avsc so
data and models interchange byte-compatibly with the reference pipeline
(TrainingExampleAvro, FeatureAvro, NameTermValueAvro,
BayesianLinearModelAvro, LatentFactorAvro, ScoringResultAvro).
"""

NAME_TERM_VALUE = {
    "name": "NameTermValueAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE = {
    "name": "FeatureAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "name": "BayesianLinearModelAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

LATENT_FACTOR = {
    "name": "LatentFactorAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

SCORING_RESULT = {
    "name": "ScoringResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

# reference model class names, for modelClass/lossFunction round-trips
MODEL_CLASS_BY_TASK = {
    "LOGISTIC_REGRESSION": "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    "LINEAR_REGRESSION": "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    "POISSON_REGRESSION": "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
TASK_BY_MODEL_CLASS = {v: k for k, v in MODEL_CLASS_BY_TASK.items()}


# ---------------------------------------------------------------------------
# diagnostic / evaluation report schemas
# (photon-avro-schemas/src/main/avro/{Point2DAvro, Curve2DAvro,
#  SegmentContextAvro, TrainingTaskAvro, MLPackageAvro,
#  ConvergenceReasonAvro, TrainingContextAvro, EvaluationContextAvro,
#  EvaluationResultAvro, FeatureSummarizationResultAvro}.avsc —
# field names/order/types byte-compatible)
# ---------------------------------------------------------------------------

_NS = "com.linkedin.photon.avro.generated"

POINT_2D = {
    "name": "Point2DAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "x", "type": "double"},
        {"name": "y", "type": "double"},
    ],
}

CURVE_2D = {
    "name": "Curve2DAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "xLabel", "type": "string"},
        {"name": "yLabel", "type": "string"},
        {"name": "points", "type": {"type": "array", "items": POINT_2D}},
    ],
}

SEGMENT_CONTEXT = {
    "name": "SegmentContextAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "value", "type": "string"},
    ],
}

TRAINING_TASK = {
    "name": "TrainingTaskAvro",
    "namespace": _NS,
    "type": "enum",
    "symbols": ["LINEAR_REGRESSION", "LOGISTIC_REGRESSION", "POISSON_REGRESSION"],
}

ML_PACKAGE = {
    "name": "MLPackageAvro",
    "namespace": _NS,
    "type": "enum",
    "symbols": ["R", "LIBLINEAR", "ADMM", "PHOTONML"],
}

CONVERGENCE_REASON = {
    "name": "ConvergenceReasonAvro",
    "namespace": _NS,
    "type": "enum",
    "symbols": [
        "MAX_ITERATIONS",
        "FUNCTION_VALUES_CONVERGED",
        "GRADIENT_CONVERGED",
        "SEARCH_FAILED",
        "OBJECTIVE_NOT_IMPROVING",
    ],
}

TRAINING_CONTEXT = {
    "name": "TrainingContextAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "trainingTask", "type": TRAINING_TASK},
        {"name": "lambda1", "type": "double"},
        {"name": "lambda2", "type": "double"},
        {"name": "applyFeatureNormalization", "type": "boolean"},
        {"name": "timestamp", "type": "string"},
        {"name": "modelSource", "type": ML_PACKAGE},
        {"name": "optimizer", "type": ["null", "string"]},
        {"name": "convergenceTolerance", "type": "double"},
        {"name": "numberOfIterations", "type": "int"},
        {"name": "convergenceReason", "type": ["null", CONVERGENCE_REASON]},
        {"name": "sourceDataPath", "type": "string"},
        {"name": "description", "type": ["null", "string"]},
        {"name": "lossFunction", "type": "string"},
        {"name": "scoreFunction", "type": "string"},
    ],
}

EVALUATION_CONTEXT = {
    "name": "EvaluationContextAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "metricsCalculator", "type": "string"},
        {"name": "modelId", "type": "string"},
        {"name": "modelPath", "type": "string"},
        {"name": "modelTrainingContext", "type": TRAINING_CONTEXT},
        {"name": "timestamp", "type": "string"},
        {"name": "dataPath", "type": "string"},
        {"name": "segmentContext", "type": ["null", SEGMENT_CONTEXT], "default": None},
    ],
}

EVALUATION_RESULT = {
    "name": "EvaluationResultAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "evaluationContext", "type": EVALUATION_CONTEXT},
        {"name": "scalarMetrics", "type": {"type": "map", "values": "double"}},
        {"name": "curves", "type": {"type": "map", "values": CURVE_2D}},
    ],
}

FEATURE_SUMMARIZATION_RESULT = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": _NS,
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

# reference loss-function class names (TrainingContextAvro.lossFunction)
LOSS_CLASS_BY_TASK = {
    "LOGISTIC_REGRESSION": "com.linkedin.photon.ml.function.LogisticLossFunction",
    "LINEAR_REGRESSION": "com.linkedin.photon.ml.function.SquaredLossFunction",
    "POISSON_REGRESSION": "com.linkedin.photon.ml.function.PoissonLossFunction",
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "com.linkedin.photon.ml.function.SmoothedHingeLossFunction",
}
