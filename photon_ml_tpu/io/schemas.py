"""Avro schemas matching the reference's wire formats.

Field names/structure mirror photon-avro-schemas/src/main/avro/*.avsc so
data and models interchange byte-compatibly with the reference pipeline
(TrainingExampleAvro, FeatureAvro, NameTermValueAvro,
BayesianLinearModelAvro, LatentFactorAvro, ScoringResultAvro).
"""

NAME_TERM_VALUE = {
    "name": "NameTermValueAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE = {
    "name": "FeatureAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "name": "BayesianLinearModelAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

LATENT_FACTOR = {
    "name": "LatentFactorAvro",
    "namespace": "com.linkedin.photon.ml.avro.generated",
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

SCORING_RESULT = {
    "name": "ScoringResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

# reference model class names, for modelClass/lossFunction round-trips
MODEL_CLASS_BY_TASK = {
    "LOGISTIC_REGRESSION": "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    "LINEAR_REGRESSION": "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    "POISSON_REGRESSION": "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
TASK_BY_MODEL_CLASS = {v: k for k, v in MODEL_CLASS_BY_TASK.items()}
