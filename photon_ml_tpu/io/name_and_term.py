"""NameAndTerm feature-set container — the reference's (deprecated)
whole-dataset feature vocabulary path.

Reference spec: avro/data/NameAndTermFeatureSetContainer.scala:38-260 and
avro/data/NameAndTerm.scala — per feature-section sets of (name, term)
pairs, persisted as one text subdirectory per section (``name\\tterm``
lines), combinable into a feature→index map for a chosen set of sections
(getFeatureNameAndTermToIndexMap :46-57), plus a standalone CLI that scans
input avro data and writes the vocabulary
(NameAndTermFeatureSetContainer.main :127-260 — the
``--feature-name-and-term-set-path`` producer for the GAME driver,
deprecated in favor of the off-heap index maps but still part of the
surface).

Design deltas from the reference (documented, deliberate):
  * index assignment is SORTED (name, term) order, not JVM Set iteration
    order — deterministic maps are required for checkpoint/resume parity;
  * the "scan" is a host-side streaming pass over avro container files
    (io/avro_data.collect_feature_keys) instead of a Spark flatMap.
"""

from __future__ import annotations

import argparse
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from photon_ml_tpu.io import avro_data
from photon_ml_tpu.io.index_map import IndexMap, feature_key

NameAndTerm = Tuple[str, str]

INTERCEPT_NAME_AND_TERM: NameAndTerm = ("(INTERCEPT)", "")


class NameAndTermFeatureSetContainer:
    """Per-section (name, term) vocabulary sets."""

    def __init__(self, feature_sets: Dict[str, Set[NameAndTerm]]):
        self.feature_sets = {k: set(v) for k, v in feature_sets.items()}

    # -- combination ----------------------------------------------------
    def feature_name_and_term_to_index_map(
        self, section_keys: Sequence[str], add_intercept: bool = True
    ) -> Dict[NameAndTerm, int]:
        """Union the chosen sections and index them
        (getFeatureNameAndTermToIndexMap :46-57; sorted for determinism)."""
        union: Set[NameAndTerm] = set()
        for key in section_keys:
            union |= self.feature_sets.get(key, set())
        out = {nt: i for i, nt in enumerate(sorted(union))}
        if add_intercept:
            out[INTERCEPT_NAME_AND_TERM] = len(out)
        return out

    def index_map(self, section_keys: Sequence[str], add_intercept: bool = True) -> IndexMap:
        """Same union as an IndexMap (the framework's native map type)."""
        union: Set[NameAndTerm] = set()
        for key in section_keys:
            union |= self.feature_sets.get(key, set())
        return IndexMap.build(
            (feature_key(n, t) for n, t in union), add_intercept=add_intercept
        )

    # -- persistence (text layout: <dir>/<section>/part-00000) ----------
    def save_as_text(self, output_dir: str) -> None:
        """One subdirectory per section of ``name\\tterm`` lines
        (saveAsTextFiles :63-69 layout)."""
        for section, feature_set in self.feature_sets.items():
            d = os.path.join(output_dir, section)
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "part-00000"), "w") as f:
                for name, term in sorted(feature_set):
                    f.write(f"{name}\t{term}\n")

    @staticmethod
    def read_from_text(
        input_dir: str, section_keys: Sequence[str]
    ) -> "NameAndTermFeatureSetContainer":
        """readNameAndTermFeatureSetContainerFromTextFiles :75-88 parity:
        1 token = name with empty term; 2 = name, term; else error."""
        sets: Dict[str, Set[NameAndTerm]] = {}
        for section in section_keys:
            d = os.path.join(input_dir, section)
            feature_set: Set[NameAndTerm] = set()
            for fname in sorted(os.listdir(d)):
                if fname.startswith((".", "_")):
                    continue
                with open(os.path.join(d, fname)) as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line:
                            continue
                        parts = line.split("\t")
                        if len(parts) == 1:
                            feature_set.add((parts[0], ""))
                        elif len(parts) == 2:
                            feature_set.add((parts[0], parts[1]))
                        else:
                            raise ValueError(
                                f"Unexpected entry {line!r}: expected 1 or 2 "
                                f"tab-separated tokens, found {len(parts)}"
                            )
            sets[section] = feature_set
        return NameAndTermFeatureSetContainer(sets)

    # -- generation from data -------------------------------------------
    @staticmethod
    def generate_from_avro(
        paths: Sequence[str], section_keys: Sequence[str]
    ) -> "NameAndTermFeatureSetContainer":
        """ONE streaming pass over the avro inputs collecting every
        section's distinct (name, term) pairs (the main()'s Spark
        flatMap+distinct, host-side)."""
        sets: Dict[str, Set[NameAndTerm]] = {k: set() for k in section_keys}
        for rec in avro_data._iter_records(paths):
            for section in section_keys:
                for f in rec.get(section) or []:
                    sets[section].add((f["name"], f["term"]))
        return NameAndTermFeatureSetContainer(sets)


def main(argv: Optional[List[str]] = None) -> NameAndTermFeatureSetContainer:
    """Standalone vocabulary-generation job (the reference's
    Generate-Feature-Name-And-Term-List CLI, :127-260; flag names kept)."""
    from photon_ml_tpu.cli.game_training_driver import (
        _input_files,
        resolve_date_range_dirs,
    )
    from photon_ml_tpu.utils.io_utils import prepare_output_dir

    p = argparse.ArgumentParser(prog="generate-feature-name-and-term-list")
    p.add_argument("--data-input-directory", required=True,
                   help="comma-separated input dirs")
    p.add_argument("--date-range", default=None)
    p.add_argument("--date-range-days-ago", default=None)
    p.add_argument("--feature-name-and-term-set-output-dir", required=True)
    p.add_argument("--feature-section-keys", default="features",
                   help="comma-separated section keys")
    p.add_argument("--delete-output-dir-if-exists", default="false")
    p.add_argument("--application-name", default="generate-feature-name-and-term-list")
    ns = p.parse_args(argv)
    if ns.date_range and ns.date_range_days_ago:
        p.error("--date-range and --date-range-days-ago are exclusive")

    dirs = [d for d in ns.data_input_directory.split(",") if d]
    sections = [s.strip() for s in ns.feature_section_keys.split(",") if s.strip()]
    prepare_output_dir(
        ns.feature_name_and_term_set_output_dir,
        str(ns.delete_output_dir_if_exists).lower() in ("true", "1", "yes"),
    )
    paths = _input_files(
        resolve_date_range_dirs(dirs, ns.date_range, ns.date_range_days_ago)
    )
    container = NameAndTermFeatureSetContainer.generate_from_avro(paths, sections)
    container.save_as_text(ns.feature_name_and_term_set_output_dir)
    return container


if __name__ == "__main__":
    main()
