"""Async pipelined data path: bounded block prefetch + double-buffered H2D.

The streaming coordinates (algorithm/streaming_random_effect.py,
algorithm/streaming_fixed_effect.py via optim/streaming.py) and the per-host
ingest (parallel/perhost_ingest.py) were fully synchronous: the device idled
while the host decoded / mmap-faulted the next block, and the host idled
while the vmapped solve ran — out-of-core wall-clock was ingest + compute.
Snap ML's pipelined chunk prefetch across the storage -> host -> accelerator
hierarchy (PAPERS.md) hides essentially all I/O behind compute; this module
is that pipeline for the TPU port:

  * :class:`Prefetcher` / :func:`prefetched` — a bounded background-thread
    stage that produces up to ``depth`` items ahead of the consumer (disk
    read + slab assembly overlap compute). Items arrive in exactly the
    source order, and a producer exception is re-raised at the position the
    failing item would have occupied — a fault injected at ``io.cache_read``
    three blocks in surfaces to the consumer after blocks 0..2, never
    reordered, never swallowed.
  * :func:`device_pipelined` — double-buffered host->device transfer: the
    NEXT block's ``jax.device_put`` (an async dispatch) is issued while the
    CURRENT block is being consumed by the solver, and the stage's own
    reference to a consumed block is dropped on swap so its buffers free as
    soon as the solver releases them (the donation on swap).

Pipelining never changes WHAT is computed — blocks arrive in source order
and the consumer's arithmetic is untouched — so results are bit-identical
with the pipeline on or off (asserted by tests/test_pipeline.py).

``PHOTON_PREFETCH_DEPTH`` overrides the default depth process-wide
(``0`` forces every pipelined loop synchronous — the A/B lever bench.py's
``streaming_pipeline`` section uses).
"""

from __future__ import annotations

import collections
import os
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = [
    "DEFAULT_DEPTH",
    "Prefetcher",
    "prefetched",
    "device_pipelined",
    "resolve_depth",
]

DEFAULT_DEPTH = 2
_DEPTH_ENV = "PHOTON_PREFETCH_DEPTH"


def resolve_depth(depth: Optional[int]) -> int:
    """Effective prefetch depth: explicit ``depth`` wins; ``None`` falls back
    to ``PHOTON_PREFETCH_DEPTH`` (default 2). Depth <= 0 means synchronous."""
    if depth is not None:
        return int(depth)
    raw = os.environ.get(_DEPTH_ENV)
    if raw is None:
        return DEFAULT_DEPTH
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{_DEPTH_ENV} must be an integer, got {raw!r}")


class _EndOfStream:
    pass


_END = _EndOfStream()


class Prefetcher:
    """Bounded background-thread prefetcher over an iterable factory.

    ``source`` is a zero-arg callable returning an iterable (called once, in
    the worker thread, so even construction-time I/O overlaps the consumer)
    or a plain iterable. At most ``depth`` produced-but-unconsumed items are
    buffered; the worker blocks once the bound is reached, so a slow
    consumer never builds an unbounded backlog of slabs in host memory.

    Ordering/exception contract: items are yielded in production order; an
    exception raised by the source is re-raised to the consumer at exactly
    the position the failing item would have occupied (everything produced
    before it is still delivered first). After the error the iterator is
    exhausted.

    ``depth <= 0`` degrades to a synchronous passthrough — no thread, no
    behavior change, one code path for callers.
    """

    def __init__(
        self,
        source: "Callable[[], Iterable[Any]] | Iterable[Any]",
        depth: Optional[int] = None,
        name: str = "prefetch",
    ):
        self._depth = resolve_depth(depth)
        self._factory = source if callable(source) else (lambda: source)
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self._consumed = False

    # -- worker -------------------------------------------------------------
    def _run(self) -> None:
        q = self._queue
        try:
            for item in self._factory():
                while not self._stop.is_set():
                    try:
                        q.put(("item", item), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — the exception is NOT
            # swallowed: it crosses the thread boundary and re-raises in the
            # consumer at the failing item's position (the module contract)
            while not self._stop.is_set():
                try:
                    q.put(("error", e), timeout=0.1)
                    return
                except queue.Full:
                    continue
            return
        while not self._stop.is_set():
            try:
                q.put(("end", _END), timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer -----------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        # non-generator wrapper so the single-pass check fires at iter()
        # time, not at the first next()
        if self._consumed:
            raise RuntimeError("Prefetcher is single-pass; build a new one")
        self._consumed = True
        return self._iterate()

    def _iterate(self) -> Iterator[Any]:
        if self._depth <= 0:
            yield from self._factory()
            return
        self._queue = queue.Queue(maxsize=self._depth)
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True
        )
        self._thread.start()
        try:
            while True:
                kind, payload = self._queue.get()
                if kind == "item":
                    yield payload
                elif kind == "error":
                    raise payload
                else:
                    return
        finally:
            self.close()

    def close(self) -> None:
        """Stop the worker (e.g. the consumer abandoned the loop early).
        Idempotent; the worker exits at its next queue interaction."""
        self._stop.set()
        if self._queue is not None:
            try:  # unblock a worker waiting on a full queue
                self._queue.get_nowait()
            except queue.Empty:
                pass

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetched(
    source: "Callable[[], Iterable[Any]] | Iterable[Any]",
    depth: Optional[int] = None,
    name: str = "prefetch",
) -> Iterator[Any]:
    """Iterate ``source`` with up to ``depth`` items produced ahead on a
    background thread (:class:`Prefetcher` as a function)."""
    return iter(Prefetcher(source, depth=depth, name=name))


def device_pipelined(
    blocks: Iterable[Any],
    place: Callable[[Any], Any],
    depth: int = 1,
) -> Iterator[Any]:
    """Double-buffered device placement over a host-block stream.

    ``place`` maps a host block to its device form (typically
    ``jax.device_put`` / ``jnp.asarray`` over the block's arrays — an async
    dispatch that returns immediately while the transfer runs). The NEXT
    ``depth`` blocks' placements are issued before the CURRENT block is
    yielded, so block k+1's H2D transfer runs while block k solves. On each
    swap this stage drops its own reference to the yielded block, so device
    buffers free the moment the solver releases them.

    ``depth <= 0`` degrades to ``map(place, blocks)`` semantics (still lazy,
    no read-ahead).
    """
    it = iter(blocks)
    if depth <= 0:
        for b in it:
            yield place(b)
        return
    pending: "collections.deque[Any]" = collections.deque()
    exhausted = False
    while True:
        while not exhausted and len(pending) < depth + 1:
            try:
                pending.append(place(next(it)))
            except StopIteration:
                exhausted = True
        if not pending:
            return
        # popleft BEFORE yield: the stage holds no reference to the block
        # the consumer is working on (the donation on swap)
        yield pending.popleft()
