"""LIBSVM text ingest -> device-ready GLM batches.

Reference spec: io/LibSVMInputDataFormat.scala:31 (LIBSVM loader path) and
GLMSuite's intercept handling (intercept appended as the last column).

Host-side parse (numpy), then a single device_put of the padded columnar
batch. Rows are padded to the max row nnz (sparse path) or densified (dense
path); batch length is padded to a multiple for stable compiled shapes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.ops.features import DenseFeatures, SparseFeatures
from photon_ml_tpu.types import real_dtype
from photon_ml_tpu.ops.objective import GLMBatch


@dataclasses.dataclass
class HostDataset:
    """Parsed, still-on-host dataset (CSR-ish)."""

    labels: np.ndarray  # (N,)
    indptr: np.ndarray  # (N+1,)
    indices: np.ndarray  # (nnz,)
    values: np.ndarray  # (nnz,)
    dim: int
    offsets: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None

    @property
    def num_rows(self) -> int:
        return len(self.labels)

    def row_slice(self, r: int) -> Tuple[np.ndarray, np.ndarray]:
        s, e = self.indptr[r], self.indptr[r + 1]
        return self.indices[s:e], self.values[s:e]


def read_libsvm(path: str, dim: Optional[int] = None, add_intercept: bool = True,
                zero_based: bool = False) -> HostDataset:
    """Parse a LIBSVM file. Labels in {-1,1} or {0,1} are mapped to {0,1}."""
    labels: List[float] = []
    indptr = [0]
    indices: List[int] = []
    values: List[float] = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                i_s, v_s = tok.split(":")
                i = int(i_s) - (0 if zero_based else 1)
                indices.append(i)
                values.append(float(v_s))
                max_idx = max(max_idx, i)
            indptr.append(len(indices))
    y = np.asarray(labels, real_dtype())
    uniq = np.unique(y)
    if set(uniq.tolist()) <= {-1.0, 1.0}:
        y = (y > 0).astype(real_dtype())
    d = dim if dim is not None else max_idx + 1
    ind = np.asarray(indices, np.int32)
    val = np.asarray(values, real_dtype())
    ptr = np.asarray(indptr, np.int64)
    if add_intercept:
        # append intercept column (index d) to every row — vectorized insert
        n = len(y)
        ind = np.insert(ind, ptr[1:], np.full(n, d, np.int32))
        val = np.insert(val, ptr[1:], np.ones(n, real_dtype()))
        ptr = ptr + np.arange(n + 1, dtype=np.int64)
        d += 1
    return HostDataset(y, ptr, ind, val, d)


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def to_batch(ds: HostDataset, dense: bool = False, pad_rows_to: int = 8) -> GLMBatch:
    """Convert a HostDataset to a padded device GLMBatch.

    Padding rows get weight 0 (they vanish from every objective/metric).
    """
    n, d = ds.num_rows, ds.dim
    n_pad = _round_up(max(n, 1), pad_rows_to)
    weights = ds.weights if ds.weights is not None else np.ones(n, real_dtype())
    offsets = ds.offsets if ds.offsets is not None else np.zeros(n, real_dtype())

    labels = np.zeros(n_pad, real_dtype())
    labels[:n] = ds.labels
    w = np.zeros(n_pad, real_dtype())
    w[:n] = weights
    off = np.zeros(n_pad, real_dtype())
    off[:n] = offsets

    # vectorized CSR -> (row, slot) scatter coordinates
    row_nnz = np.diff(ds.indptr)
    rows = np.repeat(np.arange(n, dtype=np.int64), row_nnz)
    slots = np.arange(len(ds.indices), dtype=np.int64) - np.repeat(ds.indptr[:-1], row_nnz)
    if dense:
        x = np.zeros((n_pad, d), real_dtype())
        x[rows, ds.indices] = ds.values
        feats = DenseFeatures(jnp.asarray(x))
    else:
        k = int(row_nnz.max()) if n else 1
        idx = np.zeros((n_pad, k), np.int32)
        val = np.zeros((n_pad, k), real_dtype())
        idx[rows, slots] = ds.indices
        val[rows, slots] = ds.values
        feats = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
    return GLMBatch(feats, jnp.asarray(labels), jnp.asarray(off), jnp.asarray(w))
