"""LIBSVM text ingest -> device-ready GLM batches.

Reference spec: io/LibSVMInputDataFormat.scala:31 (LIBSVM loader path) and
GLMSuite's intercept handling (intercept appended as the last column).

Host-side parse (numpy), then a single device_put of the padded columnar
batch. Rows are padded to the max row nnz (sparse path) or densified (dense
path); batch length is padded to a multiple for stable compiled shapes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.ops.features import DenseFeatures, SparseFeatures
from photon_ml_tpu.types import real_dtype
from photon_ml_tpu.ops.objective import GLMBatch


@dataclasses.dataclass
class HostDataset:
    """Parsed, still-on-host dataset (CSR-ish)."""

    labels: np.ndarray  # (N,)
    indptr: np.ndarray  # (N+1,)
    indices: np.ndarray  # (nnz,)
    values: np.ndarray  # (nnz,)
    dim: int
    offsets: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None

    @property
    def num_rows(self) -> int:
        return len(self.labels)

    def row_slice(self, r: int) -> Tuple[np.ndarray, np.ndarray]:
        s, e = self.indptr[r], self.indptr[r + 1]
        return self.indices[s:e], self.values[s:e]


def _load_lsv_native():
    import ctypes

    from photon_ml_tpu.io.native_build import load_native_lib

    def configure(lib):
        lib.lsv_parse.restype = ctypes.c_void_p
        lib.lsv_parse.argtypes = [ctypes.c_char_p, ctypes.c_int]
        for fn in (lib.lsv_rows, lib.lsv_nnz, lib.lsv_max_index):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_void_p]
        lib.lsv_ok.restype = ctypes.c_int
        lib.lsv_ok.argtypes = [ctypes.c_void_p]
        lib.lsv_fill.restype = None
        lib.lsv_fill.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.lsv_free.restype = None
        lib.lsv_free.argtypes = [ctypes.c_void_p]

    return load_native_lib("libsvm_parser.cpp", configure)


def _parse_libsvm_native(path: str, zero_based: bool):
    """C++ fast path -> (labels f64, indptr i64, indices i32, values f64,
    max_idx) or None when the native lib is unavailable/rejects the file."""
    import ctypes

    lib = _load_lsv_native()
    if lib is None:
        return None
    h = lib.lsv_parse(path.encode(), 1 if zero_based else 0)
    if not h:
        return None
    try:
        if not lib.lsv_ok(h):
            return None  # malformed token: python path raises the real error
        n, nnz = lib.lsv_rows(h), lib.lsv_nnz(h)
        labels = np.empty(n, np.float64)
        indptr = np.empty(n + 1, np.int64)
        indices = np.empty(max(nnz, 1), np.int32)
        values = np.empty(max(nnz, 1), np.float64)
        lib.lsv_fill(
            h,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        return labels, indptr, indices[:nnz], values[:nnz], int(lib.lsv_max_index(h))
    finally:
        lib.lsv_free(h)


def read_libsvm(path: str, dim: Optional[int] = None, add_intercept: bool = True,
                zero_based: bool = False) -> HostDataset:
    """Parse a LIBSVM file. Labels in {-1,1} or {0,1} are mapped to {0,1}.

    Parsing runs through the native C++ loader (native/libsvm_parser.cpp,
    the reference's JVM-executor text ingest as a native runtime component)
    when available; a pure-Python parser with identical semantics is the
    fallback (PHOTON_ML_TPU_NATIVE=0 forces it)."""
    native = _parse_libsvm_native(path, zero_based)
    if native is not None:
        labels_a, ptr, ind, val_a, max_idx = native
        y = labels_a.astype(real_dtype())
        values_out = val_a
    else:
        labels: List[float] = []
        indptr = [0]
        indices: List[int] = []
        values: List[float] = []
        max_idx = -1
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i_s, v_s = tok.split(":")
                    i = int(i_s) - (0 if zero_based else 1)
                    indices.append(i)
                    values.append(float(v_s))
                    max_idx = max(max_idx, i)
                indptr.append(len(indices))
        y = np.asarray(labels, real_dtype())
        ptr = np.asarray(indptr, np.int64)
        ind = np.asarray(indices, np.int32)
        values_out = np.asarray(values, np.float64)

    uniq = np.unique(y)
    if set(uniq.tolist()) <= {-1.0, 1.0}:
        y = (y > 0).astype(real_dtype())
    d = dim if dim is not None else max_idx + 1
    val = values_out.astype(real_dtype())
    if add_intercept:
        # append intercept column (index d) to every row — vectorized insert
        n = len(y)
        ind = np.insert(ind, ptr[1:], np.full(n, d, np.int32))
        val = np.insert(val, ptr[1:], np.ones(n, real_dtype()))
        ptr = ptr + np.arange(n + 1, dtype=np.int64)
        d += 1
    return HostDataset(y, ptr, ind, val, d)


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def to_batch(ds: HostDataset, dense: bool = False, pad_rows_to: int = 8) -> GLMBatch:
    """Convert a HostDataset to a padded device GLMBatch.

    Padding rows get weight 0 (they vanish from every objective/metric).
    """
    n, d = ds.num_rows, ds.dim
    n_pad = _round_up(max(n, 1), pad_rows_to)
    weights = ds.weights if ds.weights is not None else np.ones(n, real_dtype())
    offsets = ds.offsets if ds.offsets is not None else np.zeros(n, real_dtype())

    labels = np.zeros(n_pad, real_dtype())
    labels[:n] = ds.labels
    w = np.zeros(n_pad, real_dtype())
    w[:n] = weights
    off = np.zeros(n_pad, real_dtype())
    off[:n] = offsets

    # vectorized CSR -> (row, slot) scatter coordinates
    row_nnz = np.diff(ds.indptr)
    rows = np.repeat(np.arange(n, dtype=np.int64), row_nnz)
    slots = np.arange(len(ds.indices), dtype=np.int64) - np.repeat(ds.indptr[:-1], row_nnz)
    if dense:
        x = np.zeros((n_pad, d), real_dtype())
        x[rows, ds.indices] = ds.values
        feats = DenseFeatures(jnp.asarray(x))
    else:
        k = int(row_nnz.max()) if n else 1
        idx = np.zeros((n_pad, k), np.int32)
        val = np.zeros((n_pad, k), real_dtype())
        idx[rows, slots] = ds.indices
        val[rows, slots] = ds.values
        from photon_ml_tpu.ops.features import auto_transpose

        feats = auto_transpose(SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d))
    return GLMBatch(feats, jnp.asarray(labels), jnp.asarray(off), jnp.asarray(w))
