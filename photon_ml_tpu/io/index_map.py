"""Feature index maps: feature name <-> dense column index.

Reference spec: util/IndexMap.scala:25-49 (two-way map, feature key =
"name\x01term"), DefaultIndexMap (in-memory), PalDBIndexMap (partitioned
off-heap store with global-offset binary search, PalDBIndexMap.scala:43-230).

TPU-native: the host-side ingest needs exactly one property — a
deterministic name->index assignment shared by every host. We keep the
reference's key convention and partitioned layout (hash-partitioned names,
global offset = partition offset + local index) but store each partition as
a sorted flat file loaded via numpy memmap-friendly arrays; no JVM, no
PalDB. Determinism replaces Spark-lineage reproducibility.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Dict, Iterable, List, Optional

DELIMITER = "\x01"  # reference feature key separator (Utils.scala getFeatureKey)
INTERCEPT_KEY = "(INTERCEPT)"  # reference constant GLMSuite.INTERCEPT_NAME_TERM


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}"


def partition_keys(feature_keys: Iterable[str], num_partitions: int) -> List[List[str]]:
    """Canonical index-assignment order: dedup, drop the intercept key,
    crc32-hash-partition, sort within each partition. BOTH index builders
    (in-memory IndexMap.build and the off-heap pmix store) derive indices
    from this one function, so they always agree (FeatureIndexingJob
    hash-partition parity)."""
    keys = set(feature_keys)
    keys.discard(INTERCEPT_KEY)
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for k in keys:
        parts[zlib.crc32(k.encode()) % num_partitions].append(k)
    for p in parts:
        p.sort()
    return parts


@dataclasses.dataclass
class IndexMap:
    """Two-way feature index. Immutable once built."""

    name_to_index: Dict[str, int]
    index_to_name: List[str]

    def __len__(self) -> int:
        return len(self.index_to_name)

    def get_index(self, key: str) -> int:
        return self.name_to_index.get(key, -1)

    def get_feature_name(self, idx: int) -> Optional[str]:
        return self.index_to_name[idx] if 0 <= idx < len(self.index_to_name) else None

    def __contains__(self, key: str) -> bool:
        return key in self.name_to_index

    @property
    def intercept_index(self) -> int:
        return self.name_to_index.get(INTERCEPT_KEY, -1)

    # -- construction -------------------------------------------------------
    @staticmethod
    def build(feature_keys: Iterable[str], add_intercept: bool = True,
              num_partitions: int = 1) -> "IndexMap":
        """Deterministic build: hash-partition names (FeatureIndexingJob
        parity), sort within partitions, concatenate with global offsets."""
        ordered: List[str] = []
        for p in partition_keys(feature_keys, num_partitions):
            ordered.extend(p)
        if add_intercept:
            ordered.append(INTERCEPT_KEY)
        return IndexMap({k: i for i, k in enumerate(ordered)}, ordered)

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.index_to_name, f)

    @staticmethod
    def load(path: str) -> "IndexMap":
        from photon_ml_tpu import resilience
        from photon_ml_tpu.resilience import faults

        def read() -> list:
            faults.inject("io.index_load", path=path)
            with open(path) as f:
                return json.load(f)

        names = resilience.call_with_retry(
            read, resilience.current_config().io_policy, describe=f"load {path}"
        )
        return IndexMap({k: i for i, k in enumerate(names)}, names)
