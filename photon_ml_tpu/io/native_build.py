"""Lazy g++ build + ctypes load for the native runtime pieces.

One cached .so per (source file, content hash) under the user cache dir;
any failure (no compiler, bad toolchain) degrades to ``None`` so every
native component keeps a pure-Python fallback. Set PHOTON_ML_TPU_NATIVE=0
to force the fallbacks (useful for differential testing).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import zlib
from typing import Callable, Optional

NATIVE_ENV = "PHOTON_ML_TPU_NATIVE"

_REPO_NATIVE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_cache: dict = {}


def native_enabled() -> bool:
    return os.environ.get(NATIVE_ENV, "1") not in ("0", "false", "no")


def load_native_lib(
    source_name: str,
    configure: Callable[[ctypes.CDLL], None],
    extra_flags: tuple = (),
) -> Optional[ctypes.CDLL]:
    """Compile native/<source_name> once (content-hashed cache) and load it;
    ``configure`` sets restype/argtypes. Returns None on any failure."""
    key = source_name
    if key in _cache:
        return _cache[key]
    if not native_enabled():
        _cache[key] = None
        return None
    try:
        source = os.path.join(_REPO_NATIVE, source_name)
        with open(source, "rb") as f:
            # tag covers source AND flags: a flag fix must invalidate the
            # cached .so even when the source is unchanged
            tag = f"{zlib.crc32(f.read() + repr(extra_flags).encode()):08x}"
        stem = os.path.splitext(source_name)[0]
        cache_dir = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "photon_ml_tpu",
        )
        os.makedirs(cache_dir, exist_ok=True)
        lib_path = os.path.join(cache_dir, f"lib{stem}-{tag}.so")
        if not os.path.exists(lib_path):
            with tempfile.TemporaryDirectory() as tmp:
                tmp_lib = os.path.join(tmp, "out.so")
                # libraries (-lz ...) must FOLLOW the source file or GNU ld
                # drops them and the .so carries undefined symbols
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp_lib, source, *extra_flags],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_lib, lib_path)
        lib = ctypes.CDLL(lib_path)
        configure(lib)
        _cache[key] = lib
    except Exception:  # noqa: BLE001 — fall back to pure Python
        _cache[key] = None
    return _cache[key]
