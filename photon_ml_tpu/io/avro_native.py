"""Python wrapper for the native Avro columnar decoder
(native/avro_decoder.cpp) — the data-loader half of the native runtime.

``iter_records(path)`` parses one container file through the C++ decoder
(block framing, raw-deflate, zigzag varints all native) and reconstructs
Python record dicts from the returned COLUMNS — byte-for-byte equal to
``io.avro.read_container`` for the supported schema shapes. Unsupported
shapes (bytes/fixed/enum fields, unions with multiple non-null value
branches, arrays of non-records...) return ``None`` so callers fall back to
the pure-Python codec, which remains the source of truth.

Caveat: the native path carries long/int values as f64 internally;
the DECODER flags any long outside +/-2^53 and the whole file falls back
to the exact python codec, so id/label precision can never silently
degrade.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io.native_build import load_native_lib

D_DOUBLE, D_FLOAT, D_LONG, D_INT = 0x01, 0x02, 0x03, 0x04
D_STRING, D_BOOL, D_NULL = 0x05, 0x06, 0x07
D_UNION, D_ARRAY, D_MAP, D_RECORD = 0x10, 0x20, 0x30, 0x40

_PRIMITIVE = {
    "double": D_DOUBLE,
    "float": D_FLOAT,
    "long": D_LONG,
    "int": D_INT,
    "string": D_STRING,
    "boolean": D_BOOL,
    "null": D_NULL,
}


def _load():
    def configure(lib):
        lib.avd_parse.restype = ctypes.c_void_p
        lib.avd_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.avd_num_records.restype = ctypes.c_long
        lib.avd_num_records.argtypes = [ctypes.c_void_p]
        lib.avd_error.restype = ctypes.c_char_p
        lib.avd_error.argtypes = [ctypes.c_void_p]
        lib.avd_free.restype = None
        lib.avd_free.argtypes = [ctypes.c_void_p]
        upath = ctypes.POINTER(ctypes.c_uint32)
        for f in (
            lib.avd_col_size_nums, lib.avd_col_size_heap,
            lib.avd_col_size_counts, lib.avd_col_size_kheap,
            lib.avd_col_size_offsets, lib.avd_col_size_present,
            lib.avd_col_size_koffsets, lib.avd_col_size_kinds,
        ):
            f.restype = ctypes.c_long
            f.argtypes = [ctypes.c_void_p, upath, ctypes.c_long]
        lib.avd_col_fetch_kinds.restype = ctypes.c_int
        lib.avd_col_fetch_kinds.argtypes = [
            ctypes.c_void_p, upath, ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.avd_col_fetch.restype = ctypes.c_int
        lib.avd_col_fetch.argtypes = [
            ctypes.c_void_p, upath, ctypes.c_long,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64),
        ]

    return load_native_lib("avro_decoder.cpp", configure, extra_flags=("-lz",))


def _resolve(schema, names: Dict[str, Any]):
    if isinstance(schema, str) and schema in names:
        return names[schema]
    return schema


def _build_descriptor(schema, names: Dict[str, Any], out: bytearray) -> bool:
    """schema dict -> wire descriptor; False when unsupported."""
    schema = _resolve(schema, names)
    if isinstance(schema, str):
        code = _PRIMITIVE.get(schema)
        if code is None:
            return False
        out.append(code)
        return True
    if isinstance(schema, list):  # union
        if len(schema) > 255:
            return False
        out.append(D_UNION)
        out.append(len(schema))
        for branch in schema:
            if not _build_descriptor(branch, names, out):
                return False
        return True
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in _PRIMITIVE:
            out.append(_PRIMITIVE[t])
            return True
        if t == "record":
            fields = schema.get("fields", [])
            if len(fields) > 255:
                return False
            out.append(D_RECORD)
            out.append(len(fields))
            for f in fields:
                if not _build_descriptor(f["type"], names, out):
                    return False
            return True
        if t == "array":
            out.append(D_ARRAY)
            return _build_descriptor(schema["items"], names, out)
        if t == "map":
            out.append(D_MAP)
            value_desc = bytearray()
            if not _build_descriptor(schema["values"], names, value_desc):
                return False
            # map values ride the child node's scalar columns; only
            # string/primitive values are supported
            if value_desc[0] not in (
                D_DOUBLE, D_FLOAT, D_LONG, D_INT, D_STRING, D_BOOL,
            ):
                return False
            out.extend(value_desc)
            return True
    return False  # enum / fixed / bytes / unknown


class _Handle:
    def __init__(self, lib, h):
        self.lib, self.h = lib, h

    def __del__(self):
        try:
            if self.h:
                self.lib.avd_free(self.h)
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def _path(self, path: Sequence[int]):
        arr = (ctypes.c_uint32 * len(path))(*path)
        return arr, len(path)

    def fetch(self, path: Sequence[int]):
        """-> dict of whichever columns the node carries."""
        lib = self.lib
        arr, n = self._path(path)
        n_nums = lib.avd_col_size_nums(self.h, arr, n)
        n_heap = lib.avd_col_size_heap(self.h, arr, n)
        n_counts = lib.avd_col_size_counts(self.h, arr, n)
        n_kheap = lib.avd_col_size_kheap(self.h, arr, n)
        n_offsets = lib.avd_col_size_offsets(self.h, arr, n)
        n_present = lib.avd_col_size_present(self.h, arr, n)
        n_koffsets = lib.avd_col_size_koffsets(self.h, arr, n)
        n_kinds = lib.avd_col_size_kinds(self.h, arr, n)
        if min(n_nums, n_heap, n_counts, n_kheap, n_offsets, n_present,
               n_koffsets, n_kinds) < 0:
            raise ValueError("bad column path")
        nums = np.empty(max(n_nums, 1), np.float64)
        present = np.empty(max(n_present, 1), np.uint8)
        heap = np.empty(max(n_heap, 1), np.uint8)
        counts = np.empty(max(n_counts, 1), np.int64)
        kheap = np.empty(max(n_kheap, 1), np.uint8)
        offsets = np.zeros(n_offsets + 1, np.int64)
        koffsets = np.zeros(n_koffsets + 1, np.int64)
        kinds = np.empty(max(n_kinds, 1), np.uint8)
        lib.avd_col_fetch_kinds(
            self.h, arr, n,
            kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        lib.avd_col_fetch(
            self.h, arr, n,
            nums.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            present.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            heap.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets[1:].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            kheap.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            koffsets[1:].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return {
            "nums": nums[:n_nums],
            "present": present[:n_present],
            "heap": heap[:n_heap].tobytes(),
            "offsets": offsets,
            "counts": counts[:n_counts],
            "kheap": kheap[:n_kheap].tobytes(),
            "koffsets": koffsets,
            "kinds": kinds[:n_kinds],
        }


def _parse_file(path: str, descriptor: bytes) -> Optional[Tuple[_Handle, int]]:
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        data = f.read()
    h = lib.avd_parse(data, len(data), bytes(descriptor), len(descriptor))
    if not h:
        return None
    handle = _Handle(lib, h)
    err = lib.avd_error(h)
    if err:
        return None  # fallback (unsupported codec/shape or corrupt)
    return handle, int(lib.avd_num_records(h))


def _strings(heap: bytes, offsets: np.ndarray, count: int) -> List[str]:
    return [
        heap[offsets[i]:offsets[i + 1]].decode("utf-8") for i in range(count)
    ]


def _scalar_value(code: int, v: float):
    if code in (D_LONG, D_INT):
        return int(v)
    if code == D_BOOL:
        return bool(v)
    return float(v)


def _read_schema_and_descriptor(path: str):
    """Container header -> (resolved record schema, names, descriptor), or
    None when the file/schema can't take the native path. The ONE preamble
    shared by iter_records and read_columns."""
    try:
        from photon_ml_tpu.io.avro import MAGIC, read_bytes, read_long, read_string

        with open(path, "rb") as f:
            if f.read(4) != MAGIC:
                return None
            meta = {}
            while True:
                cnt = read_long(f)
                if cnt == 0:
                    break
                if cnt < 0:
                    read_long(f)
                    cnt = -cnt
                for _ in range(cnt):
                    k = read_string(f)
                    meta[k] = read_bytes(f)
        schema = json.loads(meta["avro.schema"].decode())
    except Exception:  # noqa: BLE001 — any malformed header degrades to the pure-Python reader
        return None
    names: Dict[str, Any] = {}
    from photon_ml_tpu.io.avro import _register

    _register(schema, names)
    schema = _resolve(schema, names)
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        return None
    desc = bytearray()
    if not _build_descriptor(schema, names, desc):
        return None
    return schema, names, bytes(desc)


def iter_records(path: str) -> Optional[List[dict]]:
    """Decode one container file natively; None -> caller falls back."""
    pre = _read_schema_and_descriptor(path)
    if pre is None:
        return None
    schema, names, desc = pre
    parsed = _parse_file(path, desc)
    if parsed is None:
        return None
    handle, n_records = parsed

    fields = schema["fields"]
    columns: List[Tuple[str, Any]] = []
    try:
        for fi, field in enumerate(fields):
            columns.append((field["name"], _materialize(
                handle, (fi,), _resolve(field["type"], names), names, n_records
            )))
    except _Unsupported:
        return None
    return [
        {name: col(i) for name, col in columns} for i in range(n_records)
    ]


class _Unsupported(Exception):
    pass


def _materialize(handle: _Handle, path: Tuple[int, ...], schema, names, n: int):
    """-> callable(record_index) producing the field's python value."""
    schema = _resolve(schema, names)
    if isinstance(schema, dict) and schema.get("type") in _PRIMITIVE:
        schema = schema["type"]
    if isinstance(schema, str):
        code = _PRIMITIVE[schema]
        col = handle.fetch(path)
        if code == D_STRING:
            strs = _strings(col["heap"], col["offsets"], n)
            return lambda i: strs[i]
        if code == D_NULL:
            return lambda i: None
        nums = col["nums"]
        return lambda i, c=code: _scalar_value(c, nums[i])
    if isinstance(schema, list):  # union: kinds = chosen branch per entry
        branches = [_resolve(b, names) for b in schema]
        # primitive dicts like {"type": "double"} normalize to their name
        branches = [
            b["type"] if isinstance(b, dict) and b.get("type") in _PRIMITIVE else b
            for b in branches
        ]
        col = handle.fetch(path)
        kinds = col["kinds"]
        nums = col["nums"]
        is_string = np.asarray(
            [isinstance(b, str) and b == "string" for b in branches], bool
        )
        str_mask = is_string[kinds] if len(kinds) else np.zeros(0, bool)
        n_strings = int(str_mask.sum())
        strs = _strings(col["heap"], col["offsets"], n_strings)
        # entry -> rank among string entries (valid only where str_mask)
        str_rank = np.cumsum(str_mask) - 1
        getters = {}
        for bi, b in enumerate(branches):
            if isinstance(b, str) and b == "null":
                getters[bi] = lambda i: None
            elif isinstance(b, str) and b in (
                "double", "float", "long", "int", "boolean"
            ):
                code = _PRIMITIVE[b]
                getters[bi] = lambda i, c=code: _scalar_value(c, nums[i])
            elif isinstance(b, str) and b == "string":
                getters[bi] = lambda i: strs[int(str_rank[i])]
            elif isinstance(b, dict) and b.get("type") in ("map", "array", "record"):
                present_b = (kinds == bi).astype(np.uint8)
                getters[bi] = _materialize_sparse(
                    handle, path + (bi,), b, names, present_b
                )
            else:
                raise _Unsupported()
        return lambda i: getters[int(kinds[i])](i)
    if isinstance(schema, dict) and schema.get("type") == "array":
        item = _resolve(schema["items"], names)
        if not (isinstance(item, dict) and item.get("type") == "record"):
            raise _Unsupported()
        col = handle.fetch(path)
        counts = col["counts"]
        starts = np.zeros(len(counts) + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        total = int(starts[-1])
        fnames = [f["name"] for f in item["fields"]]
        # recurse per field over the FLATTENED item axis — unions, nested
        # records etc. come along for free
        fgetters = [
            _materialize(handle, path + (0, fj), f["type"], names, total)
            for fj, f in enumerate(item["fields"])
        ]

        def get_array(i):
            s, e = int(starts[i]), int(starts[i + 1])
            return [
                {nm: g(j) for nm, g in zip(fnames, fgetters)}
                for j in range(s, e)
            ]

        return get_array
    if isinstance(schema, dict) and schema.get("type") == "map":
        vt = _resolve(schema["values"], names)
        if not (isinstance(vt, str) and vt == "string"):
            raise _Unsupported()
        col = handle.fetch(path)
        counts = col["counts"]
        starts = np.zeros(len(counts) + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        total = int(starts[-1])
        keys = _strings(col["kheap"], col["koffsets"], total)
        vcol = handle.fetch(path + (0,))
        vals = _strings(vcol["heap"], vcol["offsets"], total)

        def get_map(i):
            s, e = starts[i], starts[i + 1]
            return {keys[j]: vals[j] for j in range(s, e)}

        return get_map
    raise _Unsupported()


def _materialize_sparse(handle, path, schema, names, present):
    """Union branch whose values exist only for ``present`` records (the
    child node holds one entry per PRESENT record)."""
    schema = _resolve(schema, names)
    dense_index = np.cumsum(present.astype(np.int64)) - 1  # record -> child row
    n_present = int(present.sum())
    if isinstance(schema, dict) and schema.get("type") == "map":
        inner = _materialize(handle, path, schema, names, n_present)
        return lambda i: inner(int(dense_index[i])) if present[i] else None
    if isinstance(schema, dict) and schema.get("type") == "array":
        inner = _materialize(handle, path, schema, names, n_present)
        return lambda i: inner(int(dense_index[i])) if present[i] else None
    raise _Unsupported()


# ---------------------------------------------------------------------------
# columnar API — the ingest fast path proper. iter_records() above rebuilds
# python dicts (wire decode native, materialization still python-bound);
# NativeColumns hands the raw columns to vectorized consumers
# (io/avro_data.py) so ingest never touches per-record python objects.
# ---------------------------------------------------------------------------


class NativeColumns:
    """Columnar view of one parsed container file."""

    def __init__(self, handle: _Handle, n: int, schema: dict, names: dict):
        self._h = handle
        self.n = n
        self._names = names
        self._fields = {f["name"]: (fi, _resolve(f["type"], names))
                        for fi, f in enumerate(schema["fields"])}

    def has_field(self, name: str) -> bool:
        return name in self._fields

    def field_type(self, name: str):
        """Resolved (normalized) declared type of a field, or None."""
        if name not in self._fields:
            return None
        return self._norm(self._fields[name][1])

    def _norm(self, t):
        t = _resolve(t, self._names)
        if isinstance(t, dict) and t.get("type") in _PRIMITIVE:
            return t["type"]
        return t

    def scalar(self, name: str):
        """-> (values f64, present u8) for numeric/bool fields, incl. via
        union; None when the field isn't scalar-shaped."""
        if name not in self._fields:
            return None
        fi, t = self._fields[name]
        t = self._norm(t)
        col = self._h.fetch((fi,))
        if isinstance(t, str) and t in ("double", "float", "long", "int", "boolean"):
            return col["nums"], np.ones(self.n, np.uint8)
        if isinstance(t, list):
            branches = [self._norm(b) for b in t]
            scalarish = np.asarray([
                isinstance(b, str) and b in (
                    "null", "double", "float", "long", "int", "boolean",
                )
                for b in branches
            ], bool)
            if scalarish.all():
                return col["nums"], col["present"]
            # mixed union (e.g. yahoo's [double,...,string] response): usable
            # iff no record ACTUALLY chose a non-scalar branch
            kinds = col["kinds"]
            if len(kinds) == self.n and scalarish[kinds].all():
                return col["nums"], col["present"]
        return None

    def strings(self, name: str):
        """-> (list[str|None], present) for string fields (incl. union with
        null); None if not string-shaped."""
        if name not in self._fields:
            return None
        fi, t = self._fields[name]
        t = self._norm(t)
        col = self._h.fetch((fi,))
        if isinstance(t, str) and t == "string":
            return _strings(col["heap"], col["offsets"], self.n), np.ones(self.n, np.uint8)
        if isinstance(t, list):
            branches = [self._norm(b) for b in t]
            if all(isinstance(b, str) and b in ("null", "string") for b in branches):
                kinds = col["kinds"]
                is_str = np.asarray([b == "string" for b in branches], bool)
                mask = is_str[kinds].astype(np.uint8) if len(kinds) else np.zeros(0, np.uint8)
                vals = _strings(col["heap"], col["offsets"], int(mask.sum()))
                rank = np.cumsum(mask) - 1
                out = [vals[int(rank[i])] if mask[i] else None for i in range(self.n)]
                return out, mask
        return None

    def ntv_array(self, name: str):
        """-> (counts i64, names list[str], terms list[str], values f64) for
        an array of NameTermValue-shaped records (term may be a
        (null,string) union: a null term renders as the python codec does
        through feature_key — the literal string "None").

        None when the field isn't shaped like that."""
        if name not in self._fields:
            return None
        fi, t = self._fields[name]
        t = self._norm(t)
        if not (isinstance(t, dict) and t.get("type") == "array"):
            return None
        item = _resolve(t["items"], self._names)
        if not (isinstance(item, dict) and item.get("type") == "record"):
            return None
        sub = {f["name"]: (fj, self._norm(f["type"])) for fj, f in enumerate(item["fields"])}
        if not {"name", "value"} <= set(sub):
            return None
        col = self._h.fetch((fi,))
        counts = col["counts"]
        total = int(counts.sum())

        nj, nt = sub["name"]
        if nt != "string":
            return None
        ncol = self._h.fetch((fi, 0, nj))
        names_l = _strings(ncol["heap"], ncol["offsets"], total)

        vj, vt = sub["value"]
        if vt not in ("double", "float", "long", "int"):
            return None
        values = self._h.fetch((fi, 0, vj))["nums"][:total]

        if "term" in sub:
            tj, tt = sub["term"]
            tcol = self._h.fetch((fi, 0, tj))
            if tt == "string":
                terms_l = _strings(tcol["heap"], tcol["offsets"], total)
            elif isinstance(tt, list) and all(
                isinstance(b, str) and b in ("null", "string") for b in tt
            ):
                kinds = tcol["kinds"]
                is_str = np.asarray([b == "string" for b in (tt)], bool)
                mask = is_str[kinds] if len(kinds) else np.zeros(0, bool)
                vals = _strings(tcol["heap"], tcol["offsets"], int(mask.sum()))
                rank = np.cumsum(mask) - 1
                # feature_key(name, None) stringifies None — keep that exact
                terms_l = [
                    vals[int(rank[i])] if mask[i] else "None" for i in range(total)
                ]
            else:
                return None
        else:
            terms_l = [""] * total
        return counts, names_l, terms_l, values

    def ntv_array_raw(self, name: str):
        """Raw-bytes variant of :meth:`ntv_array` — no per-item python
        strings (the columnar ingest builds keys vectorized on the heaps).

        -> dict(counts, values, name_heap, name_off, term) where term is
        ("strings", heap, off) | ("union", heap, off_str_only, str_mask)
        | ("empty",); None when unsupported."""
        if name not in self._fields:
            return None
        fi, t = self._fields[name]
        t = self._norm(t)
        if not (isinstance(t, dict) and t.get("type") == "array"):
            return None
        item = _resolve(t["items"], self._names)
        if not (isinstance(item, dict) and item.get("type") == "record"):
            return None
        sub = {f["name"]: (fj, self._norm(f["type"])) for fj, f in enumerate(item["fields"])}
        if not {"name", "value"} <= set(sub):
            return None
        counts = self._h.fetch((fi,))["counts"]
        total = int(counts.sum())
        nj, nt = sub["name"]
        if nt != "string":
            return None
        ncol = self._h.fetch((fi, 0, nj))
        vj, vt = sub["value"]
        if vt not in ("double", "float", "long", "int"):
            return None
        values = self._h.fetch((fi, 0, vj))["nums"][:total]
        if "term" in sub:
            tj, tt = sub["term"]
            tcol = self._h.fetch((fi, 0, tj))
            if tt == "string":
                term = ("strings", tcol["heap"], tcol["offsets"])
            elif isinstance(tt, list) and all(
                isinstance(b, str) and b in ("null", "string") for b in tt
            ):
                kinds = tcol["kinds"]
                is_str = np.asarray([b == "string" for b in tt], bool)
                mask = is_str[kinds] if len(kinds) else np.zeros(0, bool)
                term = ("union", tcol["heap"], tcol["offsets"], mask)
            else:
                return None
        else:
            term = ("empty",)
        return {
            "counts": counts,
            "values": values,
            "name_heap": ncol["heap"],
            "name_off": ncol["offsets"],
            "term": term,
            "total": total,
        }

    def string_map(self, name: str):
        """-> (counts per PRESENT record, keys, values, present mask) for a
        map<string> field (possibly union with null); None otherwise."""
        if name not in self._fields:
            return None
        fi, t = self._fields[name]
        t = self._norm(t)
        col = self._h.fetch((fi,))
        if isinstance(t, dict) and t.get("type") == "map":
            present = np.ones(self.n, np.uint8)
            mpath = (fi,)
        elif isinstance(t, list):
            branches = [self._norm(b) for b in t]
            map_branches = [
                (bi, b) for bi, b in enumerate(branches)
                if isinstance(b, dict) and b.get("type") == "map"
            ]
            if len(map_branches) != 1 or not all(
                (isinstance(b, str) and b == "null") or
                (isinstance(b, dict) and b.get("type") == "map")
                for b in branches
            ):
                return None
            bi, b = map_branches[0]
            present = (col["kinds"] == bi).astype(np.uint8)
            t = b
            mpath = (fi, bi)
        else:
            return None
        if self._norm(t["values"]) != "string":
            return None
        mcol = self._h.fetch(mpath)
        counts = mcol["counts"]
        total = int(counts.sum())
        keys = _strings(mcol["kheap"], mcol["koffsets"], total)
        vcol = self._h.fetch(mpath + (0,))
        vals = _strings(vcol["heap"], vcol["offsets"], total)
        return counts, keys, vals, present


def read_columns(path: str) -> Optional[NativeColumns]:
    """Parse one container file into a NativeColumns view, or None when the
    native decoder is unavailable or the schema shape is unsupported."""
    pre = _read_schema_and_descriptor(path)
    if pre is None:
        return None
    schema, names, desc = pre
    parsed = _parse_file(path, desc)
    if parsed is None:
        return None
    handle, n = parsed
    return NativeColumns(handle, n, schema, names)
