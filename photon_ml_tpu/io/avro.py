"""Minimal pure-Python Avro: binary encoding + object container files.

The reference reads/writes all data and models as Avro on HDFS
(avro/AvroUtils.scala:43-270, AvroIOUtils.scala). This framework keeps the
same on-disk formats for drop-in compatibility, implemented from the public
Avro 1.x specification (binary encoding: zigzag-varint longs, little-endian
doubles, length-prefixed strings/bytes, block-encoded arrays/maps; container
file: "Obj\\x01" magic, metadata map with avro.schema/avro.codec, 16-byte
sync marker, data blocks of [count, size, payload, sync]).

Supports the subset the photon schemas use: record, array, map, union,
string, bytes, double, float, long, int, boolean, null, enum. Codecs: null
and deflate (zlib).

No external dependencies — works in the baked image (fastavro is absent).
"""

from __future__ import annotations

import io as _io
import json
import logging
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Union

MAGIC = b"Obj\x01"
DEFAULT_SYNC = b"\x50\x48\x4f\x54\x4f\x4e\x2d\x54\x50\x55\x2d\x53\x59\x4e\x43\x21"  # 16B

Schema = Union[str, dict, list]

logger = logging.getLogger(__name__)


class CorruptBlockError(ValueError):
    """A container block failed to decode. Carries the file path, block
    index, and byte offset so a corrupt shard report is actionable (which
    part-file to quarantine, where to look with a hex editor)."""

    def __init__(self, path: str, block_index: int, offset: int, reason: str):
        self.path = path
        self.block_index = block_index
        self.offset = offset
        self.reason = reason
        super().__init__(
            f"{path}: corrupt avro block {block_index} at byte offset "
            f"{offset}: {reason}"
        )


# ---------------------------------------------------------------------------
# primitive encoders / decoders
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: BinaryIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("unexpected end of avro data")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            return _zigzag_decode(acc)
        shift += 7


def write_bytes(buf: BinaryIO, data: bytes) -> None:
    write_long(buf, len(data))
    buf.write(data)


def read_bytes(buf: BinaryIO) -> bytes:
    n = read_long(buf)
    return buf.read(n)


def write_string(buf: BinaryIO, s: str) -> None:
    write_bytes(buf, s.encode("utf-8"))


def read_string(buf: BinaryIO) -> str:
    return read_bytes(buf).decode("utf-8")


# ---------------------------------------------------------------------------
# schema-driven datum encoding
# ---------------------------------------------------------------------------


def _resolve(schema: Schema, names: Dict[str, dict]) -> Schema:
    if isinstance(schema, str) and schema in names:
        return names[schema]
    return schema


def _register(schema: Schema, names: Dict[str, dict]) -> None:
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            names[schema["name"]] = schema
            full = schema.get("namespace", "") + "." + schema["name"]
            names[full.lstrip(".")] = schema
        if t == "record":
            for f in schema["fields"]:
                _register(f["type"], names)
        elif t == "array":
            _register(schema["items"], names)
        elif t == "map":
            _register(schema["values"], names)
    elif isinstance(schema, list):
        for s in schema:
            _register(s, names)


def write_datum(buf: BinaryIO, datum: Any, schema: Schema, names: Dict[str, dict]) -> None:
    schema = _resolve(schema, names)
    if isinstance(schema, list):  # union: pick first matching branch
        idx, branch = _match_union(datum, schema, names)
        write_long(buf, idx)
        write_datum(buf, datum, branch, names)
        return
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return
    if t == "boolean":
        buf.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        write_long(buf, int(datum))
    elif t == "float":
        buf.write(struct.pack("<f", float(datum)))
    elif t == "double":
        buf.write(struct.pack("<d", float(datum)))
    elif t == "bytes":
        write_bytes(buf, datum)
    elif t == "string":
        write_string(buf, datum)
    elif t == "enum":
        write_long(buf, schema["symbols"].index(datum))
    elif t == "fixed":
        buf.write(datum)
    elif t == "array":
        if datum:
            write_long(buf, len(datum))
            for item in datum:
                write_datum(buf, item, schema["items"], names)
        write_long(buf, 0)
    elif t == "map":
        if datum:
            write_long(buf, len(datum))
            for k, v in datum.items():
                write_string(buf, k)
                write_datum(buf, v, schema["values"], names)
        write_long(buf, 0)
    elif t == "record":
        for f in schema["fields"]:
            name = f["name"]
            if name in datum:
                value = datum[name]
            elif "default" in f:
                value = f["default"]
            else:
                raise ValueError(f"missing field {name} for record {schema['name']}")
            write_datum(buf, value, f["type"], names)
    else:
        raise ValueError(f"unsupported schema type: {t}")


def _match_union(datum, union: list, names) -> tuple:
    for i, branch in enumerate(union):
        b = _resolve(branch, names)
        t = b["type"] if isinstance(b, dict) else b
        if datum is None and t == "null":
            return i, branch
        if datum is not None and t != "null":
            return i, branch
    raise ValueError(f"no union branch for {datum!r} in {union}")


def read_datum(buf: BinaryIO, schema: Schema, names: Dict[str, dict]) -> Any:
    schema = _resolve(schema, names)
    if isinstance(schema, list):
        idx = read_long(buf)
        return read_datum(buf, schema[idx], names)
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return read_bytes(buf)
    if t == "string":
        return read_string(buf)
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:  # block with byte size prefix
                read_long(buf)
                count = -count
            for _ in range(count):
                out.append(read_datum(buf, schema["items"], names))
    if t == "map":
        res: Dict[str, Any] = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return res
            if count < 0:
                read_long(buf)
                count = -count
            for _ in range(count):
                k = read_string(buf)
                res[k] = read_datum(buf, schema["values"], names)
    if t == "record":
        return {f["name"]: read_datum(buf, f["type"], names) for f in schema["fields"]}
    raise ValueError(f"unsupported schema type: {t}")


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------


def write_container(
    path: str,
    records: Iterable[Any],
    schema: Schema,
    codec: str = "deflate",
    block_size: int = 4096,
) -> None:
    names: Dict[str, dict] = {}
    _register(schema, names)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode(),
        }
        write_long(f, len(meta))
        for k, v in meta.items():
            write_string(f, k)
            write_bytes(f, v)
        write_long(f, 0)
        f.write(DEFAULT_SYNC)

        block = _io.BytesIO()
        count = 0

        def flush():
            nonlocal block, count
            if count == 0:
                return
            payload = block.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate per spec
            write_long(f, count)
            write_bytes(f, payload)
            f.write(DEFAULT_SYNC)
            block = _io.BytesIO()
            count = 0

        for rec in records:
            write_datum(block, rec, schema, names)
            count += 1
            if count >= block_size:
                flush()
        flush()


def _resync(f: BinaryIO, sync: bytes, start: int) -> Optional[int]:
    """Scan forward from ``start`` for the next 16-byte sync marker; return
    the offset just past it (the next block start), or None at EOF. Reads in
    chunks with a 15-byte overlap so a marker straddling a chunk boundary is
    still found."""
    chunk_size = 1 << 16
    f.seek(start)
    carry = b""
    base = start
    while True:
        chunk = f.read(chunk_size)
        if not chunk:
            return None
        buf = carry + chunk
        hit = buf.find(sync)
        if hit >= 0:
            return base - len(carry) + hit + len(sync)
        carry = buf[-(len(sync) - 1):]
        base += len(chunk)


def read_container(
    path: str,
    on_corrupt: Optional[str] = None,
    skip_budget: Optional[int] = None,
) -> Iterator[Any]:
    """Iterate records of one container file.

    Transient read failures (OSError, including injected
    ``io.read_block`` faults) are retried per block with the active
    :class:`~photon_ml_tpu.resilience.RetryPolicy` — the file offset is
    remembered before each block so a retry re-reads exactly that block.

    ``on_corrupt="skip"`` drops undecodable blocks (resynchronizing on the
    sync marker) up to ``skip_budget`` blocks before raising; ``"raise"``
    (default) surfaces the first :class:`CorruptBlockError`. Both default to
    the process-wide resilience config.
    """
    from photon_ml_tpu import resilience
    from photon_ml_tpu.resilience import faults

    cfg = resilience.current_config()
    if on_corrupt is None:
        on_corrupt = cfg.on_corrupt
    if on_corrupt not in resilience.ON_CORRUPT_MODES:
        raise ValueError(
            f"on_corrupt must be one of {resilience.ON_CORRUPT_MODES}, "
            f"got {on_corrupt!r}"
        )
    if skip_budget is None:
        skip_budget = cfg.corrupt_skip_budget
    policy = cfg.io_policy

    with resilience.call_with_retry(
        lambda: open(path, "rb"), policy, describe=f"open {path}"
    ) as f:

        def read_header():
            """Magic + metadata map + sync marker; seeks to 0 first so the
            enclosing retry (transient read errors mid-header) is idempotent."""
            f.seek(0)
            if f.read(4) != MAGIC:
                raise ValueError(f"{path}: not an avro container file")
            meta: Dict[str, bytes] = {}
            while True:
                count = read_long(f)
                if count == 0:
                    break
                if count < 0:
                    read_long(f)
                    count = -count
                for _ in range(count):
                    k = read_string(f)
                    meta[k] = read_bytes(f)
            return meta, f.read(16)

        meta, sync = resilience.call_with_retry(
            read_header, policy, describe=f"read {path} header"
        )
        schema = json.loads(meta["avro.schema"].decode())
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("deflate", "null"):
            raise ValueError(f"unsupported codec {codec}")
        names: Dict[str, dict] = {}
        _register(schema, names)

        block_index = 0
        skipped = 0

        def read_block(offset: int, index: int) -> Optional[List[Any]]:
            """One complete block -> record list; None on clean EOF. Seeks
            back to ``offset`` first so the enclosing retry is idempotent;
            decode failures become CorruptBlockError (never retried —
            re-reading corrupt bytes cannot help)."""
            f.seek(offset)
            faults.inject("io.read_block", path=path, block=index, offset=offset)
            try:
                count = read_long(f)
            except EOFError:
                return None  # clean end of container
            try:
                payload = read_bytes(f)
                if codec == "deflate":
                    payload = zlib.decompress(payload, -15)
                block = _io.BytesIO(payload)
                records = [read_datum(block, schema, names) for _ in range(count)]
            except (EOFError, struct.error) as e:
                raise CorruptBlockError(
                    path, index, offset, f"unexpected end of avro data ({e})"
                ) from e
            except zlib.error as e:
                raise CorruptBlockError(
                    path, index, offset, f"deflate payload corrupt ({e})"
                ) from e
            except (ValueError, KeyError, IndexError, TypeError) as e:
                raise CorruptBlockError(
                    path, index, offset, f"datum decode failed ({e})"
                ) from e
            if f.read(16) != sync:
                raise CorruptBlockError(path, index, offset, "sync marker mismatch")
            return records

        while True:
            offset = f.tell()
            try:
                records = resilience.call_with_retry(
                    lambda: read_block(offset, block_index),
                    policy,
                    describe=f"read {path} block {block_index}",
                    on_retry=lambda a, e, d: logger.warning(
                        "retrying %s block %d (attempt %d): %s", path, block_index, a + 2, e
                    ),
                )
            except CorruptBlockError as err:
                if on_corrupt != "skip" or skipped >= skip_budget:
                    raise
                skipped += 1
                logger.warning(
                    "skipping corrupt block (%d/%d of skip budget): %s",
                    skipped, skip_budget, err,
                )
                next_off = _resync(f, sync, offset + 1)
                if next_off is None:
                    return  # no later sync marker: rest of the file is gone
                f.seek(next_off)
                block_index += 1
                continue
            if records is None:
                return
            yield from records
            block_index += 1


def list_part_files(path: str) -> list:
    """Part-file discovery shared by read_directory and the native fast
    path (io/avro_data._native_columns) — one definition so the two ingest
    paths can never read different file sets."""
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, name)
        for name in sorted(os.listdir(path))
        if name.endswith(".avro")
    ]


def read_directory(
    path: str,
    on_corrupt: Optional[str] = None,
    skip_budget: Optional[int] = None,
) -> Iterator[Any]:
    """Read all part files of an avro output directory (part-*.avro).
    ``on_corrupt``/``skip_budget`` apply per part file (read_container)."""
    for f in list_part_files(path):
        yield from read_container(f, on_corrupt=on_corrupt, skip_budget=skip_budget)
