"""Content-addressed on-disk cache for built ingest tensors.

The Spark-perf study (PAPERS.md, arXiv:1612.01437) identifies
serialization/shuffle of reusable intermediates as the dominant per-pass
cost GLMix-style workloads pay — which is exactly what re-running
Avro decode -> entity grouping -> padded-tensor assembly costs this port on
every run, epoch, and warm-started grid combo over unchanged inputs. This
module caches the BUILT tensors, keyed by content:

  key = SHA-256( source file stats (path, size, mtime_ns)
               + canonical JSON of the ingest config
               + cache format version )

so any change to the inputs OR the ingest configuration is a miss (no
invalidation protocol — a stale entry is simply never addressed again).

Two entry shapes:

  * array entries (:meth:`TensorCache.put` / :meth:`TensorCache.get`) —
    named ndarrays stored as individual ``.npy`` files (REAL mmap on read:
    ``np.load`` ignores ``mmap_mode`` inside ``.npz`` zips) plus a
    ``meta.json`` manifest. What ``data/game.py`` ingest consults.
  * directory entries (:meth:`TensorCache.get_dir` /
    :meth:`TensorCache.build_dir`) — an arbitrary directory a builder
    callback populates (the streaming-RE entity-block layout of
    ``write_re_entity_blocks``).

Both commit atomically: the entry is assembled in a same-filesystem temp
directory and ``os.replace``d into place, so a crash mid-write leaves no
half-entry a later run could hit. All filesystem touches go through the
resilience retry machinery (PR 1) and carry the fault sites ``io.cache_read``
/ ``io.cache_write`` so the chaos suite covers them. A cache READ that
stays broken after retries degrades to a miss (rebuild from source —
a corrupt cache must never fail a training run); a cache WRITE that stays
broken raises :class:`photon_ml_tpu.resilience.RetryError` to the caller,
who may continue uncached (the CLI drivers log and do exactly that).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from photon_ml_tpu.resilience import RetryError, RetryPolicy, call_with_retry, faults

__all__ = [
    "CACHE_FORMAT",
    "CacheStats",
    "TensorCache",
    "cache_stats",
    "content_key",
    "file_stat_token",
    "process_shard_scope",
]

CACHE_FORMAT = 1
_META = "meta.json"


class CacheStats:
    """Process-wide tensor-cache effectiveness counters (the cache analogue
    of ``compile_stats`` / ``solve_stats``): every :class:`TensorCache`
    instance reports here, and the CLI drivers log :meth:`summary` next to
    the compile/solve summaries — before this registry, whether the cache
    actually saved work was invisible outside ad-hoc HIT log lines.

    ``bytes_reused`` counts the on-disk bytes a hit handed back instead of
    rebuilding (array entries: the served ``.npy`` payloads; directory
    entries: the committed entry tree). ``broken`` counts entries that
    degraded to a miss after surviving retries (swept + rebuilt)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.hits = 0
            self.misses = 0
            self.writes = 0
            self.invalidations = 0
            self.broken = 0
            self.bytes_reused = 0
            self.bytes_written = 0

    def record_hit(self, nbytes: int = 0) -> None:
        with self._lock:
            self.hits += 1
            self.bytes_reused += int(nbytes)

    def record_miss(self) -> None:
        with self._lock:
            self.misses += 1

    def record_broken(self) -> None:
        with self._lock:
            self.broken += 1

    def record_write(self, nbytes: int = 0) -> None:
        with self._lock:
            self.writes += 1
            self.bytes_written += int(nbytes)

    def record_invalidation(self) -> None:
        with self._lock:
            self.invalidations += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "writes": self.writes,
                "invalidations": self.invalidations,
                "broken": self.broken,
                "bytes_reused": self.bytes_reused,
                "bytes_written": self.bytes_written,
            }

    def summary(self) -> str:
        s = self.snapshot()
        total = s["hits"] + s["misses"]
        rate = (100.0 * s["hits"] / total) if total else 0.0
        return (
            f"tensor cache: {s['hits']} hits / {s['misses']} misses "
            f"({rate:.0f}% hit rate), {s['writes']} writes, "
            f"{s['invalidations']} invalidations, {s['broken']} broken "
            f"entries, {s['bytes_reused']}B reused / "
            f"{s['bytes_written']}B written"
        )


#: THE process-wide registry (like ``compile_stats``): every TensorCache
#: reports here unless constructed with an explicit ``stats=``.
cache_stats = CacheStats()


def _tree_bytes(path: str) -> int:
    """Total file bytes under ``path`` (best effort — telemetry only)."""
    total = 0
    try:
        for root, _, files in os.walk(path):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
    except OSError:
        pass
    return total


def file_stat_token(paths: Iterable[str]) -> list:
    """(path, size, mtime_ns) per source file — the identity of the inputs.
    Stats are fetched up front so the key describes the files the build is
    ABOUT to read; a file modified mid-build yields tensors addressed by the
    old stats, and the next run (seeing new stats) rebuilds."""
    out = []
    for p in sorted(paths):
        st = os.stat(p)
        out.append([os.path.abspath(p), int(st.st_size), int(st.st_mtime_ns)])
    return out


def _canonical(config: Dict) -> str:
    return json.dumps(config, sort_keys=True, separators=(",", ":"), default=str)


def content_key(sources: Iterable[str], config: Dict,
                shard_scope: Optional[str] = None) -> str:
    """SHA-256 content address of (source file stats, ingest config[,
    shard scope]). ``shard_scope=None`` hashes exactly as before the scope
    existed, so unscoped caches keep their warm entries."""
    h = hashlib.sha256()
    h.update(f"format={CACHE_FORMAT}\n".encode())
    h.update(_canonical(file_stat_token(sources)).encode())
    h.update(b"\n")
    h.update(_canonical(config).encode())
    if shard_scope is not None:
        h.update(b"\nshard_scope=")
        h.update(str(shard_scope).encode())
    return h.hexdigest()


def process_shard_scope(process_index: int, num_processes: int,
                        spec: Optional[str] = None) -> str:
    """Canonical shard-scope token for per-host cache entries: process
    coordinates plus an optional shard spec (e.g. the owned-block set).
    A topology change (2 hosts -> 4) changes every host's token, so
    re-sharded runs rebuild instead of cross-reading stale layouts."""
    base = f"process={process_index}/{num_processes}"
    return base if spec is None else f"{base};{spec}"


@dataclasses.dataclass
class CacheEntry:
    """A hit: mmap-backed arrays + the meta dict stored alongside them."""

    arrays: Dict[str, np.ndarray]
    meta: Dict


class TensorCache:
    """Content-addressed tensor cache rooted at ``root`` (see module doc).

    ``policy=None`` (the default) resolves the retry policy at CALL time
    from the installed process-wide resilience config — so the drivers'
    ``--io-retries`` / ``--io-retry-base-delay`` flags govern cache I/O
    exactly like every other filesystem path (avro, index maps,
    checkpoints). Pass an explicit :class:`RetryPolicy` to override.

    ``shard_scope`` (e.g. :func:`process_shard_scope`) is folded into every
    key this instance addresses: per-host builds on a SHARED filesystem
    (the multihost streaming entity blocks, parallel/perhost_streaming.py)
    produce per-host-different tensors from the same sources + config, so
    without the scope token host A could serve host B's blocks — a silent
    cross-read, not just a collision. ``None`` (the default) leaves keys
    byte-identical to pre-scope caches, so existing entries stay warm.
    """

    def __init__(self, root: str, policy: Optional[RetryPolicy] = None,
                 shard_scope: Optional[str] = None,
                 stats: Optional[CacheStats] = None):
        self.root = root
        self.policy = policy
        self.shard_scope = shard_scope
        self.stats = stats if stats is not None else cache_stats
        os.makedirs(root, exist_ok=True)

    @property
    def _policy(self) -> RetryPolicy:
        if self.policy is not None:
            return self.policy
        from photon_ml_tpu import resilience

        return resilience.current_config().io_policy

    # -- addressing ---------------------------------------------------------
    def key_for(self, sources: Iterable[str], config: Dict) -> str:
        return content_key(sources, config, shard_scope=self.shard_scope)

    def entry_dir(self, key: str) -> str:
        return os.path.join(self.root, key[:2], key)

    def has(self, key: str) -> bool:
        return os.path.exists(os.path.join(self.entry_dir(key), _META))

    # -- array entries -------------------------------------------------------
    def get(self, key: str) -> Optional[CacheEntry]:
        """The entry at ``key``, arrays mmap-backed, or None on miss.
        A broken entry (injected/real read failure that survives retries,
        truncated file, manifest mismatch) degrades to a miss and the debris
        is swept so the rebuild can re-commit."""
        entry = self.entry_dir(key)
        meta_path = os.path.join(entry, _META)
        if not os.path.exists(meta_path):
            self.stats.record_miss()
            return None
        try:
            def read():
                faults.inject("io.cache_read", key=key, entry=entry)
                with open(meta_path) as f:
                    meta = json.load(f)
                arrays = {}
                for name in meta.get("arrays", []):
                    arrays[name] = np.load(
                        os.path.join(entry, f"{name}.npy"), mmap_mode="r"
                    )
                return CacheEntry(arrays=arrays, meta=meta.get("meta", {}))

            hit = call_with_retry(
                read, self._policy, describe=f"tensor-cache read {key[:12]}"
            )
            self.stats.record_hit(sum(a.nbytes for a in hit.arrays.values()))
            return hit
        except (RetryError, OSError, ValueError, json.JSONDecodeError):
            # a cache must never fail the run it exists to speed up: sweep
            # the broken entry (best effort) and report a miss
            shutil.rmtree(entry, ignore_errors=True)
            self.stats.record_broken()
            self.stats.record_miss()
            return None

    def put(self, key: str, arrays: Dict[str, np.ndarray], meta: Optional[Dict] = None) -> str:
        """Commit named arrays + meta under ``key`` atomically; returns the
        entry directory. Raises :class:`RetryError` if the write stays broken
        after retries (callers continue uncached)."""

        def build(tmp: str) -> None:
            manifest = {"format": CACHE_FORMAT, "key": key,
                        "arrays": sorted(arrays), "meta": meta or {}}
            for name, arr in arrays.items():
                if "/" in name or name.startswith("."):
                    raise ValueError(f"bad cache array name {name!r}")
                np.save(os.path.join(tmp, f"{name}.npy"), np.asarray(arr))
            with open(os.path.join(tmp, _META), "w") as f:
                json.dump(manifest, f)

        return self.build_dir(key, build)

    # -- directory entries ---------------------------------------------------
    def get_dir(self, key: str) -> Optional[str]:
        """The committed directory entry for ``key``, or None. The injected
        ``io.cache_read`` fault fires here too (the streaming-RE block reuse
        path); a read fault that survives retries degrades to a miss."""
        entry = self.entry_dir(key)
        if not os.path.exists(os.path.join(entry, _META)):
            self.stats.record_miss()
            return None
        try:
            def probe():
                faults.inject("io.cache_read", key=key, entry=entry)
                with open(os.path.join(entry, _META)) as f:
                    json.load(f)
                return entry

            out = call_with_retry(
                probe, self._policy, describe=f"tensor-cache probe {key[:12]}"
            )
            self.stats.record_hit(_tree_bytes(entry))
            return out
        except (RetryError, OSError, json.JSONDecodeError):
            shutil.rmtree(entry, ignore_errors=True)
            self.stats.record_broken()
            self.stats.record_miss()
            return None

    def invalidate(self, key: str) -> bool:
        """Drop the committed entry at ``key`` (cache hygiene: the delta
        retrain loop invalidates prior-run keys it has superseded so the
        store stays bounded instead of accreting one dead whole-set entry
        per day). Returns True when an entry was removed. A removal that
        stays broken after retries is LOGGED as a no-op, never raised — a
        failed invalidation leaves a never-again-addressed entry behind,
        which is wasteful but harmless (content addressing means it can
        never serve stale data)."""
        entry = self.entry_dir(key)
        if not os.path.exists(os.path.join(entry, _META)):
            return False
        try:
            def drop():
                faults.inject("io.cache_invalidate", key=key, entry=entry)
                shutil.rmtree(entry)

            call_with_retry(
                drop, self._policy,
                describe=f"tensor-cache invalidate {key[:12]}",
            )
            self.stats.record_invalidation()
            return True
        except (RetryError, OSError):
            return False

    def build_dir(self, key: str, build: Callable[[str], None]) -> str:
        """Populate a fresh entry directory through ``build(tmp_dir)`` and
        commit it atomically under ``key``; returns the final directory.
        ``build`` writes ordinary files into ``tmp_dir`` — nothing is live
        until the single ``os.replace``. Lost-race commits (another process
        finished the same key first) keep the winner and discard ours."""
        entry = self.entry_dir(key)
        os.makedirs(os.path.dirname(entry), exist_ok=True)
        tmp = tempfile.mkdtemp(
            prefix=f".tmp-{key[:12]}-", dir=os.path.dirname(entry)
        )
        try:
            def write():
                faults.inject("io.cache_write", key=key, entry=entry)
                build(tmp)
                if not os.path.exists(os.path.join(tmp, _META)):
                    with open(os.path.join(tmp, _META), "w") as f:
                        json.dump({"format": CACHE_FORMAT, "key": key}, f)

            call_with_retry(
                write, self._policy, describe=f"tensor-cache write {key[:12]}"
            )
            try:
                os.replace(tmp, entry)
            except OSError:
                if os.path.exists(os.path.join(entry, _META)):
                    pass  # lost the commit race; the winner's entry serves
                else:
                    raise
            self.stats.record_write(_tree_bytes(entry))
            return entry
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


def index_map_digest(index_map) -> str:
    """Stable digest of an index map's content for cache keys (the feature
    index assignment changes the built tensors even when input files do not
    — e.g. an --offheap-indexmap-dir swap). Works against the shared index
    protocol (``__len__`` + ``get_feature_name``), so the in-memory
    :class:`~photon_ml_tpu.io.index_map.IndexMap` and the off-heap
    :class:`~photon_ml_tpu.io.offheap.OffHeapIndexMap` both digest; the
    in-memory list is used directly when present (no per-index call)."""
    h = hashlib.sha256()
    names = getattr(index_map, "index_to_name", None)
    if names is None:
        names = (index_map.get_feature_name(i) for i in range(len(index_map)))
    for name in names:
        h.update((name or "").encode())
        h.update(b"\x00")
    return h.hexdigest()
