"""Deterministic, seedable fault injection for chaos testing.

The reference inherits fault tolerance from Spark lineage recompute
(SURVEY/PAPER §5.4); the TPU port replaces that with explicit resilience
machinery (retry/backoff I/O, corrupt-shard skip, divergence guards). This
module makes those paths *testable in plain pytest*: production code calls
:func:`inject` / :func:`corrupt` at named sites, which are no-ops unless a
:class:`FaultPlan` is active — installed either with the :func:`fault_scope`
context manager or through the ``PHOTON_FAULTS`` environment variable.

Named sites wired through the stack are registered centrally in
:data:`photon_ml_tpu.resilience.sites.FAULT_SITES` (re-exported here as
:data:`KNOWN_SITES`); the ``fault-sites`` rule of ``tools/photon_lint``
statically enforces that every production call site uses a registered
name and that no registry entry goes stale. One site is special:
``preempt.signal`` FLAGS a preemption request instead of raising (see
:func:`flag`), simulating a SIGTERM at a drain boundary.

``PHOTON_FAULTS`` grammar (';'-separated site specs, ','-separated options)::

    PHOTON_FAULTS="io.read_block:rate=0.3,seed=7;optim.step:at=3,kind=nan"

Options: ``rate`` (per-hit probability), ``at`` (fire on exactly the N-th
hit, 1-based), ``times`` (max fires, default 1 for ``at`` else unlimited),
``kind`` (``io`` -> retryable :class:`InjectedIOError`, ``fatal`` ->
:class:`InjectedFatalError`, ``nan`` -> corrupt arrays at ``corrupt`` sites),
``seed`` (per-site RNG seed). Every draw comes from a per-site
``random.Random`` so a given plan produces the same fault sequence on every
run — chaos tests are reproducible.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Any, Dict, List, Optional, Tuple

from photon_ml_tpu.resilience.sites import FAULT_SITES as KNOWN_SITES

__all__ = [
    "KNOWN_SITES",
    "InjectedIOError",
    "InjectedFatalError",
    "FaultSpec",
    "FaultPlan",
    "fault_scope",
    "install",
    "clear",
    "active_plan",
    "inject",
    "corrupt",
    "flag",
    "parse_fault_env",
]


class InjectedIOError(OSError):
    """A retryable injected I/O failure (an OSError, so the default retry
    policies treat it exactly like a real transient read error)."""


class InjectedFatalError(RuntimeError):
    """A non-retryable injected failure (process-kill analogue)."""


_KINDS = ("io", "fatal", "nan")


@dataclasses.dataclass
class FaultSpec:
    """One site's fault behavior."""

    site: str
    rate: float = 0.0
    at: Optional[int] = None  # fire on exactly the at-th hit (1-based)
    times: Optional[int] = None  # max fires; None = unlimited (1 when `at` set)
    kind: str = "io"
    seed: int = 0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"fault kind {self.kind!r} for site {self.site!r} not in {_KINDS}"
            )
        if self.at is not None and self.at < 1:
            raise ValueError(f"fault 'at' must be >= 1 (1-based hit count), got {self.at}")
        if not (self.at is not None or self.rate > 0.0):
            raise ValueError(f"fault spec for {self.site!r} needs rate>0 or at=N")
        if self.times is None:
            self.times = 1 if self.at is not None else None


class FaultPlan:
    """Active fault registry: per-site hit counters + seeded RNG streams."""

    def __init__(self, specs: List[FaultSpec]):
        self._specs: Dict[str, FaultSpec] = {}
        for s in specs:
            if s.site in self._specs:
                raise ValueError(f"duplicate fault spec for site {s.site!r}")
            self._specs[s.site] = s
        self._hits: Dict[str, int] = {s: 0 for s in self._specs}
        self._fires: Dict[str, int] = {s: 0 for s in self._specs}
        self._rngs: Dict[str, random.Random] = {
            s: random.Random(spec.seed) for s, spec in self._specs.items()
        }
        self._lock = threading.Lock()
        self.events: List[Tuple[str, Dict[str, Any]]] = []

    def spec(self, site: str) -> Optional[FaultSpec]:
        return self._specs.get(site)

    def hits(self, site: str) -> int:
        return self._hits.get(site, 0)

    def fire_count(self, site: str) -> int:
        return self._fires.get(site, 0)

    def should_fire(self, site: str, **context: Any) -> Optional[FaultSpec]:
        """Count a hit at ``site``; return the spec when this hit faults."""
        spec = self._specs.get(site)
        if spec is None:
            return None
        with self._lock:
            self._hits[site] += 1
            hit = self._hits[site]
            if spec.times is not None and self._fires[site] >= spec.times:
                return None
            if spec.at is not None:
                fire = hit == spec.at
            else:
                fire = self._rngs[site].random() < spec.rate
            if not fire:
                return None
            self._fires[site] += 1
            self.events.append((site, dict(context, hit=hit)))
            return spec


# ---------------------------------------------------------------------------
# active-plan management: explicit install/scope wins over PHOTON_FAULTS
# ---------------------------------------------------------------------------

FAULT_ENV = "PHOTON_FAULTS"

_installed: Optional[FaultPlan] = None
_env_cache: Tuple[Optional[str], Optional[FaultPlan]] = (None, None)


def parse_fault_env(value: str) -> FaultPlan:
    """Parse the ``PHOTON_FAULTS`` grammar into a plan."""
    specs: List[FaultSpec] = []
    for chunk in value.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        site, _, opts = chunk.partition(":")
        kwargs: Dict[str, Any] = {}
        for opt in opts.split(","):
            opt = opt.strip()
            if not opt:
                continue
            key, _, val = opt.partition("=")
            key = key.strip()
            val = val.strip()
            if key in ("rate",):
                kwargs[key] = float(val)
            elif key in ("at", "times", "seed"):
                kwargs[key] = int(val)
            elif key == "kind":
                kwargs[key] = val
            else:
                raise ValueError(
                    f"unknown {FAULT_ENV} option {key!r} in {chunk!r} "
                    "(expected rate/at/times/kind/seed)"
                )
        specs.append(FaultSpec(site=site.strip(), **kwargs))
    return FaultPlan(specs)


def install(plan: Optional[FaultPlan]) -> None:
    """Install (or with None, remove) the process-wide fault plan."""
    global _installed
    _installed = plan


def clear() -> None:
    install(None)


def active_plan() -> Optional[FaultPlan]:
    """The explicitly installed plan, else a plan parsed from PHOTON_FAULTS
    (cached per env value), else None."""
    global _env_cache
    if _installed is not None:
        return _installed
    env = os.environ.get(FAULT_ENV)
    if not env:
        return None
    if _env_cache[0] != env:
        _env_cache = (env, parse_fault_env(env))
    return _env_cache[1]


class fault_scope:
    """``with fault_scope(plan):`` — install for the duration of the block."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        global _installed
        self._prev = _installed
        _installed = self.plan
        return self.plan

    def __exit__(self, *exc) -> None:
        install(self._prev)
        return None


# ---------------------------------------------------------------------------
# injection points called from production code
# ---------------------------------------------------------------------------


def _raise_fault(spec: FaultSpec, site: str, context: Dict[str, Any]) -> None:
    detail = ", ".join(f"{k}={v}" for k, v in sorted(context.items()))
    msg = f"injected {spec.kind} fault at {site}" + (f" ({detail})" if detail else "")
    if spec.kind == "fatal":
        raise InjectedFatalError(msg)
    raise InjectedIOError(msg)


def inject(site: str, **context: Any) -> None:
    """Raise an injected error at ``site`` if the active plan says so.

    ``kind="io"`` raises :class:`InjectedIOError` (retryable OSError);
    ``kind="fatal"`` raises :class:`InjectedFatalError`. A ``nan`` spec at a
    raising site is ignored (NaNs are injected via :func:`corrupt`).
    """
    plan = active_plan()
    if plan is None:
        return
    spec = plan.should_fire(site, **context)
    if spec is None or spec.kind == "nan":
        return
    _raise_fault(spec, site, context)


def flag(site: str, **context: Any) -> bool:
    """Count a hit at ``site``; return True when the plan fires — WITHOUT
    raising, whatever the spec's kind. For sites where a fault is a signal
    to act on (``preempt.signal``), not an error to propagate."""
    plan = active_plan()
    if plan is None:
        return False
    return plan.should_fire(site, **context) is not None


def corrupt(site: str, tree: Any, **context: Any) -> Any:
    """Return ``tree`` with NaNs poured into its first array leaf if a
    ``kind="nan"`` fault fires at ``site``; otherwise ``tree`` unchanged.
    Non-nan kinds raise, exactly like :func:`inject`."""
    plan = active_plan()
    if plan is None:
        return tree
    spec = plan.should_fire(site, **context)
    if spec is None:
        return tree
    if spec.kind != "nan":
        _raise_fault(spec, site, context)

    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    first = jnp.asarray(leaves[0])
    leaves = [jnp.full_like(first, jnp.nan)] + list(leaves[1:])
    return jax.tree_util.tree_unflatten(treedef, leaves)
