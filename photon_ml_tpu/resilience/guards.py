"""Divergence guards: non-finite detection + coordinate rollback.

A single NaN produced by one coordinate's solve (overflowed exp, poisoned
shard, injected fault) propagates through the shared score vectors and
silently destroys every later update — on a multi-hour run the damage is
unrecoverable by the time the objective is inspected. The guard checks each
coordinate update's parameters and scores for non-finite values *before*
they enter the shared state, and either rolls the coordinate back to its
last good state (descent continues with the other coordinates) or marks the
cycle skipped. Outcomes are recorded as :class:`GuardEvent` rows surfaced on
``CoordinateDescentResult.guard_events``.

The solver kernels (optim/lbfgs.py, optim/tron.py) carry their own in-kernel
guard — a non-finite trial step is rejected branch-free inside the jitted
while_loop, like a failed line search — so the host-side guard here is the
backstop for divergence the kernels cannot see (e.g. a corrupted warm start
or a poisoned residual offset).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

__all__ = ["GuardEvent", "DivergenceGuard", "tree_all_finite"]


def tree_all_finite(tree: Any) -> bool:
    """True iff every array leaf of ``tree`` is fully finite. Blocks on the
    device values (one small scalar transfer per call)."""
    import jax
    import jax.numpy as jnp

    ok = True
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        ok = ok & jnp.all(jnp.isfinite(arr))
    if ok is True:
        return True
    return bool(ok)


@dataclasses.dataclass(frozen=True)
class GuardEvent:
    """One guarded incident during coordinate descent."""

    coordinate: str
    step: int  # global update counter (iteration * num_coordinates + index)
    action: str  # "rollback" | "skip_cycle"
    detail: str = ""


class DivergenceGuard:
    """Per-update non-finite gate for coordinate descent.

    ``mode="rollback"`` (default) keeps the coordinate's last good
    parameters and scores and lets descent continue; ``mode="skip_cycle"``
    additionally asks the caller to skip the remainder of the current cycle
    (useful when one divergence suggests the whole iteration is suspect).
    ``max_events`` bounds how many incidents are tolerated before the guard
    raises — unbounded silent rollback could mask a systematically broken
    objective.
    """

    MODES = ("rollback", "skip_cycle")

    def __init__(self, mode: str = "rollback", max_events: int = 8):
        if mode not in self.MODES:
            raise ValueError(f"guard mode {mode!r} not in {self.MODES}")
        self.mode = mode
        self.max_events = max_events
        self.events: List[GuardEvent] = []

    def filter_update(
        self,
        coordinate: str,
        step: int,
        new_params: Any,
        new_score: Any,
        prev_params: Any,
        prev_score: Any,
    ) -> Tuple[Any, Any, bool]:
        """Gate one coordinate update.

        Returns ``(params, score, ok)``: the proposed state when finite,
        else the previous (last good) state with ``ok=False`` and the event
        recorded. Raises :class:`FloatingPointError` when ``max_events`` is
        exhausted.
        """
        # one combined check = one device scalar + one host transfer (the
        # per-update cost the CD docstring quotes); checking the two trees
        # separately would double the blocking round-trips
        if tree_all_finite((new_params, new_score)):
            return new_params, new_score, True
        action = "skip_cycle" if self.mode == "skip_cycle" else "rollback"
        event = GuardEvent(
            coordinate=coordinate,
            step=step,
            action=action,
            detail="non-finite parameters or scores; restored last good state",
        )
        self.events.append(event)
        if len(self.events) > self.max_events:
            raise FloatingPointError(
                f"divergence guard exhausted: {len(self.events)} non-finite "
                f"coordinate updates (limit {self.max_events}); last at "
                f"coordinate {coordinate!r} step {step}"
            )
        return prev_params, prev_score, False
