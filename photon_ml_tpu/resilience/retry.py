"""Retry policies: exponential backoff + deterministic jitter + deadline.

The reference leans on Spark's task re-execution for transient I/O failures
(a failed partition read is simply recomputed from lineage); the TPU port
reads Avro shards, index maps, and checkpoints directly from the filesystem,
so transient failures must be retried in-process. One policy object serves
every I/O layer:

  * Avro part-file block reads (io/avro.py)
  * index-map / off-heap store loads (io/index_map.py, io/offheap.py)
  * checkpoint save/restore (checkpoint.py)
  * multihost barrier entry (parallel/multihost.py)

Delays follow ``base_delay * multiplier**attempt`` capped at ``max_delay``,
with proportional jitter drawn from a seeded RNG (deterministic in tests),
and an optional wall-clock ``deadline`` that bounds total retry time.

Environment overrides (read by :func:`RetryPolicy.io_default`):
``PHOTON_IO_RETRIES``, ``PHOTON_IO_RETRY_BASE_DELAY``,
``PHOTON_IO_RETRY_MAX_DELAY``, ``PHOTON_IO_RETRY_DEADLINE``.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "RetryError", "call_with_retry", "retryable"]


class RetryError(OSError):
    """All attempts failed; chains the last underlying error via __cause__."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry configuration (shareable across call sites)."""

    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1  # +/- fraction of the computed delay
    deadline: Optional[float] = None  # total seconds across all attempts
    retryable: Tuple[Type[BaseException], ...] = (OSError,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.multiplier < 1.0:
            raise ValueError(f"multiplier must be >= 1, got {self.multiplier}")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (0-based failed attempt)."""
        d = min(self.base_delay * (self.multiplier ** attempt), self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)

    @staticmethod
    def io_default() -> "RetryPolicy":
        """The default filesystem policy, with env overrides applied."""
        return RetryPolicy(
            max_attempts=int(_env_float("PHOTON_IO_RETRIES", 4)),
            base_delay=_env_float("PHOTON_IO_RETRY_BASE_DELAY", 0.05),
            max_delay=_env_float("PHOTON_IO_RETRY_MAX_DELAY", 2.0),
            deadline=(
                _env_float("PHOTON_IO_RETRY_DEADLINE", 0.0) or None
            ),
        )

    @staticmethod
    def no_retry() -> "RetryPolicy":
        return RetryPolicy(max_attempts=1)


def call_with_retry(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Call ``fn`` under ``policy``; raise :class:`RetryError` when exhausted.

    Only exceptions in ``policy.retryable`` are retried — anything else
    (e.g. a corrupt-data ValueError, where retrying cannot help) propagates
    immediately. ``on_retry(attempt, error, delay)`` observes each retry
    (used for warning logs). ``sleep``/``rng``/``clock`` are injectable so
    tests run instantly and deterministically.
    """
    if policy is None:
        policy = RetryPolicy.io_default()
    if rng is None:
        rng = random.Random(0)
    start = clock()
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except policy.retryable as e:
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            delay = policy.delay_for(attempt, rng)
            if policy.deadline is not None and (
                clock() - start + delay > policy.deadline
            ):
                break
            if on_retry is not None:
                on_retry(attempt, e, delay)
            if delay > 0:
                sleep(delay)
    what = describe or getattr(fn, "__name__", "operation")
    raise RetryError(
        f"{what} failed after {policy.max_attempts} attempt(s): {last}"
    ) from last


def retryable(
    policy: Optional[RetryPolicy] = None, describe: str = ""
) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
    """Decorator form of :func:`call_with_retry` for zero-glue wrapping."""

    def wrap(fn: Callable[..., Any]) -> Callable[..., Any]:
        import functools

        @functools.wraps(fn)
        def inner(*args: Any, **kwargs: Any) -> Any:
            return call_with_retry(
                lambda: fn(*args, **kwargs),
                policy,
                describe or fn.__qualname__,
            )

        return inner

    return wrap
