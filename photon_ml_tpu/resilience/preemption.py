"""Cooperative preemption: signal -> flag -> drain-to-boundary -> resume.

On TPU pods the dominant failure mode is not a bad disk block but the
scheduler taking the machine away: a SIGTERM lands, the process has seconds
to make its work durable, and a fresh process later restarts from whatever
was committed. The reference never faced this (Spark re-runs lost tasks
from lineage); the TPU port turns preemption into a *scheduled event*:

  1. **flag** — :func:`install_signal_handlers` (or the driver-facing
     :func:`signal_scope`) converts SIGTERM/SIGINT into a process-wide
     preemption flag. Nothing is interrupted mid-kernel; the flag is a
     request, not an abort.
  2. **poll** — long-running loops call :func:`check` at their safe points:
     coordinate descent between updates (site ``"cycle"``), the streaming
     random-effect block loop between blocks (``"block"``), and the
     convergence-compacted solver between chunks (``"chunk"``). A poll is a
     dict lookup + an Event check — free at loop granularity.
  3. **drain + raise** — a loop that observes the flag finishes its current
     unit, writes an emergency checkpoint (coordinate descent owns that;
     inner loops attach their in-flight state to :class:`Preempted` as a
     ``partial`` payload so the checkpoint can resume INSIDE a coordinate),
     and unwinds with :class:`Preempted`.
  4. **exit / restart** — drivers convert an unhandled :class:`Preempted`
     into :data:`PREEMPT_EXIT_CODE` (75, EX_TEMPFAIL — distinct from crash
     exit codes so supervisors can tell "rescheduled" from "broken"), or
     relaunch in-process via :func:`run_with_restarts` (``--max-restarts``).
     ``tools/run_supervised.py`` is the cross-process supervisor.

Testability: ``PHOTON_PREEMPT_AT="block:2"`` requests preemption at the
2nd poll of the ``block`` site (';'-separated specs; each fires once), and
a ``preempt.signal`` spec in ``PHOTON_FAULTS`` flags the same request
through the seeded fault registry — chaos tests deliver deterministic
"SIGTERMs" without touching process signals.
"""

from __future__ import annotations

import logging
import os
import signal as _signal
import threading
from typing import Any, Callable, Dict, Optional, Tuple, TypeVar

from photon_ml_tpu.resilience import faults
from photon_ml_tpu.resilience.sites import PREEMPT_SITES

__all__ = [
    "PREEMPT_ENV",
    "PREEMPT_EXIT_CODE",
    "Preempted",
    "check",
    "clear",
    "install_plan",
    "install_signal_handlers",
    "parse_preempt_env",
    "reason",
    "request",
    "requested",
    "reset",
    "run_with_restarts",
    "signal_scope",
]

logger = logging.getLogger(__name__)

#: Distinct process exit code for a cooperative preemption exit (75 =
#: EX_TEMPFAIL: "try again later" — exactly the supervisor contract).
PREEMPT_EXIT_CODE = 75

PREEMPT_ENV = "PHOTON_PREEMPT_AT"

#: Poll sites wired through the stack (the safe drain boundaries) —
#: registered centrally in photon_ml_tpu.resilience.sites and enforced
#: by the fault-sites photon_lint rule.
SITES = PREEMPT_SITES


class Preempted(RuntimeError):
    """Raised at a safe boundary after a preemption request.

    ``partial`` carries the in-flight sub-coordinate state (a dict with
    ``meta`` — JSON-able bookkeeping — and ``arrays`` — name -> ndarray)
    that coordinate descent folds into the emergency checkpoint so a
    restart resumes inside the interrupted coordinate, not just between
    steps. ``checkpoint_path`` is set once the emergency checkpoint landed.
    """

    def __init__(
        self,
        message: str,
        site: str = "",
        partial: Optional[Dict[str, Any]] = None,
        checkpoint_path: Optional[str] = None,
    ):
        super().__init__(message)
        self.site = site
        self.partial = partial
        self.checkpoint_path = checkpoint_path


# ---------------------------------------------------------------------------
# the process-wide flag
# ---------------------------------------------------------------------------

_flag = threading.Event()
_reason: Optional[str] = None
_lock = threading.Lock()

# poll bookkeeping for PHOTON_PREEMPT_AT / install_plan: per-site poll
# counters survive clear() so an at=N spec fires exactly once per process —
# an in-process supervised restart must not be re-preempted by the same spec
_counts: Dict[str, int] = {}
_installed_plan: Optional[Dict[str, int]] = None
_env_cache: Tuple[Optional[str], Optional[Dict[str, int]]] = (None, None)


def request(why: str = "preemption requested") -> None:
    """Set the preemption flag (signal-handler-safe: one Event.set)."""
    global _reason
    with _lock:
        if _reason is None:
            _reason = why
    _flag.set()


def requested() -> bool:
    return _flag.is_set()


def reason() -> Optional[str]:
    return _reason


def clear() -> None:
    """Drop the flag (the restart supervisor calls this between attempts).
    Poll counters are kept: an ``at=N`` spec fires once per process."""
    global _reason
    _flag.clear()
    with _lock:
        _reason = None


def reset() -> None:
    """Full reset incl. poll counters and the installed plan (tests)."""
    global _installed_plan, _env_cache
    clear()
    with _lock:
        _counts.clear()
    _installed_plan = None
    _env_cache = (None, None)


def parse_preempt_env(value: str) -> Dict[str, int]:
    """``"site:N[;site2:M]"`` -> {site: N} (N = 1-based poll count; a bare
    ``site`` means its first poll)."""
    plan: Dict[str, int] = {}
    for chunk in value.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        site, _, at = chunk.partition(":")
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown {PREEMPT_ENV} site {site!r} in {chunk!r} "
                f"(expected one of {SITES})"
            )
        try:
            n = int(at) if at.strip() else 1
        except ValueError as e:
            raise ValueError(
                f"bad {PREEMPT_ENV} count in {chunk!r} (want site:N): {e}"
            ) from e
        if n < 1:
            raise ValueError(f"{PREEMPT_ENV} count must be >= 1, got {n}")
        plan[site] = n
    return plan


def install_plan(plan: Optional[Dict[str, int]]) -> None:
    """Install (or with None, remove) an explicit {site: fire-at-poll-N}
    plan; wins over ``PHOTON_PREEMPT_AT``. Resets poll counters."""
    global _installed_plan
    _installed_plan = dict(plan) if plan is not None else None
    with _lock:
        _counts.clear()


def _active_plan() -> Optional[Dict[str, int]]:
    global _env_cache
    if _installed_plan is not None:
        return _installed_plan
    env = os.environ.get(PREEMPT_ENV)
    if not env:
        return None
    if _env_cache[0] != env:
        _env_cache = (env, parse_preempt_env(env))
        with _lock:
            _counts.clear()  # a new spec starts its own poll numbering
    return _env_cache[1]


def check(site: str, **context: Any) -> bool:
    """Poll for preemption at ``site``; True when the loop should drain.

    Counts the poll against the active ``PHOTON_PREEMPT_AT`` plan (the
    N-th poll of a planned site sets the flag, once per process) and gives
    the seeded fault registry its shot via the ``preempt.signal`` site —
    then reports the flag, however it was raised (signal, injection, or an
    explicit :func:`request`).
    """
    plan = _active_plan()
    if plan is not None and site in plan:
        with _lock:
            _counts[site] = _counts.get(site, 0) + 1
            hit = _counts[site]
        if hit == plan[site]:
            request(f"{PREEMPT_ENV} fired at {site} poll {hit}")
    if faults.flag("preempt.signal", poll_site=site, **context):
        request(f"injected preempt.signal at {site}")
    return _flag.is_set()


# ---------------------------------------------------------------------------
# signal handling
# ---------------------------------------------------------------------------

DEFAULT_SIGNALS = (_signal.SIGTERM, _signal.SIGINT)


def install_signal_handlers(signals=DEFAULT_SIGNALS):
    """Route ``signals`` to :func:`request`; returns {signum: previous
    handler} for restoration. Outside the main thread (where Python forbids
    signal registration) this is a logged no-op returning {}."""

    def _handler(signum, frame):
        # async-signal-safe: set the flag, nothing else — the training loop
        # drains at its next safe boundary
        request(f"signal {_signal.Signals(signum).name}")

    prev = {}
    for sig in signals:
        try:
            prev[sig] = _signal.signal(sig, _handler)
        except ValueError:
            # not the main thread (e.g. a driver invoked from a test
            # worker): cooperative preemption still works via check()/
            # request(), only OS signals cannot be routed from here
            logger.warning(
                "cannot install handler for %s outside the main thread; "
                "relying on PHOTON_PREEMPT_AT / explicit request()", sig
            )
    return prev


class signal_scope:
    """``with signal_scope():`` — SIGTERM/SIGINT set the preemption flag
    for the duration; previous handlers restored on exit."""

    def __init__(self, signals=DEFAULT_SIGNALS):
        self._signals = signals
        self._prev = {}

    def __enter__(self) -> "signal_scope":
        self._prev = install_signal_handlers(self._signals)
        return self

    def __exit__(self, *exc) -> None:
        for sig, handler in self._prev.items():
            try:
                _signal.signal(sig, handler)
            except ValueError:
                pass  # thread changed between enter and exit; nothing held
        return None


# ---------------------------------------------------------------------------
# restart supervision (in-process; tools/run_supervised.py is the
# cross-process variant)
# ---------------------------------------------------------------------------

T = TypeVar("T")


def run_with_restarts(
    run_once: Callable[[int], T],
    max_restarts: int,
    on_restart: Optional[Callable[[int, Preempted], None]] = None,
) -> T:
    """Call ``run_once(attempt)``; on :class:`Preempted`, clear the flag and
    relaunch up to ``max_restarts`` times (attempt numbers 0..max_restarts).
    The relaunched attempt resumes from the latest checkpoint through the
    caller's normal restore path — this helper only supervises. The final
    attempt's :class:`Preempted` propagates (the driver turns it into
    :data:`PREEMPT_EXIT_CODE`).
    """
    attempt = 0
    while True:
        try:
            return run_once(attempt)
        except Preempted as e:
            if attempt >= max_restarts:
                raise
            attempt += 1
            if on_restart is not None:
                on_restart(attempt, e)
            # keep the poll counters: the PHOTON_PREEMPT_AT spec that fired
            # must not re-fire and re-kill every restarted attempt
            clear()
