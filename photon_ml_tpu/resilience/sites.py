"""Central registry of resilience site names — THE invariant source.

Every site string handed to the fault-injection machinery
(:func:`photon_ml_tpu.resilience.faults.inject` / ``corrupt`` / ``flag``)
and every preemption poll boundary
(:func:`photon_ml_tpu.resilience.preemption.check`) must be registered
here. The registry is enforced statically by the ``fault-sites`` rule of
``tools/photon_lint`` (tier-1): an unregistered site string at a call site
fails the lint, and so does a registry entry no call site uses — the two
directions together keep this table exactly the set of live fault
surfaces, so chaos plans (``PHOTON_FAULTS`` / ``PHOTON_PREEMPT_AT``) can
be written against it without spelunking the tree.

This module is imported by :mod:`photon_ml_tpu.resilience.faults` and
:mod:`photon_ml_tpu.resilience.preemption` and must stay dependency-free
(no jax, no package imports): the linter parses it with ``ast`` only, and
``bench.py --list-sections``-style device-free tooling may import it.
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = ["FAULT_SITES", "PREEMPT_SITES"]

#: Named fault-injection sites wired through the stack: site -> where it
#: fires. Keys are the exact string literals production code passes to
#: ``faults.inject`` / ``faults.corrupt`` / ``faults.flag``.
FAULT_SITES: Dict[str, str] = {
    "io.read_block": "per Avro container block read (io/avro.py, io/avro_data.py)",
    "io.checkpoint_write": "per checkpoint save attempt (checkpoint.py)",
    "io.index_load": "index-map / off-heap store loads (io/index_map.py, io/offheap.py)",
    "io.cache_read": "tensor-cache entry reads (io/tensor_cache.py)",
    "io.cache_write": "tensor-cache entry commits (io/tensor_cache.py)",
    "io.cache_invalidate": "tensor-cache entry invalidation, delta-retrain cache hygiene (io/tensor_cache.py)",
    "retrain.delta_plan": "delta-retrain prior manifest/model reads; failure degrades to a recorded cold run (retrain/manifest.py, retrain/delta.py)",
    "multihost.barrier": "cross-host sync points (parallel/multihost.py)",
    "multihost.heartbeat": "per-host heartbeat writes (parallel/multihost.py)",
    "multihost.entity_route": "streaming entity-routing exchange (parallel/shuffle.py)",
    "multihost.membership": "elastic fleet-membership reads/commits (parallel/elastic.py)",
    "multihost.replan_barrier": "elastic re-plan barrier entry; a failure that survives retries falls back to supervised relaunch (parallel/elastic.py)",
    "io.block_transfer": "delta block/state file copies during an elastic re-shard; a failed block copy degrades to a recorded cold rebuild (parallel/elastic.py)",
    "multihost.streaming_reduce": "exact cross-host streaming merges: score scatters, FE chunk partials, reg terms (parallel/perhost_streaming.py)",
    "io.perhost_block_write": "per-host streaming entity-block writes (parallel/perhost_streaming.py)",
    "optim.step": "coordinate-descent updates, NaN corruption (algorithm/coordinate_descent.py)",
    "optim.block_skip": "adaptive-schedule skip decision boundary; an injected fault degrades the epoch to visit-everything, never a silent skip (algorithm/streaming_random_effect.py, algorithm/bucketed_random_effect.py)",
    "optim.device_drain": "fused device-loop dispatch gate; an injected fault degrades the solve to the host chunk loop, bitwise (optim/scheduler.py)",
    "preempt.signal": "preemption polls; flags instead of raising (resilience/preemption.py)",
    "serve.dequant": "quantized-store open gate: scale-sidecar/budget validation before a bf16/int8 slab may serve (serve/model_store.py)",
    "serve.route": "fleet router request-routing entry (serve/fleet/router.py)",
    "serve.replica_scatter": "per sub-request dispatch to a slab-owner replica (serve/fleet/router.py)",
    "serve.fleet_swap_barrier": "fleet-wide swap generation barrier, between prepare-all and commit (serve/fleet/swap.py)",
    "serve.fleet_delta_rollout": "delta-retrain fleet rollout entry: export-manifest validation before the generation barrier; a failure aborts to the old generation (serve/fleet/swap.py)",
    "multihost.relaunch_replan": "relaunch-time re-plan of a smaller/larger cohort from plan sidecars; a failure degrades to a recorded full re-ingest (parallel/elastic.py)",
    "retrain.multihost_delta_agree": "cross-host delta-classification agreement check; disagreement or injected fault degrades every host to a recorded cold run (cli/game_multihost_driver.py)",
}

#: Preemption poll boundaries (the safe drain points) accepted by
#: ``preemption.check`` and the ``PHOTON_PREEMPT_AT`` grammar.
PREEMPT_SITES: Tuple[str, ...] = (
    "cycle",  # coordinate-descent update/iteration boundary
    "block",  # streaming random-effect block boundary
    "chunk",  # compacted-solver chunk boundary (optim/scheduler.py)
    "bucket",  # scheduled bucketed-RE bucket boundary (algorithm/bucketed_random_effect.py)
    "rung",  # fused device-loop rung-hop boundary (optim/fused_schedule.py)
)
