"""Resilience subsystem: fault injection, retry/backoff I/O, divergence guards.

The reference Photon ML inherits fault tolerance from Spark (lineage
recompute, task re-execution — SURVEY/PAPER §5.4); the TPU port owns its
own I/O and solver loops, so it owns its own resilience:

  * :mod:`photon_ml_tpu.resilience.faults` — deterministic fault injection
    at named sites (``io.read_block``, ``io.checkpoint_write``,
    ``io.index_load``, ``multihost.barrier``, ``optim.step``), driven by a
    context manager or the ``PHOTON_FAULTS`` env var.
  * :mod:`photon_ml_tpu.resilience.retry` — exponential backoff + jitter +
    deadline retry policies applied to Avro reads, index-map/off-heap loads,
    and checkpoint save/restore.
  * :mod:`photon_ml_tpu.resilience.guards` — non-finite detection in
    coordinate descent with last-good-state rollback.
  * :mod:`photon_ml_tpu.resilience.preemption` — cooperative interruption:
    SIGTERM/SIGINT (or ``PHOTON_PREEMPT_AT`` / a ``preempt.signal`` fault)
    set a flag the training loops poll at safe boundaries; they drain,
    write an emergency checkpoint, and unwind with :class:`Preempted`
    (drivers exit with :data:`PREEMPT_EXIT_CODE` or relaunch via
    ``--max-restarts``).

This module also holds the process-wide :class:`ResilienceConfig` consulted
by the ingest layer (corrupt-shard policy + retry policy), installed by the
CLI drivers from ``--on-corrupt`` / ``--corrupt-skip-budget`` /
``--io-retries`` flags or scoped with :func:`resilience_scope` in tests.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Iterator, Optional

from photon_ml_tpu.resilience import faults, guards, preemption, retry, sites
from photon_ml_tpu.resilience.sites import FAULT_SITES, PREEMPT_SITES
from photon_ml_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFatalError,
    InjectedIOError,
    fault_scope,
)
from photon_ml_tpu.resilience.guards import DivergenceGuard, GuardEvent, tree_all_finite
from photon_ml_tpu.resilience.preemption import PREEMPT_EXIT_CODE, Preempted
from photon_ml_tpu.resilience.retry import RetryError, RetryPolicy, call_with_retry

__all__ = [
    "faults",
    "guards",
    "preemption",
    "retry",
    "sites",
    "FAULT_SITES",
    "PREEMPT_SITES",
    "PREEMPT_EXIT_CODE",
    "Preempted",
    "FaultPlan",
    "FaultSpec",
    "InjectedIOError",
    "InjectedFatalError",
    "fault_scope",
    "DivergenceGuard",
    "GuardEvent",
    "tree_all_finite",
    "RetryError",
    "RetryPolicy",
    "call_with_retry",
    "ResilienceConfig",
    "current_config",
    "set_config",
    "resilience_scope",
]

ON_CORRUPT_MODES = ("raise", "skip")


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Process-wide ingest resilience settings.

    ``on_corrupt="skip"`` lets Avro container reads drop corrupt blocks
    (resynchronizing on the sync marker) up to ``corrupt_skip_budget`` blocks
    per file before raising; ``io_policy`` is the retry policy every
    filesystem read/write path uses.
    """

    on_corrupt: str = "raise"
    corrupt_skip_budget: int = 16
    io_policy: RetryPolicy = dataclasses.field(default_factory=RetryPolicy.io_default)

    def __post_init__(self):
        if self.on_corrupt not in ON_CORRUPT_MODES:
            raise ValueError(
                f"on_corrupt must be one of {ON_CORRUPT_MODES}, got {self.on_corrupt!r}"
            )
        if self.corrupt_skip_budget < 0:
            raise ValueError(
                f"corrupt_skip_budget must be >= 0, got {self.corrupt_skip_budget}"
            )


_config: Optional[ResilienceConfig] = None


def current_config() -> ResilienceConfig:
    """The installed config, else defaults (raise on corrupt, env-tuned retry)."""
    return _config if _config is not None else ResilienceConfig()


def set_config(config: Optional[ResilienceConfig]) -> None:
    """Install (or with None, reset) the process-wide resilience config."""
    global _config
    _config = config


@contextlib.contextmanager
def resilience_scope(config: ResilienceConfig) -> Iterator[ResilienceConfig]:
    """``with resilience_scope(cfg):`` — install for the duration."""
    global _config
    prev = _config
    _config = config
    try:
        yield config
    finally:
        _config = prev
