"""Bootstrap training: coefficient confidence intervals + metric percentiles.

Reference spec: BootstrapTraining.scala:28-180 — draw numBootstrapSamples
resamples (with replacement), train a model grid per resample, then
aggregate (a) per-coefficient streaming summaries (CoefficientSummary:
min/max/mean/var/quartiles) and (b) per-metric summaries.

TPU-native redesign: a bootstrap resample of an (N,)-row batch IS a weight
vector — counts drawn from Multinomial(N, 1/N) multiply the example weights.
All k replicate solves are ONE vmapped compiled kernel over a (k, N) weight
matrix; the data tensors are shared (never copied, never gathered), so k
bootstrap fits cost k optimizer runs on identical MXU-friendly shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.optim.problem import GLMOptimizationProblem

Array = jax.Array


@dataclasses.dataclass
class CoefficientSummary:
    """Distribution summary of one scalar across bootstrap replicates.

    (supervised/model/CoefficientSummary.scala parity: min/max/mean/var and
    quartile estimates; computed exactly here since k is small.)
    """

    min: float
    max: float
    mean: float
    variance: float
    q1: float
    median: float
    q3: float

    @staticmethod
    def from_samples(samples: np.ndarray) -> "CoefficientSummary":
        return CoefficientSummary(
            min=float(samples.min()),
            max=float(samples.max()),
            mean=float(samples.mean()),
            variance=float(samples.var(ddof=1)) if samples.size > 1 else 0.0,
            q1=float(np.quantile(samples, 0.25)),
            median=float(np.quantile(samples, 0.5)),
            q3=float(np.quantile(samples, 0.75)),
        )

    def contains_zero(self) -> bool:
        """CI-includes-zero check used for post-hoc feature pruning."""
        return self.min <= 0.0 <= self.max


@dataclasses.dataclass
class BootstrapResult:
    coefficient_summaries: List[CoefficientSummary]  # one per coefficient
    metric_summaries: Dict[str, CoefficientSummary]  # metric name -> summary
    models: List[GeneralizedLinearModel]  # one per replicate


def bootstrap_weights(key: Array, num_samples: int, n: int) -> Array:
    """(k, N) multinomial resample counts — the weight-space image of
    "sample N rows with replacement" (uniform probability)."""
    keys = jax.random.split(key, num_samples)

    def one(k):
        idx = jax.random.randint(k, (n,), 0, n)
        return jnp.zeros((n,), jnp.float32).at[idx].add(1.0)

    return jax.vmap(one)(keys)


def bootstrap_train(
    problem: GLMOptimizationProblem,
    batch: GLMBatch,
    norm: NormalizationContext,
    num_samples: int,
    seed: int = 0,
    metrics_fn: Optional[Callable[[GeneralizedLinearModel], Dict[str, float]]] = None,
    init_coefficients: Optional[Array] = None,
) -> BootstrapResult:
    """Train ``num_samples`` bootstrap replicates and aggregate.

    ``metrics_fn`` maps a trained model to a metric map (typically
    ``lambda m: evaluation.metrics.evaluate(m, holdout_batch)``).
    """
    n = batch.num_rows
    counts = bootstrap_weights(jax.random.PRNGKey(seed), num_samples, n)

    def solve(count_vec):
        resampled = GLMBatch(
            batch.features, batch.labels, batch.offsets, batch.weights * count_vec
        )
        model, result = problem.run(resampled, norm, init_coefficients)
        return model.coefficients.means, result.value

    means_k, _values = jax.jit(jax.vmap(solve))(counts)
    means_k = np.asarray(means_k)  # (k, D)

    models = [
        GeneralizedLinearModel(Coefficients(jnp.asarray(means_k[i])), problem.task)
        for i in range(num_samples)
    ]
    coef_summaries = [
        CoefficientSummary.from_samples(means_k[:, j]) for j in range(means_k.shape[1])
    ]

    metric_summaries: Dict[str, CoefficientSummary] = {}
    if metrics_fn is not None:
        per_model = [metrics_fn(m) for m in models]
        keys = set().union(*[set(m) for m in per_model]) if per_model else set()
        for key in sorted(keys):
            vals = np.array([m[key] for m in per_model if key in m])
            metric_summaries[key] = CoefficientSummary.from_samples(vals)

    return BootstrapResult(coef_summaries, metric_summaries, models)
