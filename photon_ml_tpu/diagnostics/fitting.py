"""Fitting diagnostic: learning curves over growing training fractions.

Reference spec: diagnostics/fitting/FittingDiagnostic.scala:33-130 — rows
are tagged uniformly into 10 partitions; the last is held out; models are
trained on growing prefixes (10%, 20%, ... 90%) with warm start from the
previous prefix, and train/holdout metric maps are recorded per
regularization weight. Skipped when n <= 10 * dimension (MIN_SAMPLES_PER_
PARTITION_PER_DIMENSION = 10, NUM_TRAINING_PARTITIONS = 10).

TPU-native: a "subset" is a weight mask, not a data copy — the batch tensors
stay device-resident across all prefix solves, so the 9 x |lambda| solves
reuse one compiled kernel with identical shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.reporting import PlotReport, SectionReport, SimpleTextReport
from photon_ml_tpu.evaluation import metrics as metrics_mod
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.training import train_glm_grid

NUM_TRAINING_PARTITIONS = 10
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 10


@dataclasses.dataclass
class FittingReport:
    """metric name -> (portions %, train values, holdout values)
    (FittingReport.scala parity)."""

    metrics: Dict[str, Tuple[List[float], List[float], List[float]]]
    message: str = ""


def _masked(batch: GLMBatch, mask: jnp.ndarray) -> GLMBatch:
    return GLMBatch(batch.features, batch.labels, batch.offsets, batch.weights * mask)


def diagnose(
    problem: GLMOptimizationProblem,
    batch: GLMBatch,
    norm: NormalizationContext,
    reg_weights: List[float],
    warm_start: Optional[Dict[float, GeneralizedLinearModel]] = None,
    seed: int = 0,
) -> Dict[float, FittingReport]:
    """Learning curves per regularization weight.

    Returns an empty map when the dataset is too small for a meaningful
    curve (reference behavior).
    """
    # Every one of the 10 partitions must support the model: n must exceed
    # partitions * dim * per-partition minimum. (The reference compares only
    # against dim * 10, FittingDiagnostic.scala:57-58, letting a 10% prefix
    # train on ~dim samples; the constant's intent is per-partition.)
    n_total = int(jnp.sum(batch.weights > 0.0))
    min_samples = (
        batch.dim * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION * NUM_TRAINING_PARTITIONS
    )
    if n_total <= min_samples:
        return {}

    tags = jax.random.randint(
        jax.random.PRNGKey(seed), (batch.num_rows,), 0, NUM_TRAINING_PARTITIONS
    )
    holdout_mask = (tags == NUM_TRAINING_PARTITIONS - 1).astype(jnp.float32)
    holdout = _masked(batch, holdout_mask)

    # per lambda: metric -> (portions, train, test)
    curves: Dict[float, Dict[str, Tuple[List[float], List[float], List[float]]]] = {
        lam: {} for lam in reg_weights
    }
    warm = warm_start
    for max_tag in range(NUM_TRAINING_PARTITIONS - 1):
        train_mask = (tags <= max_tag).astype(jnp.float32)
        subset = _masked(batch, train_mask)
        portion = 100.0 * float(jnp.sum(train_mask * (batch.weights > 0.0))) / n_total

        trained = train_glm_grid(problem, subset, norm, reg_weights, warm_start_models=warm)
        warm = trained.as_map()

        for lam, model in zip(trained.weights, trained.models):
            test_metrics = metrics_mod.evaluate(model, holdout, norm)
            train_metrics = metrics_mod.evaluate(model, subset, norm)
            for name, test_value in test_metrics.items():
                slot = curves[lam].setdefault(name, ([], [], []))
                slot[0].append(portion)
                slot[1].append(train_metrics.get(name, float("nan")))
                slot[2].append(test_value)

    return {lam: FittingReport(by_metric) for lam, by_metric in curves.items()}


def to_section(reports: Dict[float, FittingReport]) -> SectionReport:
    """FittingToPhysicalReportTransformer parity: one train-vs-holdout plot
    per (lambda, metric)."""
    items: List[object] = [
        SimpleTextReport(
            "Metrics as a function of training set size; diverging train/holdout "
            "curves indicate overfitting, jointly poor curves indicate underfitting."
        )
    ]
    for lam in sorted(reports):
        rep = reports[lam]
        sub: List[object] = []
        if rep.message:
            sub.append(SimpleTextReport(rep.message))
        for metric in sorted(rep.metrics):
            portions, train, test = rep.metrics[metric]
            finite = [t for t in train + test if np.isfinite(t)]
            if not finite:
                continue
            sub.append(
                PlotReport(
                    title=f"{metric} (lambda={lam:g})",
                    x_label="% of training data",
                    y_label=metric,
                    series={"train": (portions, train), "holdout": (portions, test)},
                )
            )
        items.append(SectionReport(f"lambda = {lam:g}", sub))
    return SectionReport("Fitting analysis (learning curves)", items)
