"""Diagnostic mode flags (DiagnosticMode.scala:22 parity)."""

from __future__ import annotations

import enum


class DiagnosticMode(enum.Enum):
    ALL = "ALL"
    TRAIN = "TRAIN"
    VALIDATE = "VALIDATE"
    NONE = "NONE"

    @property
    def runs_train(self) -> bool:
        return self in (DiagnosticMode.ALL, DiagnosticMode.TRAIN)

    @property
    def runs_validate(self) -> bool:
        return self in (DiagnosticMode.ALL, DiagnosticMode.VALIDATE)
