"""Report assembly: system + per-model chapters -> one document.

Reference spec: diagnostics/reporting/reports/ — SystemReport (params +
feature summary) and ModelDiagnosticReport (per-lambda model: metrics,
coefficient summary, fit/importance/HL/independence/bootstrap sections) are
combined by DiagnosticToPhysicalReportTransformer into the document that
Driver.writeDiagnostics renders (Driver.scala:577-597).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.diagnostics.common import feature_names_or_indices
from photon_ml_tpu.diagnostics.reporting import (
    ChapterReport,
    DocumentReport,
    SectionReport,
    SimpleTextReport,
    TableReport,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.stats import BasicStatisticalSummary

MAX_SUMMARY_ROWS = 50


@dataclasses.dataclass
class SystemReport:
    """ParametersReport + FeatureSummaryReport parity."""

    params: Dict[str, object]
    summary: Optional[BasicStatisticalSummary] = None
    feature_names: Optional[Sequence[str]] = None

    def to_chapter(self) -> ChapterReport:
        sections = [
            SectionReport(
                "Parameters",
                [
                    TableReport(
                        ["Parameter", "Value"],
                        [[k, str(v)] for k, v in sorted(self.params.items())],
                    )
                ],
            )
        ]
        if self.summary is not None:
            mean = np.asarray(self.summary.mean)
            d = mean.shape[0]
            names = feature_names_or_indices(self.feature_names, d)
            var = np.asarray(self.summary.variance)
            mn = np.asarray(self.summary.min)
            mx = np.asarray(self.summary.max)
            nnz = np.asarray(self.summary.num_nonzeros)
            shown = min(d, MAX_SUMMARY_ROWS)
            rows = [
                [str(names[j]), float(mean[j]), float(var[j]), float(mn[j]),
                 float(mx[j]), int(nnz[j])]
                for j in range(shown)
            ]
            items: List[object] = [
                TableReport(
                    ["Feature", "Mean", "Variance", "Min", "Max", "Non-zeros"],
                    rows,
                    caption=f"Feature summary ({shown} of {d} features, "
                    f"n = {int(float(self.summary.count))})",
                )
            ]
            if d > shown:
                items.append(SimpleTextReport(f"... {d - shown} more features omitted."))
            sections.append(SectionReport("Feature summary", items))
        return ChapterReport("System", sections)


@dataclasses.dataclass
class ModelDiagnosticReport:
    """One trained model's diagnostic chapter
    (ModelDiagnosticReport.scala parity)."""

    model: GeneralizedLinearModel
    reg_weight: float
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    sections: List[SectionReport] = dataclasses.field(default_factory=list)

    def to_chapter(self) -> ChapterReport:
        head = [
            SectionReport(
                "Summary",
                [
                    SimpleTextReport(self.model.summary()),
                    TableReport(
                        ["Metric", "Value"],
                        [[k, v] for k, v in sorted(self.metrics.items())],
                    ),
                ],
            )
        ]
        return ChapterReport(
            f"Model (lambda = {self.reg_weight:g})", head + list(self.sections)
        )


def assemble_document(
    title: str,
    system: Optional[SystemReport],
    model_reports: List[ModelDiagnosticReport],
) -> DocumentReport:
    chapters: List[ChapterReport] = []
    if system is not None:
        chapters.append(system.to_chapter())
    chapters.extend(m.to_chapter() for m in model_reports)
    return DocumentReport(title, chapters)
