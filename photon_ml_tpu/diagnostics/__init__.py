"""Model/training diagnostics and the report pipeline.

Reference spec: diagnostics/ (SURVEY.md §2.10) — diagnostics produce typed
logical reports; transformers map them into a physical report tree
(Document/Chapter/Section/Plot/Text); renderers emit HTML or text.
"""

from photon_ml_tpu.diagnostics.reporting import (
    BulletedListReport,
    ChapterReport,
    DocumentReport,
    NumberedListReport,
    PlotReport,
    SectionReport,
    SimpleTextReport,
    TableReport,
    render_html,
    render_text,
)
from photon_ml_tpu.diagnostics.types import DiagnosticMode

__all__ = [
    "BulletedListReport",
    "ChapterReport",
    "DiagnosticMode",
    "DocumentReport",
    "NumberedListReport",
    "PlotReport",
    "SectionReport",
    "SimpleTextReport",
    "TableReport",
    "render_html",
    "render_text",
]
