"""Small shared helpers for the diagnostics package."""

from __future__ import annotations

from typing import List, Optional, Sequence


def feature_names_or_indices(
    names: Optional[Sequence[str]], dim: int
) -> List[str]:
    """Feature display names, falling back to stringified indices; a short
    name list is padded with indices rather than erroring."""
    if names is None:
        return [str(i) for i in range(dim)]
    out = [str(n) for n in names[:dim]]
    out.extend(str(i) for i in range(len(out), dim))
    return out
