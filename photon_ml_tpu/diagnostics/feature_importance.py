"""Feature importance diagnostics.

Reference spec: diagnostics/featureimportance/ — two rankings over the model
coefficients (AbstractFeatureImportanceDiagnostic.scala:38-100):

  EXPECTED_MAGNITUDE : importance_j = |w_j * E|x_j||   (meanAbs from summary)
  VARIANCE           : importance_j = |w_j * Var x_j|

Without a statistical summary both fall back to |w_j|. The report keeps the
top MAX_RANKED_FEATURES features with descriptions plus an importance-by-
fractile curve (getRankToImportance :84-94).

TPU-native: the ranking is one |w| * stat elementwise multiply + top_k on
device; only the top slice is materialized host-side.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.common import feature_names_or_indices
from photon_ml_tpu.diagnostics.reporting import PlotReport, SectionReport, SimpleTextReport, TableReport
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.stats import BasicStatisticalSummary

MAX_RANKED_FEATURES = 100
NUM_IMPORTANCE_FRACTILES = 20

EXPECTED_MAGNITUDE = "EXPECTED_MAGNITUDE"
VARIANCE = "VARIANCE"


@dataclasses.dataclass
class FeatureImportanceReport:
    """FeatureImportanceReport.scala parity."""

    importance_type: str  # EXPECTED_MAGNITUDE or VARIANCE
    importance_description: str
    # (feature name, index, importance, description), descending importance
    ranked_features: List[Tuple[str, int, float, str]]
    # fractile (percent) -> importance at that rank
    rank_to_importance: Dict[float, float]


def _importance_vector(
    model: GeneralizedLinearModel,
    summary: Optional[BasicStatisticalSummary],
    importance_type: str,
) -> Tuple[np.ndarray, str]:
    w = jnp.abs(model.coefficients.means)
    if summary is None:
        return np.asarray(w), "|coefficient| (no data summary available)"
    if importance_type == EXPECTED_MAGNITUDE:
        return np.asarray(w * summary.mean_abs), "|coefficient * E[|feature|]|"
    if importance_type == VARIANCE:
        return np.asarray(w * summary.variance), "|coefficient * Var[feature]|"
    raise ValueError(f"unknown importance type {importance_type}")


def diagnose(
    model: GeneralizedLinearModel,
    summary: Optional[BasicStatisticalSummary],
    feature_names: Optional[Sequence[str]] = None,
    importance_type: str = EXPECTED_MAGNITUDE,
    max_features: int = MAX_RANKED_FEATURES,
) -> FeatureImportanceReport:
    imp, description = _importance_vector(model, summary, importance_type)
    order = np.argsort(-imp)
    coeffs = model.means_as_numpy()

    names = feature_names_or_indices(feature_names, imp.shape[0])
    ranked = []
    for idx in order[:max_features]:
        idx = int(idx)
        desc = f"coefficient={coeffs[idx]:.6g}"
        if summary is not None:
            desc += (
                f", mean={float(summary.mean[idx]):.4g}"
                f", std={float(summary.std[idx]):.4g}"
                f", mean|x|={float(summary.mean_abs[idx]):.4g}"
            )
        ranked.append((str(names[idx]), idx, float(imp[idx]), desc))

    # importance at the 0th, 5th, ... 100th percentile rank (:84-94)
    d = imp.shape[0]
    rank_to_importance = {}
    sorted_desc = imp[order]
    for f in range(NUM_IMPORTANCE_FRACTILES + 1):
        pos = f * (d - 1) // NUM_IMPORTANCE_FRACTILES if d else 0
        rank_to_importance[100.0 * f / NUM_IMPORTANCE_FRACTILES] = (
            float(sorted_desc[pos]) if d else 0.0
        )
    return FeatureImportanceReport(importance_type, description, ranked, rank_to_importance)


def to_section(report: FeatureImportanceReport, top_rows: int = 25) -> SectionReport:
    fractiles = sorted(report.rank_to_importance)
    return SectionReport(
        f"Feature importance ({report.importance_type})",
        [
            SimpleTextReport(f"Importance measure: {report.importance_description}"),
            TableReport(
                ["Feature", "Index", "Importance", "Detail"],
                [list(r) for r in report.ranked_features[:top_rows]],
                caption=f"Top {min(top_rows, len(report.ranked_features))} features",
            ),
            PlotReport(
                title="Importance by rank fractile",
                x_label="Rank fractile (%)",
                y_label="Importance",
                series={
                    "importance": (
                        fractiles,
                        [report.rank_to_importance[f] for f in fractiles],
                    )
                },
            ),
        ],
    )
