"""Prediction–error independence analysis via Kendall's tau.

Reference spec: diagnostics/independence/ — KendallTauAnalysis.scala:32-95
subsamples ~sqrt(n) points, counts concordant / discordant / tied pairs over
the cartesian square, and reports tau-alpha, tau-beta, the normal-
approximation z score (z = tau / sqrt(2(2n+5)/(9n(n-1)))) and the two-sided
p mass; PredictionErrorIndependenceDiagnostic.scala pairs (prediction,
label - prediction).

TPU-native: the pair census is a vectorized (m, m) sign-comparison on
device — the O(m^2) cartesian product is a pair of broadcast compares, not a
shuffle. m = ceil(sqrt(n)) keeps it tiny.
"""

from __future__ import annotations

import dataclasses
import math
from typing import TYPE_CHECKING, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.reporting import SectionReport, SimpleTextReport, TableReport
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.objective import GLMBatch

if TYPE_CHECKING:  # pragma: no cover
    from photon_ml_tpu.ops.normalization import NormalizationContext

Array = jax.Array


@dataclasses.dataclass
class KendallTauReport:
    """KendallTauReport.scala parity."""

    num_concordant: int
    num_discordant: int
    num_samples: int
    num_pairs: int
    effective_pairs: int  # concordant + discordant
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    p_value: float
    message: str


def _pair_census(a: Array, b: Array):
    """Count concordant/discordant/tied-in-a/tied-in-b unordered pairs."""
    sa = jnp.sign(a[:, None] - a[None, :])
    sb = jnp.sign(b[:, None] - b[None, :])
    upper = jnp.triu(jnp.ones_like(sa, dtype=bool), k=1)
    concordant = jnp.sum((sa * sb > 0) & upper)
    discordant = jnp.sum((sa * sb < 0) & upper)
    ties_a = jnp.sum((sa == 0) & upper)
    # Reference tie taxonomy (KendallTauAnalysis.checkConcordance): a pair
    # tied in A is counted as TIES_IN_A regardless of B; TIES_IN_B only
    # counts pairs with distinct A values.
    ties_b = jnp.sum((sa != 0) & (sb == 0) & upper)
    return concordant, discordant, ties_a, ties_b


def analyze(
    a: np.ndarray, b: np.ndarray, max_points: Optional[int] = None, seed: int = 0
) -> KendallTauReport:
    """Kendall-tau independence test between two draws of (A, B).

    ``max_points=None`` reproduces the reference's sqrt(n) subsample for
    n > ~10k points; smaller inputs are used whole.
    """
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    n = a.shape[0]
    if max_points is None:
        max_points = max(int(math.sqrt(n)), min(n, 2048))
    if n > max_points:
        idx = np.random.default_rng(seed).choice(n, size=max_points, replace=False)
        a, b = a[idx], b[idx]
    m = a.shape[0]

    conc, disc, ties_a, ties_b = jax.jit(_pair_census)(jnp.asarray(a), jnp.asarray(b))
    return analyze_counts(int(conc), int(disc), int(ties_a), int(ties_b), m)


def analyze_counts(
    num_concordant: int,
    num_discordant: int,
    num_ties_a: int,
    num_ties_b: int,
    num_items: int,
) -> KendallTauReport:
    """KendallTauAnalysis.analyze(counts) parity."""
    from scipy.stats import norm

    num_pairs = num_items * (num_items - 1) // 2
    no_ties_a = num_pairs - num_ties_a
    no_ties_b = num_pairs - num_ties_b
    effective = num_concordant + num_discordant
    tau_alpha = (num_concordant - num_discordant) / effective if effective else 0.0
    denom = math.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (num_concordant - num_discordant) / denom if denom > 0 else 0.0

    a = 2.0 * (2.0 * num_items + 5.0)
    b = 9.0 * num_items * (num_items - 1.0)
    d = math.sqrt(a / b) if b > 0 else 1.0
    z_alpha = tau_alpha / d
    # Deviation from KendallTauAnalysis.scala:76-77 (which stores the
    # confidence mass P(|Z| <= z)): this is the actual two-sided p-value —
    # small p rejects independence, large p is consistent with it.
    p_value = float(2.0 * (1.0 - norm.cdf(abs(z_alpha))))

    message = ""
    if num_ties_a + num_ties_b > 0:
        message = (
            f"Note: detected ties (ties in first variable: {num_ties_a}, ties in "
            f"second variable: {num_ties_b}). The computed z score / p value for "
            "tau-alpha over-estimates the degree of independence between A and B."
        )
    return KendallTauReport(
        num_concordant, num_discordant, num_items, num_pairs, effective,
        tau_alpha, tau_beta, z_alpha, p_value, message,
    )


@dataclasses.dataclass
class PredictionErrorIndependenceReport:
    """(prediction, error) independence (PredictionErrorIndependenceReport
    .scala parity)."""

    kendall_tau: KendallTauReport


def diagnose(
    model: GeneralizedLinearModel,
    batch: GLMBatch,
    seed: int = 0,
    norm: Optional["NormalizationContext"] = None,
) -> PredictionErrorIndependenceReport:
    """Test independence of prediction vs (label - prediction).

    Pass the training ``norm`` when the coefficients live in normalized space.
    """
    pred = np.asarray(model.compute_mean_functions(batch, norm))
    labels = np.asarray(batch.labels)
    mask = np.asarray(batch.weights) > 0.0
    pred, labels = pred[mask], labels[mask]
    return PredictionErrorIndependenceReport(analyze(pred, labels - pred, seed=seed))


def to_section(report: PredictionErrorIndependenceReport) -> SectionReport:
    kt = report.kendall_tau
    items = [
        SimpleTextReport(
            "Kendall tau test of independence between model prediction and "
            "prediction error (label - prediction). Small |tau| / large p-value "
            "is consistent with independence."
        ),
        TableReport(
            ["Statistic", "Value"],
            [
                ["Samples analyzed", kt.num_samples],
                ["Total pairs", kt.num_pairs],
                ["Concordant pairs", kt.num_concordant],
                ["Discordant pairs", kt.num_discordant],
                ["Effective (untied) pairs", kt.effective_pairs],
                ["tau-alpha", kt.tau_alpha],
                ["tau-beta", kt.tau_beta],
                ["z (tau-alpha)", kt.z_alpha],
                ["two-sided p-value", kt.p_value],
            ],
        ),
    ]
    if kt.message:
        items.append(SimpleTextReport(kt.message))
    return SectionReport("Prediction / error independence", items)
