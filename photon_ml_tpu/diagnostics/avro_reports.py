"""Machine-readable diagnostic outputs (VERDICT r2 missing #5).

The reference ships report record schemas (EvaluationResultAvro,
Curve2DAvro, FeatureSummarizationResultAvro, ... —
photon-avro-schemas/src/main/avro/) consumed by offline tooling; its driver
emits HTML only. Here the GLM driver writes BOTH: the HTML report and an
``diagnostics/`` directory of avro records per trained model — scalar
metric maps, ROC / precision-recall curves (classifiers), and per-feature
summary statistics — in the reference's schemas so existing consumers can
read them unchanged.
"""

from __future__ import annotations

import os
from email.utils import format_datetime
from datetime import datetime, timezone
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.types import ConvergenceReason, TaskType

EVALUATION_FILE = "evaluation-results.avro"
FEATURE_SUMMARY_FILE = "feature-summaries.avro"

# ConvergenceReason -> ConvergenceReasonAvro symbol (AbstractOptimizer
# reasons; NOT_CONVERGED has no symbol and maps to null)
_REASON_SYMBOL = {
    ConvergenceReason.MAX_ITERATIONS: "MAX_ITERATIONS",
    ConvergenceReason.FUNCTION_VALUES_CONVERGED: "FUNCTION_VALUES_CONVERGED",
    ConvergenceReason.GRADIENT_CONVERGED: "GRADIENT_CONVERGED",
    ConvergenceReason.OBJECTIVE_NOT_IMPROVING: "OBJECTIVE_NOT_IMPROVING",
}


def _rfc2822_now() -> str:
    return format_datetime(datetime.now(timezone.utc))


def _weighted_tp_fp(
    scores: np.ndarray, labels: np.ndarray, weights: Optional[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Cumulative WEIGHTED TP/FP, descending-score sweep — the same
    semantics as evaluation.metrics._roc_pr_curves, so the persisted curves
    agree with the weighted scalar AUC/AUPR; weight-0 rows (row padding
    from to_batch) contribute nothing."""
    order = np.argsort(-scores, kind="stable")
    y = (labels[order] > 0.5).astype(np.float64)
    w = np.ones_like(y) if weights is None else np.asarray(weights, np.float64)[order]
    tp = np.cumsum(w * y)
    fp = np.cumsum(w * (1.0 - y))
    return tp, fp


def roc_curve(
    scores: np.ndarray,
    labels: np.ndarray,
    weights: Optional[np.ndarray] = None,
    max_points: int = 200,
) -> List[dict]:
    """(FPR, TPR) Point2DAvro list, weighted, subsampled."""
    tp, fp = _weighted_tp_fp(scores, labels, weights)
    n_pos, n_neg = max(tp[-1], 1.0), max(fp[-1], 1.0)
    tpr = np.concatenate([[0.0], tp / n_pos])
    fpr = np.concatenate([[0.0], fp / n_neg])
    idx = np.unique(np.linspace(0, len(tpr) - 1, max_points).astype(int))
    return [{"x": float(fpr[i]), "y": float(tpr[i])} for i in idx]


def pr_curve(
    scores: np.ndarray,
    labels: np.ndarray,
    weights: Optional[np.ndarray] = None,
    max_points: int = 200,
) -> List[dict]:
    """(recall, precision) Point2DAvro list, weighted."""
    tp, fp = _weighted_tp_fp(scores, labels, weights)
    precision = tp / np.maximum(tp + fp, 1e-9)
    recall = tp / max(tp[-1], 1.0)
    idx = np.unique(np.linspace(0, len(tp) - 1, max_points).astype(int))
    return [{"x": float(recall[i]), "y": float(precision[i])} for i in idx]


def training_context(
    task: TaskType,
    lambda1: float,
    lambda2: float,
    normalized: bool,
    optimizer: str,
    tolerance: float,
    num_iterations: int,
    reason: Optional[ConvergenceReason],
    source_data_path: str,
) -> dict:
    return {
        "trainingTask": task.value if task != TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
        else "LOGISTIC_REGRESSION",  # enum has no SVM symbol; nearest task
        "lambda1": float(lambda1),
        "lambda2": float(lambda2),
        "applyFeatureNormalization": bool(normalized),
        "timestamp": _rfc2822_now(),
        "modelSource": "PHOTONML",
        "optimizer": f"com.linkedin.photon.ml.optimization.{optimizer}",
        "convergenceTolerance": float(tolerance),
        "numberOfIterations": int(num_iterations),
        "convergenceReason": _REASON_SYMBOL.get(reason),
        "sourceDataPath": source_data_path,
        "description": None,
        "lossFunction": schemas.LOSS_CLASS_BY_TASK[task.value],
        "scoreFunction": schemas.LOSS_CLASS_BY_TASK[task.value],
    }


def evaluation_result(
    model_id: str,
    model_path: str,
    data_path: str,
    train_ctx: dict,
    scalar_metrics: Dict[str, float],
    scores: Optional[np.ndarray] = None,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    with_curves: bool = False,
) -> dict:
    curves: Dict[str, dict] = {}
    if with_curves and scores is not None and labels is not None and len(scores):
        s_, l_ = np.asarray(scores), np.asarray(labels)
        w_ = None if weights is None else np.asarray(weights)
        curves["roc"] = {
            "xLabel": "false positive rate",
            "yLabel": "true positive rate",
            "points": roc_curve(s_, l_, w_),
        }
        curves["precisionRecall"] = {
            "xLabel": "recall",
            "yLabel": "precision",
            "points": pr_curve(s_, l_, w_),
        }
    return {
        "evaluationContext": {
            "metricsCalculator": "photon_ml_tpu.evaluation.metrics",
            "modelId": model_id,
            "modelPath": model_path,
            "modelTrainingContext": train_ctx,
            "timestamp": _rfc2822_now(),
            "dataPath": data_path,
            "segmentContext": None,
        },
        "scalarMetrics": {k: float(v) for k, v in scalar_metrics.items()},
        "curves": curves,
    }


def write_evaluation_results(output_dir: str, records: Sequence[dict]) -> str:
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, EVALUATION_FILE)
    avro_io.write_container(path, records, schemas.EVALUATION_RESULT)
    return path


def feature_summaries(
    feature_names: Sequence[str],
    summary,
) -> List[dict]:
    """BasicStatisticalSummary -> FeatureSummarizationResultAvro records
    (name/term split on ':' — the HTML report's display convention)."""
    out = []
    mean = np.asarray(summary.mean)
    var = np.asarray(summary.variance)
    mn = np.asarray(summary.min)
    mx = np.asarray(summary.max)
    nnz = np.asarray(summary.num_nonzeros)
    for j, full in enumerate(feature_names):
        name, _, term = full.partition(":")
        out.append(
            {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(mean[j]),
                    "variance": float(var[j]),
                    "min": float(mn[j]),
                    "max": float(mx[j]),
                    "numNonzeros": float(nnz[j]),
                },
            }
        )
    return out


def write_feature_summaries(output_dir: str, records: Sequence[dict]) -> str:
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, FEATURE_SUMMARY_FILE)
    avro_io.write_container(path, records, schemas.FEATURE_SUMMARIZATION_RESULT)
    return path
