"""Bootstrap training diagnostic: metric distributions + coefficient CIs.

Reference spec: diagnostics/bootstrap/ — BootstrapTrainingDiagnostic runs
BootstrapTraining over the dataset and reports (BootstrapReport.scala:27-32):
metric distributions (min/q1/median/q3/max), bagged-model metrics (simple
coefficient averaging), the coefficient distributions of the most important
features, and features whose bootstrap CI straddles zero.

TPU-native: built on photon_ml_tpu.bootstrap (all replicates are one vmapped
solve over a (k, N) resample-weight matrix — no data copies).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.bootstrap import BootstrapResult, CoefficientSummary, bootstrap_train
from photon_ml_tpu.diagnostics.common import feature_names_or_indices
from photon_ml_tpu.diagnostics.reporting import SectionReport, SimpleTextReport, TableReport
from photon_ml_tpu.evaluation import metrics as metrics_mod
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.optim.problem import GLMOptimizationProblem

DEFAULT_BOOTSTRAP_SAMPLES = 10
NUM_IMPORTANT_FEATURES = 20


@dataclasses.dataclass
class BootstrapDiagnosticReport:
    """BootstrapReport.scala parity."""

    # metric -> (min, q1, median, q3, max)
    metric_distributions: Dict[str, Tuple[float, float, float, float, float]]
    bagged_model_metrics: Dict[str, float]
    # feature name -> coefficient summary, for the most important features
    important_feature_distributions: Dict[str, CoefficientSummary]
    # feature name -> (index, importance, summary) for CI-straddles-zero features
    zero_crossing_features: Dict[str, Tuple[int, float, CoefficientSummary]]


def diagnose(
    problem: GLMOptimizationProblem,
    batch: GLMBatch,
    norm: NormalizationContext,
    holdout: GLMBatch,
    feature_names: Optional[Sequence[str]] = None,
    num_samples: int = DEFAULT_BOOTSTRAP_SAMPLES,
    seed: int = 0,
) -> BootstrapDiagnosticReport:
    result: BootstrapResult = bootstrap_train(
        problem,
        batch,
        norm,
        num_samples=num_samples,
        seed=seed,
        metrics_fn=lambda m: metrics_mod.evaluate(m, holdout, norm),
    )

    metric_distributions = {
        name: (s.min, s.q1, s.median, s.q3, s.max)
        for name, s in result.metric_summaries.items()
    }

    # Bagged model = mean coefficients across replicates
    mean_coeffs = np.mean(
        [m.means_as_numpy() for m in result.models], axis=0
    )
    import jax.numpy as jnp

    bagged = GeneralizedLinearModel(Coefficients(jnp.asarray(mean_coeffs)), problem.task)
    bagged_metrics = metrics_mod.evaluate(bagged, holdout, norm)

    names = feature_names_or_indices(feature_names, mean_coeffs.shape[0])
    importance = np.abs(mean_coeffs)
    top = np.argsort(-importance)[:NUM_IMPORTANT_FEATURES]
    important = {
        str(names[int(i)]): result.coefficient_summaries[int(i)] for i in top
    }
    zero_crossing = {
        str(names[j]): (j, float(importance[j]), s)
        for j, s in enumerate(result.coefficient_summaries)
        if s.contains_zero() and importance[j] > 0.0
    }
    return BootstrapDiagnosticReport(
        metric_distributions, bagged_metrics, important, zero_crossing
    )


def to_section(report: BootstrapDiagnosticReport, max_zero_rows: int = 25) -> SectionReport:
    items: List[object] = [
        TableReport(
            ["Metric", "Min", "Q1", "Median", "Q3", "Max"],
            [[m, *vals] for m, vals in sorted(report.metric_distributions.items())],
            caption="Holdout metric distribution across bootstrap replicates",
        ),
        TableReport(
            ["Metric", "Bagged model value"],
            [[m, v] for m, v in sorted(report.bagged_model_metrics.items())],
            caption="Metrics of the coefficient-averaged (bagged) model",
        ),
        TableReport(
            ["Feature", "Min", "Q1", "Median", "Q3", "Max"],
            [
                [name, s.min, s.q1, s.median, s.q3, s.max]
                for name, s in report.important_feature_distributions.items()
            ],
            caption="Coefficient distributions of the most important features",
        ),
    ]
    if report.zero_crossing_features:
        rows = sorted(
            report.zero_crossing_features.items(), key=lambda kv: -kv[1][1]
        )[:max_zero_rows]
        items.append(
            TableReport(
                ["Feature", "Index", "|mean coefficient|", "Min", "Max"],
                [[name, idx, imp, s.min, s.max] for name, (idx, imp, s) in rows],
                caption="Features whose bootstrap CI straddles zero "
                "(candidates for removal)",
            )
        )
    else:
        items.append(SimpleTextReport("No feature CI straddles zero."))
    return SectionReport("Bootstrap analysis", items)
