"""Physical report tree + HTML / plain-text renderers.

Reference spec: diagnostics/reporting/ (SURVEY.md §2.10) — the reference
models rendered output as a typed tree (DocumentPhysicalReport →
ChapterPhysicalReport → SectionPhysicalReport → {SimpleText, BulletedList,
NumberedList, Plot} physical reports; reporting/html/*.scala renderers walk
the tree emitting HTML with chapter/section numbering from a
NumberingContext; reporting/text/*.scala emit plain text).

This build keeps the same two-stage split (logical diagnostic reports are
transformed into this physical tree, then rendered) but collapses the
renderer strategy classes into two walkers. Plots are embedded as inline
SVG (the reference rasterizes xchart plots through batik; here matplotlib
renders straight to SVG, no raster round-trip).
"""

from __future__ import annotations

import dataclasses
import html as _html
import io
from typing import Dict, List, Sequence, Tuple, Union


# ---------------------------------------------------------------------------
# Physical report tree
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SimpleTextReport:
    """One paragraph (SimpleTextPhysicalReport.scala parity)."""

    text: str


@dataclasses.dataclass
class BulletedListReport:
    items: List[str]


@dataclasses.dataclass
class NumberedListReport:
    items: List[str]


@dataclasses.dataclass
class TableReport:
    """Header + rows of stringifiable cells.

    The reference renders tables as preformatted text blocks inside
    SimpleTextPhysicalReports; a first-class table node renders better HTML.
    """

    header: List[str]
    rows: List[List[object]]
    caption: str = ""


@dataclasses.dataclass
class PlotReport:
    """An XY plot (PlotPhysicalReport.scala parity, matplotlib-rendered).

    ``series``: name -> (x, y) arrays. Rendered lazily to SVG so building a
    report tree stays cheap when the text renderer is used.
    """

    title: str
    x_label: str
    y_label: str
    series: Dict[str, Tuple[Sequence[float], Sequence[float]]]
    log_x: bool = False
    log_y: bool = False
    caption: str = ""

    def to_svg(self) -> str:
        import matplotlib

        matplotlib.use("svg", force=False)
        from matplotlib import pyplot as plt

        fig, ax = plt.subplots(figsize=(7.0, 4.2), dpi=96)
        try:
            for name, (xs, ys) in self.series.items():
                ax.plot(list(xs), list(ys), marker="o", markersize=3, label=name)
            if self.log_x:
                ax.set_xscale("log")
            if self.log_y:
                ax.set_yscale("log")
            ax.set_title(self.title)
            ax.set_xlabel(self.x_label)
            ax.set_ylabel(self.y_label)
            if len(self.series) > 1:
                ax.legend(loc="best", fontsize=8)
            ax.grid(True, alpha=0.3)
            buf = io.StringIO()
            fig.savefig(buf, format="svg", bbox_inches="tight")
            return buf.getvalue()
        finally:
            plt.close(fig)


LeafReport = Union[SimpleTextReport, BulletedListReport, NumberedListReport, TableReport, PlotReport]


@dataclasses.dataclass
class SectionReport:
    """SectionPhysicalReport.scala parity: titled list of leaves/subsections."""

    title: str
    items: List[Union[LeafReport, "SectionReport"]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ChapterReport:
    title: str
    sections: List[SectionReport] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DocumentReport:
    title: str
    chapters: List[ChapterReport] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# HTML renderer (reporting/html/*.scala parity)
# ---------------------------------------------------------------------------

_CSS = """
body { font-family: -apple-system, 'Segoe UI', Helvetica, Arial, sans-serif;
       margin: 2em auto; max-width: 70em; color: #1a1a1a; }
h1 { border-bottom: 2px solid #444; padding-bottom: .3em; }
h2 { border-bottom: 1px solid #999; padding-bottom: .2em; margin-top: 2em; }
h3 { margin-top: 1.5em; }
table { border-collapse: collapse; margin: 1em 0; }
th, td { border: 1px solid #bbb; padding: .3em .7em; text-align: right; }
th { background: #eee; }
td:first-child, th:first-child { text-align: left; }
caption { caption-side: top; font-weight: bold; text-align: left; }
pre { background: #f6f6f6; padding: .8em; overflow-x: auto; }
nav ul { list-style: none; }
.plot svg { max-width: 100%; height: auto; }
"""


def _esc(s: object) -> str:
    return _html.escape(str(s))


def _fmt_cell(v: object) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _render_leaf_html(item: LeafReport, out: List[str]) -> None:
    if isinstance(item, SimpleTextReport):
        out.append(f"<p>{_esc(item.text)}</p>")
    elif isinstance(item, BulletedListReport):
        out.append("<ul>" + "".join(f"<li>{_esc(i)}</li>" for i in item.items) + "</ul>")
    elif isinstance(item, NumberedListReport):
        out.append("<ol>" + "".join(f"<li>{_esc(i)}</li>" for i in item.items) + "</ol>")
    elif isinstance(item, TableReport):
        out.append("<table>")
        if item.caption:
            out.append(f"<caption>{_esc(item.caption)}</caption>")
        out.append(
            "<thead><tr>" + "".join(f"<th>{_esc(h)}</th>" for h in item.header) + "</tr></thead>"
        )
        out.append("<tbody>")
        for row in item.rows:
            out.append("<tr>" + "".join(f"<td>{_esc(_fmt_cell(c))}</td>" for c in row) + "</tr>")
        out.append("</tbody></table>")
    elif isinstance(item, PlotReport):
        out.append('<div class="plot">')
        out.append(item.to_svg())
        if item.caption:
            out.append(f"<p><em>{_esc(item.caption)}</em></p>")
        out.append("</div>")
    else:  # pragma: no cover - defensive
        out.append(f"<pre>{_esc(item)}</pre>")


def _render_section_html(
    section: SectionReport, number: str, level: int, out: List[str]
) -> None:
    tag = f"h{min(level, 6)}"
    anchor = "sec-" + number.replace(".", "-")
    out.append(f'<{tag} id="{anchor}">{number} {_esc(section.title)}</{tag}>')
    sub = 0
    for item in section.items:
        if isinstance(item, SectionReport):
            sub += 1
            _render_section_html(item, f"{number}.{sub}", level + 1, out)
        else:
            _render_leaf_html(item, out)


def render_html(doc: DocumentReport) -> str:
    """Render the tree to a standalone HTML page (DocumentToHTMLRenderer
    parity: title, table of contents, numbered chapters/sections)."""
    body: List[str] = [f"<h1>{_esc(doc.title)}</h1>"]

    toc: List[str] = ["<nav><ul>"]
    for ci, chapter in enumerate(doc.chapters, 1):
        toc.append(f'<li><a href="#ch-{ci}">{ci} {_esc(chapter.title)}</a><ul>')
        for si, section in enumerate(chapter.sections, 1):
            toc.append(
                f'<li><a href="#sec-{ci}-{si}">{ci}.{si} {_esc(section.title)}</a></li>'
            )
        toc.append("</ul></li>")
    toc.append("</ul></nav>")
    body.extend(toc)

    for ci, chapter in enumerate(doc.chapters, 1):
        body.append(f'<h2 id="ch-{ci}">{ci} {_esc(chapter.title)}</h2>')
        for si, section in enumerate(chapter.sections, 1):
            _render_section_html(section, f"{ci}.{si}", 3, body)

    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>{_esc(doc.title)}</title><style>{_CSS}</style></head><body>"
        + "\n".join(body)
        + "</body></html>"
    )


# ---------------------------------------------------------------------------
# Text renderer (reporting/text/*.scala parity)
# ---------------------------------------------------------------------------


def _render_leaf_text(item: LeafReport, indent: str, out: List[str]) -> None:
    if isinstance(item, SimpleTextReport):
        out.append(indent + item.text)
    elif isinstance(item, (BulletedListReport, NumberedListReport)):
        numbered = isinstance(item, NumberedListReport)
        for i, entry in enumerate(item.items, 1):
            bullet = f"{i}." if numbered else "*"
            out.append(f"{indent}{bullet} {entry}")
    elif isinstance(item, TableReport):
        if item.caption:
            out.append(indent + item.caption)
        out.append(indent + " | ".join(item.header))
        for row in item.rows:
            out.append(indent + " | ".join(_fmt_cell(c) for c in row))
    elif isinstance(item, PlotReport):
        out.append(f"{indent}[plot: {item.title} ({item.x_label} vs {item.y_label})]")


def _render_section_text(section: SectionReport, number: str, out: List[str]) -> None:
    out.append(f"{number} {section.title}")
    sub = 0
    for item in section.items:
        if isinstance(item, SectionReport):
            sub += 1
            _render_section_text(item, f"{number}.{sub}", out)
        else:
            _render_leaf_text(item, "  ", out)


def render_text(doc: DocumentReport) -> str:
    out: List[str] = [doc.title, "=" * len(doc.title)]
    for ci, chapter in enumerate(doc.chapters, 1):
        out.append(f"\n{ci} {chapter.title}")
        for si, section in enumerate(chapter.sections, 1):
            _render_section_text(section, f"{ci}.{si}", out)
    return "\n".join(out) + "\n"
