"""Hosmer–Lemeshow goodness-of-fit test for logistic models.

Reference spec: diagnostics/hl/ — scores are binned into uniform-width
probability bins (HistogramBin semantics in
PredictedProbabilityVersusObservedFrequencyHistogramBin.scala:39-64:
expected positives = ceil(count * bin midpoint)); the default binner picks
min(dim + 2, 0.9*sqrt(n) + 0.9*log1p(n)) bins
(DefaultPredictedProbabilityVersusObservedFrequencyBinner.scala:29-57); the
chi-square statistic sums (obs-exp)^2/exp over pos and neg sides per bin
with a minimum-expected-count caveat of 5, dof = bins - 2, and the report
carries the chi2 CDF probability plus standard-confidence cutoffs
(HosmerLemeshowDiagnostic.scala:46-105).

TPU-native: binning is one segment-sum over the (N,) predicted-probability
vector on device; only the B-bin histogram lands on the host.
"""

from __future__ import annotations

import dataclasses
import math
from typing import TYPE_CHECKING, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.diagnostics.reporting import (
    PlotReport,
    SectionReport,
    SimpleTextReport,
    TableReport,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.types import TaskType

if TYPE_CHECKING:  # pragma: no cover
    from photon_ml_tpu.ops.normalization import NormalizationContext

STANDARD_CONFIDENCE_LEVELS = (
    0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999,
)
MINIMUM_EXPECTED_IN_BUCKET = 5


@dataclasses.dataclass
class HistogramBin:
    """One probability bin; expected positives = ceil(count * midpoint)."""

    lower: float
    upper: float
    observed_pos: int = 0
    observed_neg: int = 0

    @property
    def expected_pos(self) -> int:
        mid = (self.lower + self.upper) / 2.0
        return int(math.ceil((self.observed_pos + self.observed_neg) * mid))

    @property
    def expected_neg(self) -> int:
        return self.observed_pos + self.observed_neg - self.expected_pos


@dataclasses.dataclass
class HosmerLemeshowReport:
    binning_msg: str
    chi_square_msg: str
    chi_square: float
    degrees_of_freedom: int
    chi_square_probability: float  # P(X <= chi2) under the null
    confidence_cutoffs: List[Tuple[float, float]]  # (level, chi2 cutoff)
    histogram: List[HistogramBin]

    def test_description(self) -> str:
        return (
            f"chi2 = {self.chi_square:.6g} with {self.degrees_of_freedom} d.o.f.; "
            f"P(chi2 <= observed | model is well calibrated) = "
            f"{self.chi_square_probability:.6g}"
        )


def default_bin_count(num_items: int, num_dimensions: int) -> Tuple[str, int]:
    """min(dimension-driven, data-driven) uniform bins, never below 3
    (dof = bins - 2 must stay positive for the chi2 to be defined)."""
    by_dim = num_dimensions + 2
    # The reference applies factor 0.9 to both terms
    # (DefaultPredictedProbabilityVersusObservedFrequencyBinner.scala:51-57).
    by_data = int(0.9 * math.sqrt(num_items) + 0.9 * math.log1p(num_items))
    bins = max(3, min(by_dim, by_data))
    ok = (
        "Sufficient bins for a discriminative test"
        if bins >= by_dim
        else "Not enough bins for a discriminative test; please be careful when "
        "interpreting these results or rerun with more data"
    )
    msg = (
        f"Number of test set samples: {num_items}\n"
        f"Sample dimensionality: {num_dimensions}\n"
        f"Target number of bins based on dimensionality alone: {by_dim}\n"
        f"Target number of bins based on data alone: {by_data}\n" + ok
    )
    return msg, bins


def bin_scores(
    predicted: jnp.ndarray,
    labels: jnp.ndarray,
    num_bins: int,
    weights: Optional[jnp.ndarray] = None,
) -> List[HistogramBin]:
    """Histogram (predicted probability, label) pairs into uniform bins.

    One pass on device: bin index = floor(p * B) clamped, pos/neg counts via
    two bincounts. Padding rows (weight 0) are dropped.
    """
    p = jnp.clip(predicted, 0.0, 1.0)
    idx = jnp.minimum((p * num_bins).astype(jnp.int32), num_bins - 1)
    present = (
        jnp.ones_like(p) if weights is None else (weights > 0.0).astype(p.dtype)
    )
    # integer accumulation: float32 bincount weights saturate at 2^24 rows
    pos = (labels * present).astype(jnp.int32)
    neg = ((1.0 - labels) * present).astype(jnp.int32)
    pos_counts = np.asarray(jax.ops.segment_sum(pos, idx, num_segments=num_bins))
    neg_counts = np.asarray(jax.ops.segment_sum(neg, idx, num_segments=num_bins))
    return [
        HistogramBin(
            i / num_bins, (i + 1) / num_bins, int(pos_counts[i]), int(neg_counts[i])
        )
        for i in range(num_bins)
    ]


def hosmer_lemeshow_test(
    bins: List[HistogramBin], binning_msg: str = ""
) -> HosmerLemeshowReport:
    """Chi-square over the binned histogram (HosmerLemeshowDiagnostic.scala:
    46-105 semantics, including the per-side zero-expected guard)."""
    from scipy.stats import chi2 as chi2_dist

    msgs: List[str] = []
    score = 0.0
    for b in bins:
        if b.expected_pos > 0:
            score += (b.observed_pos - b.expected_pos) ** 2 / float(b.expected_pos)
        if b.expected_pos < MINIMUM_EXPECTED_IN_BUCKET:
            msgs.append(
                f"For bin [{b.lower:.4f}, {b.upper:.4f}), expected positive count "
                "is too small to soundly use in a Chi^2 estimate"
            )
        if b.expected_neg > 0:
            score += (b.observed_neg - b.expected_neg) ** 2 / float(b.expected_neg)
        if b.expected_neg < MINIMUM_EXPECTED_IN_BUCKET:
            msgs.append(
                f"For bin [{b.lower:.4f}, {b.upper:.4f}), expected negative count "
                "is too small to soundly use in a Chi^2 estimate"
            )

    dof = max(len(bins) - 2, 1)
    dist = chi2_dist(dof)
    cutoffs = [(lvl, float(dist.ppf(lvl))) for lvl in STANDARD_CONFIDENCE_LEVELS]
    prob = float(dist.cdf(score))
    return HosmerLemeshowReport(binning_msg, "\n".join(msgs), score, dof, prob, cutoffs, bins)


def diagnose(
    model: GeneralizedLinearModel,
    batch: GLMBatch,
    num_bins: Optional[int] = None,
    norm: Optional["NormalizationContext"] = None,
) -> HosmerLemeshowReport:
    """Full HL diagnostic on a logistic model over one batch.

    Pass the training ``norm`` when the coefficients live in normalized space.
    """
    if model.task != TaskType.LOGISTIC_REGRESSION:
        raise ValueError("Hosmer-Lemeshow requires a logistic regression model")
    predicted = model.compute_mean_functions(batch, norm)
    n = int(jnp.sum(batch.weights > 0.0))
    if num_bins is None:
        msg, num_bins = default_bin_count(n, batch.dim)
    else:
        msg = f"Fixed bin count: {num_bins}"
    bins = bin_scores(predicted, batch.labels, num_bins, batch.weights)
    return hosmer_lemeshow_test(bins, msg)


def to_section(report: HosmerLemeshowReport) -> SectionReport:
    """Physical-report transformer (NaiveHosmerLemeshowToPhysicalReport-
    Transformer.scala parity): histogram table, calibration plot, chi2 text."""
    rows = [
        [f"[{b.lower:.3f}, {b.upper:.3f})", b.observed_pos, b.expected_pos,
         b.observed_neg, b.expected_neg]
        for b in report.histogram
    ]
    mids = [(b.lower + b.upper) / 2.0 for b in report.histogram]
    total = [max(b.observed_pos + b.observed_neg, 1) for b in report.histogram]
    observed_freq = [
        b.observed_pos / t for b, t in zip(report.histogram, total)
    ]
    items: List[object] = [
        SimpleTextReport(report.binning_msg),
        SimpleTextReport(report.test_description()),
        TableReport(
            ["Score range", "Pos observed", "Pos expected", "Neg observed", "Neg expected"],
            rows,
            caption="Predicted probability vs observed frequency",
        ),
        PlotReport(
            title="Calibration (Hosmer-Lemeshow)",
            x_label="Predicted probability (bin midpoint)",
            y_label="Observed positive frequency",
            series={
                "observed": (mids, observed_freq),
                "perfectly calibrated": (mids, mids),
            },
        ),
        TableReport(
            ["Confidence level", "Chi^2 cutoff"],
            [[lvl, cut] for lvl, cut in report.confidence_cutoffs],
            caption="Chi^2 cutoffs at standard confidence levels "
            f"(d.o.f. = {report.degrees_of_freedom})",
        ),
    ]
    if report.chi_square_msg:
        items.insert(2, SimpleTextReport(report.chi_square_msg))
    return SectionReport("Hosmer-Lemeshow calibration", items)
