"""Request micro-batching onto the canonical shape ladder.

Per-request dispatch would hand XLA a new shape per request (a compile) or
a batch-of-one (an executable running at 1/B fill). The micro-batcher sits
between the request threads and the device: concurrent requests coalesce —
bounded by ``max_batch_rows`` and a ``max_wait_ms`` window — into ONE
batch whose row count and nnz width are rounded up the PR-3
:class:`~photon_ml_tpu.compile.ShapeBucketer` ladder, so every batch hits
one of a small fixed set of already-compiled executables; responses are
sliced back per request. The first request in an idle window pays at most
``max_wait_ms``; a saturated queue never waits (the batch fills first).

The batcher is model-agnostic: it coalesces :class:`RowBatch` values and
calls a ``score_batch`` function; featurization (name/term -> index,
entity id -> slab row) happened in the server before ``submit``.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.compile import ShapeBucketer, pad_axis
from photon_ml_tpu.serve.stats import ServeStats


@dataclasses.dataclass
class RowBatch:
    """Host-side featurized rows (one request's worth, or a coalesced
    batch). Per-shard COO uses the scoring driver's padding convention:
    pad column 0 with value 0 (a gather-safe exact no-op)."""

    offset: np.ndarray  # (n,) f32
    shard_idx: Dict[str, np.ndarray]  # shard -> (n, k) int32
    shard_val: Dict[str, np.ndarray]  # shard -> (n, k) f32
    ent_row: Dict[str, np.ndarray]  # RE coordinate name -> (n,) int32

    @property
    def num_rows(self) -> int:
        return len(self.offset)

    @staticmethod
    def concat(batches: List["RowBatch"]) -> "RowBatch":
        """Row-concatenate request batches (shared shard/coordinate keys);
        per-shard nnz widths equalize to the widest member (zero padding)."""
        first = batches[0]
        if len(batches) == 1:
            return first
        shard_idx, shard_val = {}, {}
        for s in first.shard_idx:
            k = max(b.shard_idx[s].shape[1] for b in batches)
            shard_idx[s] = np.concatenate(
                [pad_axis(b.shard_idx[s], 1, k, 0) for b in batches]
            )
            shard_val[s] = np.concatenate(
                [pad_axis(b.shard_val[s], 1, k, 0.0) for b in batches]
            )
        return RowBatch(
            offset=np.concatenate([b.offset for b in batches]),
            shard_idx=shard_idx,
            shard_val=shard_val,
            ent_row={
                c: np.concatenate([b.ent_row[c] for b in batches])
                for c in first.ent_row
            },
        )

    def padded(self, bucketer: Optional[ShapeBucketer]) -> "RowBatch":
        """Rows and nnz widths rounded up the ladder. Padded rows carry
        offset 0, entity row -1 (scores 0, sliced off before response);
        padded nnz slots are index 0 / value 0 no-ops."""
        if bucketer is None:
            return self
        n = self.num_rows
        n_pad = bucketer.canon(n)
        return RowBatch(
            offset=pad_axis(self.offset, 0, n_pad, 0.0),
            shard_idx={
                s: pad_axis(
                    pad_axis(a, 1, bucketer.canon(a.shape[1]), 0), 0, n_pad, 0
                )
                for s, a in self.shard_idx.items()
            },
            shard_val={
                s: pad_axis(
                    pad_axis(a, 1, bucketer.canon(a.shape[1]), 0.0), 0, n_pad, 0.0
                )
                for s, a in self.shard_val.items()
            },
            ent_row={
                c: pad_axis(a, 0, n_pad, -1) for c, a in self.ent_row.items()
            },
        )


@dataclasses.dataclass
class _Pending:
    batch: RowBatch
    future: Future
    submitted: float
    # per-request scoring closure (model-swap correctness: a request
    # featurized against model generation G must score against G's slabs —
    # its entity rows index THAT slab layout); None = the batcher default
    score_fn: Optional[Callable[[RowBatch], np.ndarray]]


class MicroBatcher:
    """Background coalescing loop: ``submit`` returns a Future; a single
    worker drains the queue, pads the coalesced batch up the ladder, scores
    once, slices per request."""

    def __init__(
        self,
        score_batch: Callable[[RowBatch], np.ndarray],
        max_batch_rows: int = 128,
        max_wait_ms: float = 2.0,
        bucketer: Optional[ShapeBucketer] = None,
        stats: Optional[ServeStats] = None,
    ):
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        self._score_batch = score_batch
        self.max_batch_rows = max_batch_rows
        self.max_wait_s = max(max_wait_ms, 0.0) / 1e3
        self.bucketer = bucketer
        self.stats = stats if stats is not None else ServeStats()
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._carry: Optional[_Pending] = None  # worker-thread only
        self._closed = False
        self._outstanding = 0
        self._outstanding_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()

    # ------------------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="photon-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def submit(
        self,
        batch: RowBatch,
        score_fn: Optional[Callable[[RowBatch], np.ndarray]] = None,
    ) -> Future:
        """Enqueue one request's rows; the Future resolves to its (n,)
        score slice (or raises the batch's scoring error). ``score_fn``
        pins the request to a specific model generation — requests pinned
        to different generations coalesce into separate device calls."""
        fut: Future = Future()
        fut.add_done_callback(self._on_done)
        # closed-check, bookkeeping, and the put share one lock so a submit
        # can never slip its item in AFTER close()'s shutdown sentinel
        # (which would strand the Future unresolved forever)
        with self._outstanding_lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._outstanding += 1
            self._idle.clear()
            self._queue.put(_Pending(batch, fut, time.monotonic(), score_fn))
        return fut

    def _on_done(self, _fut: Future) -> None:
        with self._outstanding_lock:
            self._outstanding -= 1
            if self._outstanding == 0:
                self._idle.set()

    def outstanding(self) -> int:
        with self._outstanding_lock:
            return self._outstanding

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted request has resolved (the model
        swapper's fence before retiring an old store). True on success."""
        return self._idle.wait(timeout)

    def close(self) -> None:
        """Drain outstanding requests, then stop the worker."""
        with self._outstanding_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # sentinel ordered after every submit
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # ------------------------------------------------------------------
    def _collect(self, first: _Pending) -> Tuple[List[_Pending], bool]:
        """Coalesce: wait up to the window for more requests, stop early at
        ``max_batch_rows``. A request that would push the batch PAST the
        cap is carried to the next batch instead (an overshot batch would
        pad to a ladder rung warmup never compiled — a request-path
        compile). Returns (members, saw_shutdown)."""
        members = [first]
        rows = first.batch.num_rows
        deadline = time.monotonic() + self.max_wait_s
        while rows < self.max_batch_rows:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                return members, True
            if rows + item.batch.num_rows > self.max_batch_rows:
                self._carry = item
                break
            members.append(item)
            rows += item.batch.num_rows
        return members, False

    def _process(self, members: List[_Pending]) -> None:
        # group by scoring closure, preserving submit order: mid-swap, old-
        # and new-generation requests must not share one gather (their
        # entity rows index different slab layouts); steady state is one
        # group, transiently two
        groups: List[Tuple[Optional[Callable], List[_Pending]]] = []
        for m in members:
            if groups and groups[-1][0] is m.score_fn:
                groups[-1][1].append(m)
            else:
                groups.append((m.score_fn, [m]))
        for score_fn, group in groups:
            self._score_group(score_fn or self._score_batch, group)

    def _score_group(self, score_fn: Callable, members: List[_Pending]) -> None:
        try:
            merged = RowBatch.concat([m.batch for m in members])
            n_real = merged.num_rows
            padded = merged.padded(self.bucketer)
            scores = np.asarray(score_fn(padded))[:n_real]
            self.stats.record_batch(n_real, padded.num_rows, len(members))
        except Exception as e:  # noqa: BLE001 — fan the failure to every caller
            self.stats.record_error()
            for m in members:
                if not m.future.cancelled():
                    m.future.set_exception(e)
            return
        done = time.monotonic()
        lo = 0
        for m in members:
            hi = lo + m.batch.num_rows
            self.stats.record_request(done - m.submitted, m.batch.num_rows)
            if not m.future.cancelled():
                m.future.set_result(scores[lo:hi])
            lo = hi

    def _worker(self) -> None:
        while True:
            if self._carry is not None:
                item, self._carry = self._carry, None
            else:
                item = self._queue.get()
            if item is None:
                return
            members, shutdown = self._collect(item)
            self._process(members)
            if shutdown:
                return
