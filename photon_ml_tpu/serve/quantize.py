"""Quantized coefficient storage for the serving slabs.

Serving memory is the binding constraint on the request path: a
billion-coefficient model's `(E_pad, D)` f32 slabs cost 4 GB/host of mmap
residency (Snap ML, arXiv:1803.06333, wins GLM throughput on exactly this
memory-hierarchy footprint). This module is the repo's first deliberate
accuracy/speed dial: a ``store_dtype`` policy for the slab files —

  * ``f32``  — the default; layout unchanged, scores stay BITWISE-equal
    to the batch scoring driver (the existing oracle).
  * ``bf16`` — slabs stored as raw bf16 bit patterns (uint16 on disk, so
    numpy mmaps them without a custom-dtype dependency); dequantize is an
    exact widening cast (bf16 is the top 16 bits of f32). 50% of f32
    slab bytes.
  * ``int8`` — slabs stored as int8 with a per-slab-row absmax scale
    sidecar (``scales.npy``, f32 ``(E_pad,)``); dequantize is
    ``q.astype(f32) * scale[row]`` on the gathered elements. ~25% of f32
    slab bytes.

The dial is measured, not assumed: quantized exports carry a PINNED
per-coefficient error budget derived analytically from the true slab
(:func:`row_coeff_budget`), the realized error is computed against the
true slab at export time (:func:`slab_error_report`), and an export whose
realized error exceeds its budget FAILS — it never serves. Both numbers
are recorded in store meta and re-asserted at open. Per-score error then
bounds as ``||values||_1 * coeff_err_budget`` per random-effect
coordinate (fixed-effect vectors stay f32 — they are ``(D,)`` and
replicated; the slabs are the bytes), which is the budget the serve/fleet
tests and the ``quantized_serving`` bench section assert against.

Quantization error, per slab row with absmax ``m``:

  * bf16 round-to-nearest-even: ``|w_q - w| <= u * |w| <= u * m`` with
    unit roundoff ``u = 2^-8`` (8 bits of precision incl. the hidden bit).
  * int8 absmax: ``scale = m / 127``, ``q = round(w / scale)`` (clip is a
    no-op at the extremes since ``m / scale == 127`` exactly in the
    round-trip), so ``|w_q - w| <= scale / 2 = m / 254`` plus a small f32
    slack for the two f32 roundings (computing the scale, and the
    ``q * scale`` dequant product).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

#: the store_dtype policy values accepted everywhere a store is built
STORE_DTYPES = ("f32", "bf16", "int8")

#: bf16 unit roundoff (1 sign + 8 exp + 7 mantissa bits -> precision 8)
_BF16_U = 2.0 ** -8
#: int8 absmax rounding step is scale/2 = absmax/254; the extra term
#: covers the f32 roundings in the scale computation and the dequant
#: product (a handful of ulps, bounded well under 2^-20 relative)
_INT8_U = 0.5 / 127.0 + 2.0 ** -20


def _bf16(require: bool = True):
    """ml_dtypes.bfloat16, gated: it ships with jax (a hard dependency),
    but a bf16 store must fail ACTIONABLY if the environment lost it."""
    try:
        import ml_dtypes

        return ml_dtypes.bfloat16
    except ImportError as e:
        if require:
            raise IOError(
                "bf16 serving stores need the ml_dtypes package (a jax "
                "dependency) to view the uint16 bit patterns as bfloat16; "
                f"import failed: {e}. Re-export the store with "
                "--store-dtype f32 or restore ml_dtypes."
            ) from e
        return None


def validate_store_dtype(store_dtype: str) -> str:
    if store_dtype not in STORE_DTYPES:
        raise ValueError(
            f"store_dtype must be one of {STORE_DTYPES}, got {store_dtype!r}"
        )
    return store_dtype


def row_coeff_budget(store_dtype: str, absmax: np.ndarray) -> np.ndarray:
    """Per-slab-row bound on ``|w_quantized - w|`` given each row's absmax
    — the analytic budget a quantized export is pinned to."""
    validate_store_dtype(store_dtype)
    absmax = np.asarray(absmax, np.float64)
    if store_dtype == "f32":
        return np.zeros_like(absmax)
    if store_dtype == "bf16":
        # the 2^-133 floor covers rounding inside bf16's subnormal range
        # (spacing 2^-133), where the relative bound alone is too tight
        return absmax * _BF16_U + 2.0 ** -133
    return absmax * _INT8_U


def quantize_slab(
    slab: np.ndarray, store_dtype: str
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """True f32 slab -> (stored array, per-row scale sidecar or None).

    bf16 returns the raw bit patterns as uint16 (mmap-able by plain
    numpy); int8 returns (int8 slab, (E_pad,) f32 scales). All-zero rows
    get scale 1.0 so the sidecar stays finite and strictly positive — the
    open-time corruption gate can then reject ANY non-finite or
    non-positive scale outright.
    """
    validate_store_dtype(store_dtype)
    slab = np.ascontiguousarray(slab, np.float32)
    if store_dtype == "f32":
        return slab, None
    if store_dtype == "bf16":
        return slab.astype(_bf16()).view(np.uint16), None
    absmax = np.max(np.abs(slab), axis=1)
    scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.rint(slab / scales[:, None]), -127, 127
    ).astype(np.int8)
    return q, scales


def dequantize_slab(
    stored: np.ndarray, scales: Optional[np.ndarray], store_dtype: str
) -> np.ndarray:
    """Host-side dequantize to f32 — the exact values the device kernels
    gather (export validation and the host scoring oracle both use this)."""
    validate_store_dtype(store_dtype)
    if store_dtype == "f32":
        return np.asarray(stored, np.float32)
    if store_dtype == "bf16":
        return np.asarray(stored).view(_bf16()).astype(np.float32)
    return stored.astype(np.float32) * np.asarray(scales, np.float32)[:, None]


def slab_error_report(
    true_slab: np.ndarray,
    stored: np.ndarray,
    scales: Optional[np.ndarray],
    store_dtype: str,
) -> Dict[str, float]:
    """Realized vs budgeted quantization error for one exported slab.

    Raises IOError when the realized error exceeds the pinned budget —
    the export fails; a slab over budget never serves.
    """
    true_slab = np.asarray(true_slab, np.float32)
    deq = dequantize_slab(stored, scales, store_dtype)
    realized = float(np.max(np.abs(deq.astype(np.float64) - true_slab)))
    budget = float(
        np.max(
            row_coeff_budget(
                store_dtype, np.max(np.abs(true_slab), axis=1)
            )
        )
        if true_slab.size
        else 0.0
    )
    # `not (realized <= budget)` (NOT `realized > budget`): a NaN/inf
    # realized error must FAIL the gate, and every comparison against
    # NaN is False
    if not (realized <= budget):
        if not np.all(np.isfinite(true_slab)):
            hint = (
                "the true slab carries non-finite coefficients (e.g. the "
                "optim.step NaN-corruption fault mode)"
            )
        elif not np.isfinite(realized):
            # two finite-slab ways to a non-finite round trip: an f32
            # coefficient past bf16's max finite overflows to inf in the
            # narrowing cast; a subnormal row absmax underflows the int8
            # scale to zero
            hint = (
                "the true slab is finite but does not survive the "
                f"{store_dtype} round trip (overflow past the dtype's "
                "max finite, or a subnormal row absmax underflowing the "
                "scale)"
            )
        else:
            hint = "the coefficients exceed this dtype's analytic budget"
        raise IOError(
            f"quantized slab exceeds its pinned error budget: realized "
            f"max |w_q - w| = {realized:.3e} > budget {budget:.3e} "
            f"({store_dtype}; {hint}); refusing the export — this slab "
            "must not serve"
        )
    return {
        "realized_max_abs_coeff_err": realized,
        "coeff_err_budget": budget,
    }
