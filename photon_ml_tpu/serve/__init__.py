"""Online scoring service: the persistent GAME request path.

Production GLMix exists to score millions of users at request time. This
package is the warm-process serving layer over the training stack's
already-shipped pieces — and deliberately nothing more (no network
framework; transport is the deployment's problem):

  * :mod:`.model_store` — mmap'd off-heap coefficient store (the
    ``io/offheap.py`` PalDB machinery generalized from feature indices to
    coefficient slabs, entity -> slab-row hash probes in mapped memory).
  * :mod:`.batcher` — request micro-batching onto the PR-3 canonical
    shape ladder (bounded wait, padded batch, sliced responses).
  * :mod:`.server` — the scoring engine + JSON-lines request loop; warm
    startup through the persistent XLA cache asserts zero new compiles;
    scores are bitwise-equal to the batch ``game_scoring_driver``.
  * :mod:`.swap` — zero-downtime model rolls through the checkpoint
    by-reference protocol (no dropped requests, no recompiles).
  * :mod:`.stats` — p50/p99 latency, batch-fill ratio, QPS telemetry.

Driver: ``photon_ml_tpu.cli.serve_driver`` (``bench.py serving`` publishes
latency/QPS vs micro-batch size and the swap proof).

Fleet: :mod:`photon_ml_tpu.serve.fleet` shards the store across replicas
behind a consistent-hash router for models that cannot fit one host
(``bench.py serving_fleet``; driver ``photon_ml_tpu.cli.fleet_driver``).
"""

from __future__ import annotations

from photon_ml_tpu.serve.batcher import MicroBatcher, RowBatch
from photon_ml_tpu.serve.model_store import (
    ModelStore,
    build_model_store,
    is_model_store,
)
from photon_ml_tpu.serve.quantize import STORE_DTYPES
from photon_ml_tpu.serve.server import ScoringServer, serve_json_lines
from photon_ml_tpu.serve.stats import FleetStats, ServeStats, serve_stats
from photon_ml_tpu.serve.swap import ModelSwapper

__all__ = [
    "FleetStats",
    "MicroBatcher",
    "ModelStore",
    "ModelSwapper",
    "RowBatch",
    "STORE_DTYPES",
    "ScoringServer",
    "ServeStats",
    "build_model_store",
    "is_model_store",
    "serve_json_lines",
    "serve_stats",
]
