"""Mmap'd coefficient store for the online scoring server.

A saved GAME model (the reference's Avro layout, io/model_io.py) is great
for offline interchange and terrible for a warm request path: every open
re-parses name/term records and re-densifies coefficients through a Python
dict. This module EXPORTS a model once into an off-heap serving layout and
then serves it with zero parse work per process:

  ``store_dir/``
    ``meta.json``                 format/coordinates/shards/ladder manifest
    ``features/<shard>/``         pmix feature index (io/offheap.py store;
                                  the SAME store the batch drivers accept
                                  via ``--offheap-indexmap-dir``)
    ``fixed/<name>.npy``          (D,) f32 fixed-effect coefficients (mmap)
    ``random/<name>/rows/``       pmix entity -> slab-row lookup
                                  (:class:`~photon_ml_tpu.io.offheap.
                                  SlabRowIndex` — the feature-index
                                  machinery generalized to coefficient
                                  slabs)
    ``random/<name>/slab.npy``    (E_pad, D) per-entity coefficient slab
                                  (f32, or bf16-as-uint16 / int8 under a
                                  quantized ``store_dtype`` — see
                                  :mod:`photon_ml_tpu.serve.quantize`),
                                  row order = the rows store's index
                                  order, entity count padded up the PR-3
                                  shape ladder so a model swap that stays
                                  within the rung reuses every compiled
                                  executable
    ``random/<name>/scales.npy``  (E_pad,) f32 per-row absmax scale
                                  sidecar (int8 stores only)

Opening the store is a handful of mmaps (the page cache is the share
mechanism — concurrent servers on one host map the same physical pages,
the owner-computes lookup never copies a slab), and the store participates
in the checkpoint by-reference protocol (``__checkpoint_ref__`` /
``__checkpoint_from_ref__``, photon_ml_tpu/checkpoint.py) so the
:class:`~photon_ml_tpu.serve.swap.ModelSwapper` rolls a live server to a
new store through the same path streaming checkpoints restore through.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu.checkpoint import CheckpointRefError
from photon_ml_tpu.compile import ShapeBucketer
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import model_io
from photon_ml_tpu.io.index_map import DELIMITER, INTERCEPT_KEY, feature_key
from photon_ml_tpu.io.offheap import (
    OffHeapIndexMap,
    SlabRowIndex,
    build_offheap_store,
    build_slab_index,
)
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.serve import quantize

logger = logging.getLogger(__name__)

STORE_FORMAT = "game-serve-store"
# version 2: optional quantized slabs (store_dtype + scale sidecars +
# pinned error budgets in meta). A version-1 store (no store_dtype key)
# still opens — it is exactly a version-2 f32 store.
STORE_VERSION = 2
META_FILE = "meta.json"
FEATURES_DIR = "features"
FIXED_DIR = "fixed"
RANDOM_DIR = "random"
ROWS_DIR = "rows"
SLAB_FILE = "slab.npy"
SCALES_FILE = "scales.npy"

#: on-disk slab dtype per store_dtype (bf16 travels as its raw bit
#: pattern so plain numpy can mmap it)
_DISK_DTYPE = {"f32": np.float32, "bf16": np.uint16, "int8": np.int8}


def _scan_records(model_dir: str, kind: str, name: str) -> List[dict]:
    return list(
        avro_io.read_directory(
            os.path.join(model_dir, kind, name, model_io.COEFFICIENTS)
        )
    )


def _record_keys(rec: dict) -> List[str]:
    """Feature keys named by one BayesianLinearModelAvro record (the
    intercept pseudo-feature is excluded — the index store carries its own
    intercept slot)."""
    out = []
    for section in ("means", "variances"):
        for ntv in rec.get(section) or []:
            if ntv["name"] == INTERCEPT_KEY and ntv["term"] == "":
                continue
            out.append(feature_key(ntv["name"], ntv["term"]))
    return out


def build_model_store(
    model_dir: str,
    store_dir: str,
    num_partitions: int = 1,
    bucketer: Optional[ShapeBucketer] = None,
    force_python: bool = False,
    entity_filter: Optional[Callable[[str], bool]] = None,
    store_dtype: str = "f32",
) -> dict:
    """Export a saved GAME model dir into the serving layout. Returns the
    written meta dict.

    ``store_dtype`` (``f32`` | ``bf16`` | ``int8``) selects the slab
    storage policy (:mod:`photon_ml_tpu.serve.quantize`): ``f32`` keeps
    the bitwise-to-the-batch-driver contract; the quantized dtypes trade
    a pinned, export-time-verified coefficient error budget for 2x/4x
    smaller slabs. Fixed-effect vectors stay f32 under every policy (they
    are ``(D,)`` and replicated — the slabs are the serving bytes).

    The feature space is scanned FROM THE MODEL ITSELF (every name/term its
    coefficient records mention) — no training inputs needed at export
    time. Features a request carries that the model never weighted resolve
    to index -1 and drop out, which contributes exactly the 0.0 their zero
    coefficient would have.

    ``entity_filter`` (serve/fleet sharded export) keeps only the matching
    random-effect entities in each slab while the feature vocabulary,
    feature index order, and fixed-effect vectors stay the FULL model's —
    every fleet replica agrees bitwise on the feature space and fixed
    coefficients, and owns only its slab partition.
    """
    quantize.validate_store_dtype(store_dtype)
    layout = model_io.list_game_model(model_dir)
    fixed_entries = []
    for name in layout[model_io.FIXED_EFFECT]:
        with open(
            os.path.join(model_dir, model_io.FIXED_EFFECT, name, model_io.ID_INFO)
        ) as f:
            shard = f.read().strip()
        fixed_entries.append((name, shard))
    random_entries = []
    for name in layout[model_io.RANDOM_EFFECT]:
        with open(
            os.path.join(model_dir, model_io.RANDOM_EFFECT, name, model_io.ID_INFO)
        ) as f:
            lines = f.read().splitlines()
        re_id = lines[0] if lines else ""
        shard = lines[1] if len(lines) > 1 else ""
        random_entries.append((name, re_id, shard))

    # pass 1: raw records per coordinate + per-shard feature vocabulary
    fixed_recs: Dict[str, dict] = {}
    random_recs: Dict[str, List[dict]] = {}
    shard_keys: Dict[str, set] = {}
    task = None
    for name, shard in fixed_entries:
        recs = _scan_records(model_dir, model_io.FIXED_EFFECT, name)
        fixed_recs[name] = recs[0]
        shard_keys.setdefault(shard, set()).update(_record_keys(recs[0]))
        task = task or recs[0].get("modelClass")
    for name, re_id, shard in random_entries:
        if model_io.is_factored_random_effect(model_dir, name):
            logger.warning(
                "random effect %r is factored: serving its projected-back "
                "coefficients (bitwise parity holds against the driver's "
                "--host-scoring oracle, not the latent-native device path)",
                name,
            )
        recs = _scan_records(model_dir, model_io.RANDOM_EFFECT, name)
        random_recs[name] = recs
        keys = shard_keys.setdefault(shard, set())
        for rec in recs:
            keys.update(_record_keys(rec))
        task = task or (recs[0].get("modelClass") if recs else None)

    os.makedirs(store_dir, exist_ok=True)

    # feature index stores (one per shard; the batch drivers open these
    # directly via --offheap-indexmap-dir <store_dir>/features)
    maps: Dict[str, OffHeapIndexMap] = {}
    for shard, keys in sorted(shard_keys.items()):
        fdir = os.path.join(store_dir, FEATURES_DIR, shard)
        build_offheap_store(
            fdir,
            sorted(keys),
            add_intercept=True,
            num_partitions=num_partitions,
            force_python=force_python,
        )
        maps[shard] = OffHeapIndexMap(fdir, force_python=force_python)

    meta: dict = {
        "format": STORE_FORMAT,
        "version": STORE_VERSION,
        "store_dtype": store_dtype,
        "task": model_io.schemas.TASK_BY_MODEL_CLASS.get(
            task, "LOGISTIC_REGRESSION"
        ),
        "source_model_dir": os.path.abspath(model_dir),
        "ladder": bucketer.describe() if bucketer is not None else None,
        "shards": {s: {"dim": len(m), "intercept": True} for s, m in maps.items()},
        "fixed": [],
        "random": [],
    }

    os.makedirs(os.path.join(store_dir, FIXED_DIR), exist_ok=True)
    for name, shard in fixed_entries:
        means, _ = model_io._record_to_dense(fixed_recs[name], maps[shard])
        np.save(
            os.path.join(store_dir, FIXED_DIR, f"{name}.npy"),
            means.astype(np.float32),
        )
        meta["fixed"].append({"name": name, "shard": shard})

    for name, re_id, shard in random_entries:
        base = os.path.join(store_dir, RANDOM_DIR, name)
        os.makedirs(base, exist_ok=True)
        recs = random_recs[name]
        if entity_filter is not None:
            recs = [r for r in recs if entity_filter(str(r["modelId"]))]
        entity_ids = sorted(str(rec["modelId"]) for rec in recs)
        build_slab_index(
            os.path.join(base, ROWS_DIR),
            entity_ids,
            num_partitions=num_partitions,
            force_python=force_python,
        )
        rows = SlabRowIndex(os.path.join(base, ROWS_DIR), force_python=force_python)
        n_entities = rows.num_rows
        padded = (
            bucketer.canon(max(n_entities, 1))
            if bucketer is not None
            else n_entities
        )
        slab = np.zeros((max(padded, 1), len(maps[shard])), np.float32)
        for rec in recs:
            row = rows.get_row(str(rec["modelId"]))
            means, _ = model_io._record_to_dense(rec, maps[shard])
            slab[row] = means
        rows.close()
        stored, scales = quantize.quantize_slab(slab, store_dtype)
        # the pinned-budget gate: realized error vs the analytic budget,
        # computed against the TRUE slab — an over-budget slab fails the
        # export here and never serves
        err_report = quantize.slab_error_report(
            slab, stored, scales, store_dtype
        )
        np.save(os.path.join(base, SLAB_FILE), stored)
        if scales is not None:
            np.save(os.path.join(base, SCALES_FILE), scales)
        meta["random"].append(
            {
                "name": name,
                "re_id": re_id,
                "shard": shard,
                "entities": n_entities,
                "padded_rows": int(stored.shape[0]),
                "quantization": err_report,
            }
        )

    for m in maps.values():
        m.close()
    tmp = os.path.join(store_dir, META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
    os.replace(tmp, os.path.join(store_dir, META_FILE))
    return meta


def is_model_store(path: str) -> bool:
    try:
        with open(os.path.join(path, META_FILE)) as f:
            return json.load(f).get("format") == STORE_FORMAT
    except (OSError, ValueError):
        return False


@dataclasses.dataclass
class FixedEffectSlab:
    name: str
    shard: str
    coefficients: np.ndarray  # (D,) f32 memmap


@dataclasses.dataclass
class RandomEffectSlab:
    name: str
    re_id: str
    shard: str
    rows: SlabRowIndex  # entity raw id -> slab row
    slab: np.ndarray  # (E_pad, D) memmap (f32 / bf16-as-uint16 / int8)
    entities: int  # real (unpadded) entity count
    store_dtype: str = "f32"
    scales: Optional[np.ndarray] = None  # (E_pad,) f32 memmap (int8 only)
    quantization: Optional[dict] = None  # realized/budget coeff error

    def dequantized(self) -> np.ndarray:
        """The f32 coefficient values the device kernels serve (for f32
        stores, the slab itself) — the host-oracle view of this slab."""
        return quantize.dequantize_slab(
            self.slab, self.scales, self.store_dtype
        )


class ModelStore:
    """One opened serving store: mmap'd coefficients + entity/feature
    lookups. Read-only and thread-safe after construction (every member is
    an immutable mmap or a mapped hash probe)."""

    def __init__(self, store_dir: str, force_python: bool = False):
        self.store_dir = os.path.abspath(store_dir)
        with open(os.path.join(store_dir, META_FILE)) as f:
            self.meta = json.load(f)
        if self.meta.get("format") != STORE_FORMAT:
            raise IOError(f"{store_dir} is not a {STORE_FORMAT} directory")
        if int(self.meta.get("version") or 1) > STORE_VERSION:
            raise IOError(
                f"{store_dir} is a version-{self.meta['version']} store; "
                f"this build reads <= {STORE_VERSION} — upgrade the serving "
                "binary before pointing it at this export"
            )
        # version-1 stores carry no store_dtype key: they are f32 exports
        self.store_dtype: str = self.meta.get("store_dtype") or "f32"
        quantize.validate_store_dtype(self.store_dtype)
        if self.store_dtype == "bf16":
            quantize._bf16()  # fail at OPEN, not first gather, if absent
        self.feature_maps: Dict[str, OffHeapIndexMap] = {
            shard: OffHeapIndexMap(
                os.path.join(store_dir, FEATURES_DIR, shard),
                force_python=force_python,
            )
            for shard in self.meta["shards"]
        }
        self.fixed: List[FixedEffectSlab] = [
            FixedEffectSlab(
                e["name"],
                e["shard"],
                np.load(
                    os.path.join(store_dir, FIXED_DIR, f"{e['name']}.npy"),
                    mmap_mode="r",
                ),
            )
            for e in self.meta["fixed"]
        ]
        self.random: List[RandomEffectSlab] = []
        for e in self.meta["random"]:
            base = os.path.join(store_dir, RANDOM_DIR, e["name"])
            slab = np.load(os.path.join(base, SLAB_FILE), mmap_mode="r")
            scales = self._open_quantized(base, e, slab)
            self.random.append(
                RandomEffectSlab(
                    e["name"],
                    e["re_id"],
                    e["shard"],
                    SlabRowIndex(
                        os.path.join(base, ROWS_DIR), force_python=force_python
                    ),
                    slab,
                    int(e["entities"]),
                    store_dtype=self.store_dtype,
                    scales=scales,
                    quantization=e.get("quantization"),
                )
            )

    def _open_quantized(
        self, base: str, entry: dict, slab: np.ndarray
    ) -> Optional[np.ndarray]:
        """Open-time dequantization gate for one coordinate: the slab's
        on-disk dtype, the recorded error budget, and (int8) the scale
        sidecar are all validated BEFORE the store can serve — a corrupt
        sidecar or over-budget meta fails the open actionably; it never
        degrades to serving garbage coefficients."""
        name = entry["name"]
        want = _DISK_DTYPE[self.store_dtype]
        if slab.dtype != want:
            raise IOError(
                f"store {self.store_dir} coordinate {name!r}: slab dtype "
                f"{slab.dtype} does not match store_dtype "
                f"{self.store_dtype!r} (expected {np.dtype(want)}); the "
                "export is inconsistent — re-export the store"
            )
        if self.store_dtype == "f32":
            return None
        faults.inject("serve.dequant", coordinate=name)
        q = entry.get("quantization") or {}
        realized = q.get("realized_max_abs_coeff_err")
        budget = q.get("coeff_err_budget")
        # `not (realized <= budget)` so a NaN smuggled into the meta (or
        # written by a pre-fix exporter from a NaN-corrupted slab) is
        # refused — NaN fails every comparison, including this gate's
        if realized is None or budget is None or not (realized <= budget):
            raise IOError(
                f"store {self.store_dir} coordinate {name!r}: quantized "
                f"slab has no valid pinned error budget in meta "
                f"(realized={realized!r}, budget={budget!r}); refusing to "
                "serve an unverified quantized export"
            )
        if self.store_dtype != "int8":
            return None
        try:
            scales = np.load(os.path.join(base, SCALES_FILE), mmap_mode="r")
        except (OSError, ValueError) as e:
            raise IOError(
                f"store {self.store_dir} coordinate {name!r}: int8 scale "
                f"sidecar {SCALES_FILE} is missing or unreadable ({e}); "
                "the store cannot dequantize — re-export it"
            ) from e
        if (
            scales.dtype != np.float32
            or scales.shape != (slab.shape[0],)
            or not bool(np.all(np.isfinite(scales)))
            or not bool(np.all(np.asarray(scales) > 0))
        ):
            raise IOError(
                f"store {self.store_dir} coordinate {name!r}: int8 scale "
                f"sidecar is corrupt (dtype {scales.dtype}, shape "
                f"{scales.shape}; scales must be finite and > 0); "
                "refusing to serve garbage coefficients — re-export the "
                "store"
            )
        return scales

    # -- lookups ------------------------------------------------------------
    def shard_dim(self, shard: str) -> int:
        return len(self.feature_maps[shard])

    def feature_index(self, shard: str, key: str) -> int:
        return self.feature_maps[shard].get_index(key)

    def entity_row(self, coordinate: str, raw_id: Optional[str]) -> int:
        """Slab row of ``raw_id`` for a random-effect coordinate; -1 when
        the entity has no model (its contribution is 0 —
        RandomEffectModel.scala:129-158 semantics)."""
        if raw_id is None:
            return -1
        for re in self.random:
            if re.name == coordinate:
                return re.rows.get_row(str(raw_id))
        raise KeyError(f"no random-effect coordinate {coordinate!r} in store")

    def features_dir(self) -> str:
        """The per-shard feature index stores — hand this to the batch
        scoring driver as ``--offheap-indexmap-dir`` so both paths score
        through an identical feature space."""
        return os.path.join(self.store_dir, FEATURES_DIR)

    def footprint(self) -> dict:
        """Store-footprint gauges for :class:`~photon_ml_tpu.serve.stats.
        ServeStats`: slab bytes on disk (slab files + scale sidecars
        ONLY — the quantization dial's denominator; fixed-effect vectors
        are f32 under every policy), bytes mapped into this process
        (slabs + scales + fixed), and the storage dtype."""
        disk = 0
        mapped = 0
        for f in self.fixed:
            mapped += int(f.coefficients.nbytes)
        for r in self.random:
            base = os.path.join(self.store_dir, RANDOM_DIR, r.name)
            mapped += int(r.slab.nbytes)
            disk += self._file_size(os.path.join(base, SLAB_FILE))
            if r.scales is not None:
                mapped += int(r.scales.nbytes)
                disk += self._file_size(os.path.join(base, SCALES_FILE))
        return {
            "slab_bytes_disk": disk,
            "mapped_bytes": mapped,
            "store_dtype": self.store_dtype,
        }

    @staticmethod
    def _file_size(path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def describe(self) -> str:
        re_desc = ", ".join(
            f"{r.name}({r.entities} entities, slab {tuple(r.slab.shape)})"
            for r in self.random
        )
        fp = self.footprint()
        return (
            f"model store {self.store_dir} "
            f"[{self.store_dtype}, {fp['slab_bytes_disk']} slab bytes]: "
            f"{len(self.fixed)} fixed / {len(self.random)} random "
            f"[{re_desc}]"
        )

    def close(self) -> None:
        for m in self.feature_maps.values():
            m.close()
        for r in self.random:
            r.rows.close()
        self.feature_maps = {}
        self.fixed = []
        self.random = []

    # -- checkpoint by-reference protocol (photon_ml_tpu/checkpoint.py) ----
    def __checkpoint_ref__(self) -> dict:
        return {
            "kind": STORE_FORMAT,
            "version": STORE_VERSION,
            "store_dir": self.store_dir,
        }

    def __checkpoint_from_ref__(self, ref: dict) -> "ModelStore":
        if not isinstance(ref, dict) or ref.get("kind") != STORE_FORMAT:
            raise CheckpointRefError(
                f"not a {STORE_FORMAT} reference: {ref!r}"
            )
        store_dir = ref.get("store_dir", "")
        if not is_model_store(store_dir):
            raise CheckpointRefError(
                f"serve-store reference points at {store_dir!r}, which is "
                "missing or not a store — it may have been retired; refusing "
                "to swap"
            )
        return ModelStore(store_dir)
