"""Zero-downtime model roll for the scoring server.

Production GLMix retrains daily; the serving fleet must pick the new model
up WITHOUT a restart (a restart pays model load + warmup and drops every
open connection). The swapper rolls a live :class:`~photon_ml_tpu.serve.
server.ScoringServer` to a new :class:`~photon_ml_tpu.serve.model_store.
ModelStore` through the checkpoint by-reference protocol
(:func:`photon_ml_tpu.checkpoint.rebuild_from_ref` — the same path a
streaming checkpoint's spilled-coefficient leaves restore through):

  1. REBUILD: the new store opens from its ref (a handful of mmaps; a
     stale/missing ref raises ``CheckpointRefError`` — the old model keeps
     serving).
  2. VALIDATE: coordinate names, feature dims, and padded slab shapes are
     compared against the live bundle. Matching shapes (the point of
     padding slab rows up the shape ladder) mean every compiled executable
     is reused — the swap is compile-free.
  3. UPLOAD + FLIP: device arrays are prepared OUTSIDE the lock, then the
     current-bundle pointer flips atomically. Requests featurized against
     the old generation stay PINNED to it through the batcher (their
     entity rows index the old slab layout), so nothing is dropped or
     mis-scored mid-roll.
  4. PROBE + RETIRE: a zero batch scored against the new bundle proves the
     no-new-compiles claim (watermark-asserted); after a drain fence the
     old store's mmaps close.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Union

from photon_ml_tpu.checkpoint import CheckpointRefError, rebuild_from_ref
from photon_ml_tpu.compile import compile_stats
from photon_ml_tpu.serve.model_store import (
    STORE_FORMAT,
    STORE_VERSION,
    ModelStore,
)
from photon_ml_tpu.serve.server import ScoringServer

logger = logging.getLogger(__name__)


class ModelSwapper:
    """Serialized (one roll at a time) model swaps for one server."""

    def __init__(self, server: ScoringServer, drain_timeout_s: float = 60.0):
        self.server = server
        self.drain_timeout_s = drain_timeout_s

    def _resolve(self, target: Union[str, dict]) -> ModelStore:
        """A store dir or a checkpoint ref -> an opened ModelStore, via the
        by-reference rebuild (the current store is the template leaf)."""
        ref = (
            target
            if isinstance(target, dict)
            else {
                "kind": STORE_FORMAT,
                "version": STORE_VERSION,
                "store_dir": os.path.abspath(str(target)),
            }
        )
        return rebuild_from_ref(self.server.store, ref)

    def validate_compatible(self, new_store: ModelStore) -> list:
        """Shape/coordinate mismatches vs the live model (each one is a
        future recompile or a refused swap; empty = compile-free roll)."""
        cur = self.server.store
        problems = []
        if cur.store_dtype != new_store.store_dtype:
            problems.append(
                f"store dtype changed: {cur.store_dtype} -> "
                f"{new_store.store_dtype} (the gather kernels re-trace on "
                "the new slab dtype; the first post-swap batch compiles)"
            )
        if sorted(cur.feature_maps) != sorted(new_store.feature_maps):
            problems.append(
                f"feature shards changed: {sorted(cur.feature_maps)} -> "
                f"{sorted(new_store.feature_maps)}"
            )
        for shard in set(cur.feature_maps) & set(new_store.feature_maps):
            if len(cur.feature_maps[shard]) != len(new_store.feature_maps[shard]):
                problems.append(
                    f"shard {shard!r} dim {len(cur.feature_maps[shard])} -> "
                    f"{len(new_store.feature_maps[shard])}"
                )
        cur_re = {r.name: r for r in cur.random}
        new_re = {r.name: r for r in new_store.random}
        if sorted(cur_re) != sorted(new_re):
            problems.append(
                f"random-effect coordinates changed: {sorted(cur_re)} -> "
                f"{sorted(new_re)}"
            )
        for name in set(cur_re) & set(new_re):
            if cur_re[name].slab.shape != new_re[name].slab.shape:
                problems.append(
                    f"coordinate {name!r} slab {cur_re[name].slab.shape} -> "
                    f"{new_re[name].slab.shape} (entity count crossed a "
                    "ladder rung; the first post-swap batch recompiles)"
                )
        if [f.name for f in cur.fixed] != [f.name for f in new_store.fixed]:
            problems.append(
                f"fixed-effect coordinates changed: "
                f"{[f.name for f in cur.fixed]} -> "
                f"{[f.name for f in new_store.fixed]}"
            )
        return problems

    def swap(
        self,
        target: Union[str, dict],
        require_compatible: bool = False,
        probe: bool = True,
        retire_old: bool = True,
    ) -> dict:
        """Roll the server to ``target`` (store dir or checkpoint ref).

        Returns a report: ``{"generation", "store_dir", "shape_compatible",
        "problems", "new_compiles", "dropped_requests"}`` —
        ``dropped_requests`` is definitionally 0 (pinned generations), kept
        in the report so monitoring has the explicit claim to alert on.
        """
        new_store = self._resolve(target)
        problems = self.validate_compatible(new_store)
        if problems and require_compatible:
            new_store.close()
            raise CheckpointRefError(
                "refusing incompatible swap: " + "; ".join(problems)
            )
        for p in problems:
            logger.warning("model swap shape change: %s", p)

        old_bundle = self.server.install_bundle(new_store)
        new_compiles = 0
        if probe:
            # prove the claim NOW (not on the first unlucky request): one
            # zero batch at the smallest warmed rung through the new
            # bundle. The watermark brackets ONLY the probe — a concurrent
            # request's documented first-sight compile (nnz past the
            # warmed rungs) must not be booked as a swap compile.
            wm = compile_stats.watermark()
            self._probe(self.server.model)
            new_compiles = wm.new_traces()
        if retire_old:
            # per-generation fence: waits only on requests pinned to the
            # OLD bundle (new-generation traffic cannot starve it — a
            # busy server still retires the old store promptly). The
            # drain->retire pair loops because a submit that read the old
            # bundle pre-flip may pin it between the two; retire_if_idle
            # is atomic, so once it returns True no pin can follow.
            deadline = time.monotonic() + self.drain_timeout_s
            retired = False
            while not retired:
                remaining = deadline - time.monotonic()
                if not old_bundle.drain(max(remaining, 0.0)):
                    break
                retired = old_bundle.retire_if_idle()
            if retired:
                old_bundle.store.close()
            else:
                logger.warning(
                    "old model generation %d still has in-flight requests "
                    "after %.0fs; leaving its store open",
                    old_bundle.generation, self.drain_timeout_s,
                )
        report = {
            "generation": self.server.model.generation,
            "store_dir": new_store.store_dir,
            "shape_compatible": not problems,
            "problems": problems,
            "new_compiles": int(new_compiles),
            "dropped_requests": 0,
        }
        self.server.stats.record_swap(int(new_compiles))
        logger.info(
            "model swap -> generation %d (%s; %d new compiles)",
            report["generation"],
            "shape-compatible" if not problems else "SHAPES CHANGED",
            report["new_compiles"],
        )
        return report

    def _probe(self, bundle) -> None:
        server = self.server
        n = server._ladder_rungs(1, 1)[0] if server.bucketer else 1
        k = server.bucketer.canon(1) if server.bucketer else 1
        server._score_with(bundle, server._zero_batch(bundle, n, k))
