"""Persistent low-latency GAME scoring server.

A warm process that loads a model once and answers scoring requests with
ZERO per-request work beyond the math:

  * coefficients come from the mmap'd :class:`~photon_ml_tpu.serve.
    model_store.ModelStore` (no Avro parse, no dict densify — open is a
    handful of mmaps, per-entity lookup is a hash probe in mapped memory);
  * concurrent requests coalesce in the :class:`~photon_ml_tpu.serve.
    batcher.MicroBatcher` onto the canonical shape ladder, so every batch
    shape hits a small fixed set of compiled executables;
  * startup goes through ``compat.enable_persistent_cache`` + an explicit
    :meth:`ScoringServer.warmup` over the ladder rungs — a warm start
    reports **zero new XLA compiles** (asserted via ``compile_stats``);
  * a live model roll goes through :class:`~photon_ml_tpu.serve.swap.
    ModelSwapper` (the checkpoint by-reference protocol) without dropping
    in-flight requests or recompiling.

Scoring math mirrors ``cli/game_scoring_driver`` EXACTLY — the random-
effect kernel is literally the driver's ``_re_gather_contrib_impl`` under
``instrumented_jit``, the fixed-effect kernel is ``SparseFeatures.matvec``
over the same pad-col-0 COO convention, and contributions accumulate in
the same coordinate order — so served scores are bitwise-equal to the
batch driver's output for the same inputs (pinned by tests/test_serve.py
and the ``bench.py serving`` arm).

Request wire format (JSON-lines on stdin via :func:`serve_json_lines`, or
the in-process :meth:`ScoringServer.score_rows` API):

    {"id": "r1", "rows": [{"features": {"<section>": [{"name": ...,
        "term": ..., "value": ...}, ...]}, "ids": {"<idType>": "<raw>"},
        "offset": 0.0}, ...]}
    -> {"id": "r1", "scores": [...]}

Control lines: ``{"cmd": "stats"}``, ``{"cmd": "swap", "store_dir": ...}``,
``{"cmd": "shutdown"}``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu.compile import ShapeBucketer, compile_stats, instrumented_jit, resolve_bucketer
from photon_ml_tpu.io.index_map import feature_key
from photon_ml_tpu.serve.batcher import MicroBatcher, RowBatch
from photon_ml_tpu.serve.model_store import ModelStore
from photon_ml_tpu.serve.stats import ServeStats, serve_stats

logger = logging.getLogger(__name__)

#: default nnz cap the warmup assumes per shard (requests wider than the
#: warmed rungs still work — they just pay one compile on first sight)
DEFAULT_WARM_NNZ = 64


def _fixed_contrib_impl(w, idx, vals):
    """sum_k vals_nk * w[idx_nk] — SparseFeatures.matvec over the pad-col-0
    COO convention (identical math to the batch scoring driver's jitted
    ``feats.matvec(w)``)."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.features import _acc_dtype

    acc = _acc_dtype(vals.dtype)
    return jnp.sum(w[idx].astype(acc) * vals.astype(acc), axis=-1)


def _re_gather_dequant_impl(slab, scales, ent_pos, idx, vals):
    """Quantized-store variant of the driver's ``_re_gather_contrib_impl``:
    gather the stored elements (bf16 or int8), dequantize ON the gathered
    ``(n, k)`` tile — widen to f32, multiply by the per-slab-row scale —
    then the identical masked K-sum. Only the gathered elements ever
    widen; the resident slab stays at its storage width on device. For
    bf16 stores ``scales`` is all-ones (``x * 1.0`` is exact in f32, so
    one kernel body serves both quantized dtypes; the executables differ
    by slab input dtype exactly as the ladder expects)."""
    import jax.numpy as jnp

    safe_e = jnp.maximum(ent_pos, 0)
    gathered = slab[safe_e[:, None], idx].astype(jnp.float32)
    gathered = gathered * scales[safe_e][:, None]
    valid = ent_pos[:, None] >= 0
    return jnp.sum(jnp.where(valid, gathered * vals, 0.0), axis=-1)


def _concat_futures(parts: List) -> "Future":
    """One Future resolving to the row-concatenation of ``parts`` (first
    part failure wins; remaining parts are ignored once failed)."""
    from concurrent.futures import Future

    combined: Future = Future()
    results: List[Optional[np.ndarray]] = [None] * len(parts)
    remaining = [len(parts)]
    lock = threading.Lock()

    def on_part(i: int, fut) -> None:
        try:
            results[i] = fut.result()
        except Exception as e:  # noqa: BLE001 — fan the failure to the caller
            with lock:
                if not combined.done():
                    combined.set_exception(e)
            return
        with lock:
            remaining[0] -= 1
            if remaining[0] == 0 and not combined.done():
                combined.set_result(np.concatenate(results))

    for i, fut in enumerate(parts):
        fut.add_done_callback(lambda f, i=i: on_part(i, f))
    return combined


@dataclasses.dataclass
class _ModelBundle:
    """One model generation resident on device: read-only coefficient
    arrays + the host-side lookup handles that featurized this generation's
    requests. Never mutated — a swap installs a NEW bundle. Requests pinned
    to the generation are counted in/out so the swapper's retire fence
    waits only on THIS generation (global batcher idleness never happens
    under sustained traffic)."""

    generation: int
    store: ModelStore
    fixed: List[tuple]  # (name, shard, w_dev)
    random: List[tuple]  # (name, re_id, shard, slab_dev, scales_dev|None)
    score_fn: Optional[Callable] = None  # bound by the server after build
    _inflight: int = 0
    _retired: bool = False
    _lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    _idle: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )

    def __post_init__(self):
        self._idle.set()

    def begin_request(self) -> bool:
        """Pin one request to this generation; False once retired (the
        caller must re-read the current bundle and pin THAT — closes the
        read-then-pin race against a concurrent swap's store close)."""
        with self._lock:
            if self._retired:
                return False
            self._inflight += 1
            self._idle.clear()
            return True

    def end_request(self, _fut=None) -> None:
        with self._lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.set()

    def retire_if_idle(self) -> bool:
        """Atomically mark retired iff nothing is pinned; after True no
        begin_request can succeed, so the store is safe to close."""
        with self._lock:
            if self._inflight:
                return False
            self._retired = True
            return True

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until no request is featurizing against or queued for this
        generation (then its store's mmaps are safe to close)."""
        return self._idle.wait(timeout)


class ScoringServer:
    """In-process scoring API + the engine under the JSON-lines loop."""

    def __init__(
        self,
        store: ModelStore,
        shard_sections: Optional[Dict[str, List[str]]] = None,
        bucketer: "Optional[ShapeBucketer | str | bool]" = "on",
        max_batch_rows: int = 128,
        max_wait_ms: float = 2.0,
        stats: Optional[ServeStats] = None,
    ):
        # the ladder defaults ON here (unlike training): a serving process
        # lives or dies by executable reuse across arbitrary request sizes
        self.bucketer = resolve_bucketer(bucketer)
        self.shard_sections = shard_sections or {}
        self.stats = stats if stats is not None else serve_stats
        compile_stats.install_xla_listeners()
        self._fixed_kernel = instrumented_jit(
            _fixed_contrib_impl, site="serve.fixed_contrib"
        )
        # the EXACT driver kernel body — parity by construction
        from photon_ml_tpu.cli.game_scoring_driver import _re_gather_contrib_impl

        self._re_kernel = instrumented_jit(
            _re_gather_contrib_impl, site="serve.re_gather"
        )
        # quantized stores gather through the dequantize variant under the
        # SAME instrumented site — warm-swap accounting and the ladder see
        # one gather site whatever the storage dtype; the f32 default
        # keeps the untouched driver kernel (bitwise by construction)
        self._re_dequant_kernel = instrumented_jit(
            _re_gather_dequant_impl, site="serve.re_gather"
        )
        self._generation = 0
        self._swap_lock = threading.Lock()
        self._model = self._build_bundle(store)
        # footprint gauges update at INSTALL, not bundle build — a staged
        # fleet bundle whose swap aborts must not leave the stats
        # describing a store that never served
        self.stats.record_store_footprint(**store.footprint())
        # the default scores against the CURRENT generation at call time —
        # binding a specific bundle's closure here would pin generation 1's
        # device slabs (and its store) for the server's whole life
        self.batcher = MicroBatcher(
            lambda batch: self._score_with(self._model, batch),
            max_batch_rows=max_batch_rows,
            max_wait_ms=max_wait_ms,
            bucketer=self.bucketer,
            stats=self.stats,
        ).start()
        self._request_watermark = compile_stats.watermark()

    # -- model install / swap ----------------------------------------------
    def _build_bundle(self, store: ModelStore) -> _ModelBundle:
        """Upload a store's coefficients to the device (outside any lock —
        slow) and bind its scoring closure. Quantized slabs upload AT
        their storage width (bf16/int8 device residency — the footprint
        win travels to the device) plus the f32 scale vector; dequantize
        happens per gathered element inside the kernel."""
        import jax.numpy as jnp

        self._generation += 1
        random = []
        for r in store.random:
            if r.store_dtype == "f32":
                entry = (jnp.asarray(r.slab, jnp.float32), None)
            elif r.store_dtype == "bf16":
                from photon_ml_tpu.serve.quantize import _bf16

                entry = (
                    jnp.asarray(np.asarray(r.slab).view(_bf16())),
                    jnp.ones(r.slab.shape[0], jnp.float32),
                )
            else:  # int8
                entry = (
                    jnp.asarray(r.slab, jnp.int8),
                    jnp.asarray(r.scales, jnp.float32),
                )
            random.append((r.name, r.re_id, r.shard) + entry)
        bundle = _ModelBundle(
            generation=self._generation,
            store=store,
            fixed=[
                (f.name, f.shard, jnp.asarray(f.coefficients, jnp.float32))
                for f in store.fixed
            ],
            random=random,
        )
        bundle.score_fn = lambda batch: self._score_with(bundle, batch)
        return bundle

    def install_bundle(self, store: ModelStore) -> _ModelBundle:
        """Atomically make ``store`` the current model; returns the OLD
        bundle (still valid for any in-flight request pinned to it — the
        swapper retires it after a drain)."""
        new = self._build_bundle(store)
        with self._swap_lock:
            old, self._model = self._model, new
        self.stats.record_store_footprint(**store.footprint())
        return old

    @property
    def model(self) -> _ModelBundle:
        return self._model

    @property
    def store(self) -> ModelStore:
        return self._model.store

    # -- scoring -------------------------------------------------------------
    def _score_with(self, bundle: _ModelBundle, batch: RowBatch) -> np.ndarray:
        """Device scoring of one padded batch against one model generation.
        Mirrors GameScoringDriver._score_device: total starts at the
        offset, fixed-effect contributions add first, then random effects,
        each through its own jitted kernel with eager f32 adds between —
        the exact op sequence the batch driver runs."""
        import jax
        import jax.numpy as jnp

        # one upload per shard, shared by every coordinate on that shard
        # (fixed + random on one shard must not pay the H2D copy twice)
        idx_dev = {s: jnp.asarray(a) for s, a in batch.shard_idx.items()}
        val_dev = {s: jnp.asarray(a) for s, a in batch.shard_val.items()}
        total = jnp.asarray(batch.offset, jnp.float32)
        for _name, shard, w in bundle.fixed:
            total = total + self._fixed_kernel(w, idx_dev[shard], val_dev[shard])
        for name, _re_id, shard, slab, scales in bundle.random:
            total = total + self._re_contrib(
                slab,
                scales,
                jnp.asarray(batch.ent_row[name]),
                idx_dev[shard],
                val_dev[shard],
            )
        return np.asarray(jax.device_get(total))

    def _re_contrib(self, slab, scales, ent_dev, idx_dev, val_dev):
        """One random-effect coordinate's contribution: the untouched f32
        driver kernel when the slab is f32 (bitwise contract), the
        dequantize-on-gather kernel for bf16/int8 slabs."""
        if scales is None:
            return self._re_kernel(slab, ent_dev, idx_dev, val_dev)
        return self._re_dequant_kernel(slab, scales, ent_dev, idx_dev, val_dev)

    def featurize(
        self, rows: List[dict], bundle: Optional[_ModelBundle] = None
    ) -> RowBatch:
        """Request rows -> host COO against a model generation's feature
        space. Per-row feature order matches the batch driver's ingest
        (sections in configured order, record order within a section,
        intercept appended last) so the per-row K-sum is term-for-term the
        driver's."""
        bundle = bundle or self._model
        store = bundle.store
        n = len(rows)
        offsets = np.zeros(n, np.float32)
        per_shard: Dict[str, List[List[tuple]]] = {
            s: [] for s in store.feature_maps
        }
        for i, row in enumerate(rows):
            offsets[i] = float(row.get("offset") or 0.0)
            feats = row.get("features") or {}
            if isinstance(feats, list):  # bare list = the default section
                feats = {"features": feats}
            for shard, imap in store.feature_maps.items():
                entries = []
                for section in self.shard_sections.get(shard) or ["features"]:
                    for f in feats.get(section) or []:
                        j = imap.get_index(
                            feature_key(f.get("name", ""), f.get("term", ""))
                        )
                        if j >= 0:
                            entries.append((j, float(f["value"])))
                if imap.intercept_index >= 0:
                    entries.append((imap.intercept_index, 1.0))
                per_shard[shard].append(entries)
        shard_idx, shard_val = {}, {}
        for shard, rows_entries in per_shard.items():
            k = max((len(e) for e in rows_entries), default=1) or 1
            idx = np.zeros((n, k), np.int32)
            val = np.zeros((n, k), np.float32)
            for i, entries in enumerate(rows_entries):
                for slot, (j, v) in enumerate(entries):
                    idx[i, slot] = j
                    val[i, slot] = v
            shard_idx[shard] = idx
            shard_val[shard] = val
        ent_row = {}
        for re in store.random:
            ids = np.full(n, -1, np.int32)
            for i, row in enumerate(rows):
                raw = (row.get("ids") or {}).get(re.re_id)
                ids[i] = re.rows.get_row(str(raw)) if raw is not None else -1
            ent_row[re.name] = ids
        return RowBatch(
            offset=offsets, shard_idx=shard_idx, shard_val=shard_val,
            ent_row=ent_row,
        )

    def submit_rows(self, rows: List[dict]):
        """Non-blocking scoring: featurize against the CURRENT generation
        and pin the request to it. Returns a Future of (n,) scores.

        A request wider than ``max_batch_rows`` is split into cap-sized
        sub-batches (scores are row-independent, so the concatenation is
        bit-identical) — one giant request must not form a batch padded
        past the top warmed ladder rung and pay a hot-path compile."""
        cap = self.batcher.max_batch_rows
        if len(rows) > cap:
            parts = [
                self.submit_rows(rows[i : i + cap])
                for i in range(0, len(rows), cap)
            ]
            return _concat_futures(parts)
        while True:
            bundle = self._model  # the pin travels with the batch
            if bundle.begin_request():
                break
            # lost the race with a swap retiring this generation; the
            # CURRENT bundle (never retired while installed) is next read
        try:
            batch = self.featurize(rows, bundle)
            fut = self.batcher.submit(batch, score_fn=bundle.score_fn)
        except BaseException:  # noqa: BLE001 — unpin-and-reraise: the generation pin must not leak on ANY failure (incl. KeyboardInterrupt), or swap's drain fence waits forever
            bundle.end_request()
            raise
        fut.add_done_callback(bundle.end_request)
        return fut

    def score_rows(self, rows: List[dict]) -> np.ndarray:
        if not rows:
            return np.zeros(0, np.float32)
        return self.submit_rows(rows).result()

    # -- warmup / compile accounting -----------------------------------------
    def _zero_batch(self, bundle: _ModelBundle, n: int, k: int) -> RowBatch:
        """Synthetic all-zero (n rows, k nnz) batch shaped like a real
        featurized request against ``bundle`` — the ONE batch layout the
        warmup rungs and the swap probe both score (so a layout change
        cannot diverge between them)."""
        return RowBatch(
            offset=np.zeros(n, np.float32),
            shard_idx={
                s: np.zeros((n, k), np.int32)
                for s in bundle.store.feature_maps
            },
            shard_val={
                s: np.zeros((n, k), np.float32)
                for s in bundle.store.feature_maps
            },
            ent_row={
                r.name: np.full(n, -1, np.int32) for r in bundle.store.random
            },
        )

    def _ladder_rungs(self, lo: int, hi: int) -> List[int]:
        if self.bucketer is None:
            return [hi]
        rungs, r = [], self.bucketer.canon(max(lo, 1))
        top = self.bucketer.canon(hi)
        while True:
            rungs.append(r)
            if r >= top:
                return rungs
            r = self.bucketer.canon(r + 1)

    def warmup(self, warm_nnz: Optional[int] = None) -> dict:
        """Pre-score synthetic zero batches at every (batch-rows, nnz)
        ladder rung the request path can produce, so steady-state requests
        never compile. Under a warm persistent cache every one of these
        compiles is a cache HIT — the driver then logs "fully warm: zero
        new XLA compiles"."""
        wm = compile_stats.watermark()
        max_dim = max(
            (len(m) for m in self.store.feature_maps.values()), default=1
        )
        cap = min(max_dim, warm_nnz or DEFAULT_WARM_NNZ)
        n_rungs = self._ladder_rungs(1, self.batcher.max_batch_rows)
        k_rungs = self._ladder_rungs(1, cap)
        bundle = self._model
        batches = 0
        for n in n_rungs:
            for k in k_rungs:
                self._score_with(bundle, self._zero_batch(bundle, n, k))
                batches += 1
        self._request_watermark = compile_stats.watermark()
        return {
            "warm_batches": batches,
            "row_rungs": n_rungs,
            "nnz_rungs": k_rungs,
            "new_traces": wm.new_traces(),
            "new_xla_misses": wm.new_xla_misses(),
        }

    def fully_warm(self) -> bool:
        """True when the whole process start compiled NOTHING new in XLA
        (every executable came from the persistent cache)."""
        return compile_stats.xla_cache_misses == 0

    def new_request_compiles(self) -> int:
        """Traces since warmup finished — nonzero means a request shape
        escaped the warmed ladder (widen warm_nnz / max_batch_rows)."""
        return self._request_watermark.new_traces()

    # ------------------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        return self.batcher.drain(timeout)

    def close(self) -> None:
        self.batcher.close()
        self._model.store.close()


def serve_json_lines(
    server: ScoringServer,
    in_stream,
    out_stream,
    swapper=None,
) -> int:
    """Blocking JSON-lines request loop (no network framework — pipe the
    server behind whatever transport the deployment has). Returns the
    number of scoring requests handled. Responses are written in COMPLETION
    order (micro-batching reorders under concurrency) and always carry the
    request's ``id``."""
    handled = 0
    # fence on RESPONSES ENQUEUED, not futures resolved: the batcher's idle
    # event flips on the first done-callback, but the response enqueue is a
    # later callback — draining the batcher alone could return with the
    # last response still pending
    resp_lock = threading.Lock()
    resp_outstanding = 0
    resp_idle = threading.Event()
    resp_idle.set()
    # responses are WRITTEN by a dedicated thread: done-callbacks run on
    # the batcher's scoring worker, and a consumer that stops reading the
    # out stream must stall only this queue, never the device loop
    resp_q: "queue.Queue[Optional[dict]]" = queue.Queue()

    def _writer() -> None:
        while True:
            payload = resp_q.get()
            if payload is None:
                return
            out_stream.write(json.dumps(payload) + "\n")
            out_stream.flush()

    writer = threading.Thread(
        target=_writer, name="photon-serve-responder", daemon=True
    )
    writer.start()

    def respond(payload: dict) -> None:
        resp_q.put(payload)

    def on_done(req_id, fut) -> None:
        nonlocal resp_outstanding
        try:
            scores = fut.result()
            respond({"id": req_id, "scores": [float(s) for s in scores]})
        except Exception as e:  # noqa: BLE001 — a bad request must not kill the loop
            respond({"id": req_id, "error": f"{type(e).__name__}: {e}"})
        finally:
            with resp_lock:
                resp_outstanding -= 1
                if resp_outstanding == 0:
                    resp_idle.set()

    for line in in_stream:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError as e:
            respond({"error": f"bad JSON: {e}"})
            continue
        cmd = msg.get("cmd")
        if cmd == "shutdown":
            break
        if cmd == "stats":
            respond(
                {
                    "id": msg.get("id"),
                    "stats": server.stats.snapshot(),
                    "new_request_compiles": server.new_request_compiles(),
                }
            )
            continue
        if cmd == "swap":
            if swapper is None:
                respond({"id": msg.get("id"), "error": "no swapper configured"})
                continue
            try:
                report = swapper.swap(msg.get("store_dir", ""))
                respond({"id": msg.get("id"), "swap": report})
            except Exception as e:  # noqa: BLE001 — a bad swap must not kill serving
                respond({"id": msg.get("id"), "error": f"{type(e).__name__}: {e}"})
            continue
        rows = msg.get("rows")
        if not isinstance(rows, list) or not rows:
            respond({"id": msg.get("id"), "error": "request needs a non-empty 'rows' list"})
            continue
        try:
            fut = server.submit_rows(rows)
        except Exception as e:  # noqa: BLE001 — malformed rows fail THIS request only
            respond({"id": msg.get("id"), "error": f"{type(e).__name__}: {e}"})
            continue
        handled += 1
        with resp_lock:
            resp_outstanding += 1
            resp_idle.clear()
        fut.add_done_callback(
            lambda f, req_id=msg.get("id"): on_done(req_id, f)
        )
    server.drain()
    resp_idle.wait()
    resp_q.put(None)  # after every enqueue: writer drains, then exits
    writer.join()
    return handled
