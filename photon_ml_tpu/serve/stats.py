"""Serving telemetry: request latency percentiles, batch fill, QPS.

The registry mirrors :mod:`photon_ml_tpu.compile.stats` — a thread-safe
process-wide instance (``serve_stats``) every server records into, a
``snapshot()`` the tests/bench assert on, and a one-screen ``summary()``
the serve driver logs next to ``compile_stats.summary()``.

What gets recorded:

  * per REQUEST: end-to-end latency (submit -> response ready), row count.
    Latencies feed a bounded-memory streaming digest
    (:class:`photon_ml_tpu.slo.quantiles.StreamingQuantileDigest`):
    exact nearest-rank percentiles up to ``max_samples`` raw samples
    (bit-identical to the old sorted-deque accounting), then O(1) P²
    estimation over EVERY sample since the last reset — a day-long
    million-request run keeps honest p50/p99 without holding a latency
    per request or silently windowing to the newest samples.
  * per BATCH: real rows vs ladder-padded rows (the fill ratio — how much
    of each canonical executable's work was real) and the number of
    requests coalesced into it (avg requests/batch is THE number the
    micro-batcher exists to raise).
  * swaps: count + whether each was compile-free.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from photon_ml_tpu.slo.quantiles import StreamingQuantileDigest


class ServeStats:
    """Thread-safe serving-telemetry registry (batcher worker, responder
    threads, and in-process callers all record concurrently)."""

    def __init__(self, max_samples: int = 100_000):
        self._lock = threading.Lock()
        # max_samples bounds the EXACT regime: up to that many raw
        # latencies are kept (and percentiles are exact nearest-rank,
        # the historical behavior); past it the digest flips to P²
        # markers seeded from the exact sample and memory stays O(1)
        self._latencies = StreamingQuantileDigest(
            (0.50, 0.99), exact_limit=max_samples
        )
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batch_rows_real = 0
        self.batch_rows_padded = 0
        self.batch_requests = 0
        self.errors = 0
        self.swaps = 0
        self.swap_compiles = 0
        # store-footprint gauges (set at bundle install, overwritten by a
        # swap — they always describe the CURRENTLY serving store)
        self.store_slab_bytes = 0
        self.store_mapped_bytes = 0
        self.store_dtype: Optional[str] = None
        self._first_ts: Optional[float] = None
        self._last_ts: Optional[float] = None

    # -- recording ----------------------------------------------------------
    def record_request(self, latency_s: float, num_rows: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._latencies.add(latency_s)
            self.requests += 1
            self.rows += num_rows
            if self._first_ts is None:
                self._first_ts = now
            self._last_ts = now

    def record_batch(self, rows_real: int, rows_padded: int, num_requests: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows_real += rows_real
            self.batch_rows_padded += rows_padded
            self.batch_requests += num_requests

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_swap(self, new_compiles: int) -> None:
        with self._lock:
            self.swaps += 1
            self.swap_compiles += new_compiles

    def record_store_footprint(
        self, slab_bytes_disk: int, mapped_bytes: int, store_dtype: str
    ) -> None:
        """Gauge update from :meth:`ModelStore.footprint` — recorded at
        every bundle install so the summary always shows the bytes and
        dtype of the store actually serving."""
        with self._lock:
            self.store_slab_bytes = int(slab_bytes_disk)
            self.store_mapped_bytes = int(mapped_bytes)
            self.store_dtype = store_dtype

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            span = (
                (self._last_ts - self._first_ts)
                if self._first_ts is not None and self._last_ts is not None
                else 0.0
            )
            return {
                "requests": self.requests,
                "rows": self.rows,
                "errors": self.errors,
                "batches": self.batches,
                "p50_ms": round(self._latencies.quantile(0.50) * 1e3, 3),
                "p99_ms": round(self._latencies.quantile(0.99) * 1e3, 3),
                "qps": round(self.requests / span, 1) if span > 0 else 0.0,
                "rows_per_sec": round(self.rows / span, 1) if span > 0 else 0.0,
                "batch_fill_ratio": (
                    round(self.batch_rows_real / self.batch_rows_padded, 4)
                    if self.batch_rows_padded
                    else 0.0
                ),
                "avg_batch_rows": (
                    round(self.batch_rows_real / self.batches, 2)
                    if self.batches
                    else 0.0
                ),
                "avg_requests_per_batch": (
                    round(self.batch_requests / self.batches, 2)
                    if self.batches
                    else 0.0
                ),
                "swaps": self.swaps,
                "swap_compiles": self.swap_compiles,
                "store_slab_bytes": self.store_slab_bytes,
                "store_mapped_bytes": self.store_mapped_bytes,
                "store_dtype": self.store_dtype or "",
            }

    def reset(self) -> None:
        with self._lock:
            self._latencies.reset()
            self.requests = 0
            self.rows = 0
            self.batches = 0
            self.batch_rows_real = 0
            self.batch_rows_padded = 0
            self.batch_requests = 0
            self.errors = 0
            self.swaps = 0
            self.swap_compiles = 0
            # store footprint gauges survive reset: they describe the
            # store currently serving, not traffic since the last reset
            self._first_ts = None
            self._last_ts = None

    def summary(self) -> str:
        """One-screen driver-log summary (the compile_stats.summary shape)."""
        s = self.snapshot()
        return (
            f"serve stats: {s['requests']} requests / {s['rows']} rows in "
            f"{s['batches']} batches; latency p50 {s['p50_ms']:.3f}ms / "
            f"p99 {s['p99_ms']:.3f}ms; {s['qps']:.1f} req/s "
            f"({s['rows_per_sec']:.1f} rows/s); batch fill "
            f"{s['batch_fill_ratio']:.2%} (avg {s['avg_batch_rows']} rows / "
            f"{s['avg_requests_per_batch']} requests per batch); "
            f"{s['errors']} errors; {s['swaps']} swaps "
            f"({s['swap_compiles']} swap compiles); store "
            f"{s['store_dtype'] or 'n/a'}: "
            f"{s['store_slab_bytes'] / 1e6:.2f}MB slabs on disk / "
            f"{s['store_mapped_bytes'] / 1e6:.2f}MB mapped"
        )


class FleetStats(ServeStats):
    """Router-side fleet telemetry on top of the per-server registry:
    scatter fan-out, hedges, routed retries, degraded rows (a dead owner's
    random-effect contribution replaced by the cold-entity 0), and
    fleet-swap accounting. The request/latency/QPS surface is inherited so
    the serve driver's stats command works unchanged against a router."""

    def __init__(self, max_samples: int = 100_000):
        super().__init__(max_samples)
        self.scatter_calls = 0
        self.hedges = 0
        self.reroutes = 0
        self.routed_retries = 0
        self.stale_rescores = 0
        self.degraded_rows = 0
        self.dead_replica_skips = 0

    def record_scatter(self, num_subrequests: int) -> None:
        with self._lock:
            self.scatter_calls += num_subrequests

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges += 1

    def record_reroute(self) -> None:
        with self._lock:
            self.reroutes += 1

    def record_routed_retry(self) -> None:
        with self._lock:
            self.routed_retries += 1

    def record_stale_rescore(self) -> None:
        with self._lock:
            self.stale_rescores += 1

    def record_degraded_rows(self, n: int) -> None:
        with self._lock:
            self.degraded_rows += n

    def record_dead_replica_skip(self) -> None:
        with self._lock:
            self.dead_replica_skips += 1

    def snapshot(self) -> Dict[str, float]:
        snap = super().snapshot()
        with self._lock:
            snap.update(
                {
                    "scatter_calls": self.scatter_calls,
                    "hedges": self.hedges,
                    "reroutes": self.reroutes,
                    "routed_retries": self.routed_retries,
                    "stale_rescores": self.stale_rescores,
                    "degraded_rows": self.degraded_rows,
                    "dead_replica_skips": self.dead_replica_skips,
                }
            )
        return snap

    def reset(self) -> None:
        super().reset()
        with self._lock:
            self.scatter_calls = 0
            self.hedges = 0
            self.reroutes = 0
            self.routed_retries = 0
            self.stale_rescores = 0
            self.degraded_rows = 0
            self.dead_replica_skips = 0


#: process-wide default registry (servers may carry their own instance)
serve_stats = ServeStats()
