"""Serving shard plan: which fleet replica owns which entities.

The training side already solved deterministic entity partitioning
(PR 9, :mod:`photon_ml_tpu.parallel.perhost_streaming`): hash entities
into stable buckets, cost the buckets, and bin-pack buckets onto owners
with the greedy balanced partitioner — every participant derives the
identical assignment from the same inputs with no coordination. The
serving fleet reuses EXACTLY that machinery
(:func:`~photon_ml_tpu.parallel.shuffle.stable_entity_keys` /
:func:`~photon_ml_tpu.parallel.shuffle.bucket_of` /
:func:`~photon_ml_tpu.parallel.shuffle.balanced_bucket_owners`) so:

  * the router maps a request's raw entity id -> bucket -> owner replica
    with two array lookups and ZERO model state (a thin router — it never
    opens a slab or a feature map);
  * the export side (:func:`build_fleet_stores`) filters each replica's
    store to exactly the entities the router will send it;
  * the plan is a small explicit placement object (the DrJAX framing,
    arXiv:2403.07128) that travels in ``fleet.json`` and is VALIDATED on
    swap — a new model generation must carry the identical plan, or
    routing and slab ownership would silently diverge.

Consistent hashing note: ownership is per-bucket, not per-replica-modulo,
so a future re-shard (ROADMAP "elastic entity re-sharding") moves only the
buckets whose owner changed.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.parallel.shuffle import (
    balanced_bucket_owners,
    bucket_of,
    stable_entity_keys,
)

#: default bucket count: plenty of granularity for balanced packing at
#: small fleet sizes while keeping the plan object tiny
DEFAULT_NUM_BUCKETS = 64

FLEET_META_FILE = "fleet.json"
FLEET_FORMAT = "game-serve-fleet"
FLEET_VERSION = 1
REPLICA_DIR_FMT = "replica-{r}"


@dataclasses.dataclass(frozen=True)
class ServeShardPlan:
    """bucket -> owner replica, derived deterministically from the model's
    entity population (counts per bucket) alone."""

    num_replicas: int
    num_buckets: int
    owners: np.ndarray  # (num_buckets,) int32 owner replica per bucket

    @classmethod
    def build(
        cls,
        entity_ids: List[str],
        num_replicas: int,
        num_buckets: int = DEFAULT_NUM_BUCKETS,
    ) -> "ServeShardPlan":
        """Plan from the model's entity ids (union across coordinates):
        bucket-count the population, then balanced bin-packing of buckets
        onto replicas — identical on every builder for identical inputs."""
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        if num_buckets < num_replicas:
            raise ValueError(
                f"num_buckets ({num_buckets}) must be >= num_replicas "
                f"({num_replicas})"
            )
        counts = np.zeros(num_buckets, np.int64)
        if entity_ids:
            buckets = bucket_of(stable_entity_keys(entity_ids), num_buckets)
            counts += np.bincount(buckets, minlength=num_buckets)
        owners = balanced_bucket_owners(counts, num_replicas)
        return cls(
            num_replicas=num_replicas,
            num_buckets=num_buckets,
            owners=owners.astype(np.int32),
        )

    # -- routing -------------------------------------------------------------
    def bucket_of_raw(self, raw_id: str) -> int:
        return int(bucket_of(stable_entity_keys([str(raw_id)]), self.num_buckets)[0])

    def owner_of(self, raw_id: Optional[str]) -> int:
        """Owner replica of an entity id; -1 for a row with no id (its
        random-effect contribution is 0 wherever it is computed)."""
        if raw_id is None:
            return -1
        return int(self.owners[self.bucket_of_raw(raw_id)])

    def owners_of(self, raw_ids: List[Optional[str]]) -> np.ndarray:
        """(n,) int32 owner per raw id (-1 for None) — the vectorized form
        the router uses per request batch."""
        out = np.full(len(raw_ids), -1, np.int32)
        present = [i for i, r in enumerate(raw_ids) if r is not None]
        if present:
            ids = [str(raw_ids[i]) for i in present]
            owned = self.owners[bucket_of(stable_entity_keys(ids), self.num_buckets)]
            out[np.asarray(present)] = owned
        return out

    # -- (de)serialization ---------------------------------------------------
    def to_json(self) -> dict:
        return {
            "num_replicas": self.num_replicas,
            "num_buckets": self.num_buckets,
            "owners": [int(o) for o in self.owners],
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ServeShardPlan":
        owners = np.asarray(obj["owners"], np.int32)
        if len(owners) != int(obj["num_buckets"]):
            raise ValueError(
                f"plan owners length {len(owners)} != num_buckets "
                f"{obj['num_buckets']}"
            )
        return cls(
            num_replicas=int(obj["num_replicas"]),
            num_buckets=int(obj["num_buckets"]),
            owners=owners,
        )

    def same_assignment(self, other: "ServeShardPlan") -> bool:
        """True when routing under ``self`` and ``other`` is identical —
        the fleet-swap compatibility requirement (a plan change means slabs
        moved; that is a re-shard, not a swap)."""
        return (
            self.num_replicas == other.num_replicas
            and self.num_buckets == other.num_buckets
            and bool(np.array_equal(self.owners, other.owners))
        )


def replica_store_dir(fleet_dir: str, replica: int) -> str:
    return os.path.join(fleet_dir, REPLICA_DIR_FMT.format(r=replica))


def build_fleet_stores(
    model_dir: str,
    fleet_dir: str,
    num_replicas: int,
    num_buckets: int = DEFAULT_NUM_BUCKETS,
    bucketer=None,
    num_partitions: int = 1,
    force_python: bool = False,
    store_dtype: str = "f32",
) -> dict:
    """Export one saved GAME model into ``num_replicas`` sharded serving
    stores plus a ``fleet.json`` plan.

    Replica r's store (``<fleet_dir>/replica-r/``) carries the FULL feature
    index and fixed-effect vectors (replicated — any replica can compute a
    fixed contribution) and only the random-effect slab rows of the
    entities the plan assigns to r. The union of the replica slabs is
    exactly the single-store export, partitioned disjointly.

    ``store_dtype`` applies to EVERY replica store (the one dial for the
    whole fleet, recorded in ``fleet.json``): a mixed-dtype fleet would
    give requests different error characteristics depending on which
    replica owns their entity, so :func:`load_fleet_meta` refuses one
    loudly.
    """
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import model_io
    from photon_ml_tpu.serve.model_store import build_model_store

    # entity population (union across RE coordinates) for bucket costing
    layout = model_io.list_game_model(model_dir)
    entity_ids: List[str] = []
    for name in layout[model_io.RANDOM_EFFECT]:
        for rec in avro_io.read_directory(
            os.path.join(
                model_dir, model_io.RANDOM_EFFECT, name, model_io.COEFFICIENTS
            )
        ):
            entity_ids.append(str(rec["modelId"]))
    all_ids = sorted(set(entity_ids))
    plan = ServeShardPlan.build(all_ids, num_replicas, num_buckets)
    # ONE vectorized ownership pass; the per-replica filter is then a set
    # probe per record, not a per-record hash round-trip x num_replicas
    owners = plan.owners_of(all_ids)
    owned_ids = [
        frozenset(i for i, o in zip(all_ids, owners) if o == r)
        for r in range(num_replicas)
    ]

    os.makedirs(fleet_dir, exist_ok=True)
    replica_meta: List[dict] = []
    # fleet-wide pinned quantization budget per coordinate: the MAX of the
    # replica slabs' realized/budget errors (a request's entity lives on
    # exactly one replica, so the worst replica bounds every score)
    fleet_quant: Dict[str, dict] = {}
    for r in range(num_replicas):
        meta = build_model_store(
            model_dir,
            replica_store_dir(fleet_dir, r),
            num_partitions=num_partitions,
            bucketer=bucketer,
            force_python=force_python,
            entity_filter=owned_ids[r].__contains__,
            store_dtype=store_dtype,
        )
        for e in meta["random"]:
            q = e.get("quantization") or {}
            agg = fleet_quant.setdefault(
                e["name"],
                {"realized_max_abs_coeff_err": 0.0, "coeff_err_budget": 0.0},
            )
            for k in agg:
                agg[k] = max(agg[k], float(q.get(k) or 0.0))
        replica_meta.append(
            {
                "replica": r,
                "store_dir": os.path.abspath(replica_store_dir(fleet_dir, r)),
                "entities": {e["name"]: e["entities"] for e in meta["random"]},
            }
        )
    # coordinate order comes from the LAST store meta — every replica store
    # lists the same coordinates in the same order (same source model)
    fleet_meta = {
        "format": FLEET_FORMAT,
        "version": FLEET_VERSION,
        "source_model_dir": os.path.abspath(model_dir),
        "store_dtype": store_dtype,
        "task": meta["task"],
        "plan": plan.to_json(),
        "fixed": meta["fixed"],
        "random": [
            {
                "name": e["name"],
                "re_id": e["re_id"],
                "shard": e["shard"],
                "quantization": fleet_quant[e["name"]],
            }
            for e in meta["random"]
        ],
        "replicas": replica_meta,
    }
    tmp = os.path.join(fleet_dir, FLEET_META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(fleet_meta, f, indent=1)
    os.replace(tmp, os.path.join(fleet_dir, FLEET_META_FILE))
    return fleet_meta


def is_fleet_dir(path: str) -> bool:
    try:
        with open(os.path.join(path, FLEET_META_FILE)) as f:
            return json.load(f).get("format") == FLEET_FORMAT
    except (OSError, ValueError):
        return False


def load_fleet_meta(fleet_dir: str) -> dict:
    """Read + validate ``fleet.json``. A mixed-dtype fleet (replica store
    metas disagreeing with the fleet's ``store_dtype``) is refused HERE,
    loudly — per-request error characteristics must not depend on which
    replica owns the entity. Replica stores whose meta is unreadable from
    this host are skipped (the replica process re-validates its own store
    against this value at startup)."""
    with open(os.path.join(fleet_dir, FLEET_META_FILE)) as f:
        meta = json.load(f)
    if meta.get("format") != FLEET_FORMAT:
        raise IOError(f"{fleet_dir} is not a {FLEET_FORMAT} directory")
    fleet_dtype = meta.get("store_dtype") or "f32"
    mixed = []
    for rep in meta.get("replicas") or []:
        try:
            with open(os.path.join(rep["store_dir"], "meta.json")) as rf:
                rep_dtype = json.load(rf).get("store_dtype") or "f32"
        except (OSError, ValueError, KeyError):
            continue  # remote/missing replica store: its process validates
        if rep_dtype != fleet_dtype:
            mixed.append(f"replica {rep.get('replica')}: {rep_dtype}")
    if mixed:
        raise IOError(
            f"{fleet_dir} is a MIXED-DTYPE fleet (fleet.json says "
            f"{fleet_dtype!r} but {'; '.join(mixed)}); refusing to route — "
            "re-export the whole fleet at one store_dtype"
        )
    return meta
